#!/usr/bin/env python
"""One process, the whole product: trainer -> gate -> fleet, always learning.

Runs the supervised continuous-learning loop (``pipeline/``,
docs/pipeline.md) end to end: a Trainer streams checkpoints into
``logs/{name}/``, every candidate is judged by the PromotionGate (the
compiled robustness matrix + clean-return regression vs the served
baseline — ONE jitted eval program across all candidates, budget-1
RetraceGuard receipt), passing candidates are published to
``logs/{name}/promoted/`` and hot-swapped into a multi-replica serving
fleet at the batch barrier (globally step-monotonic ``model_step``),
and an optional RollbackMonitor demotes to last-good on a served-metric
regression. Verdicts land in ``logs/{name}/promotions.jsonl``.

Usage (same key=value CLI as every entry point; trainer keys ride
through to ``train.build_trainer``):

    python scripts/always_learning.py name=always num_formation=64 \\
        total_timesteps=64000 max_steps=100 pipeline_replicas=2

    # what bench.py phase 7 measures (forced 2-device CPU, tiny run):
    JAX_PLATFORMS=cpu python scripts/always_learning.py name=bench_pipeline \\
        num_formation=16 total_timesteps=4800 max_steps=60 \\
        gate_formations=32 pipeline_replicas=2

Prints exactly one JSON line: promotions / rejections / rollbacks,
``promotion_latency_s_p50``/``p95`` (train-step -> served model_step
wall time), ``gate_eval_steps_per_sec``, the compile-once receipts, and
the final served step.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from marl_distributedformation_tpu.utils import (  # noqa: E402
    env_params_from_config,
    load_config,
    setup_platform,
    validate_override_keys,
)

PIPELINE_KEYS = (
    # gate
    "gate_scenarios",
    "gate_severities",
    "gate_formations",
    "gate_seed",
    "gate_clean_tolerance",
    "gate_rung_tolerance",
    # adversarial rung + auto-curriculum feedback (docs/adversarial.md)
    "gate_adversarial",
    "gate_adversarial_scenarios",
    "gate_adversarial_min_severity",
    "gate_adversarial_drop_tolerance",
    "gate_adversarial_max_severity",
    "gate_adversarial_grid",
    "gate_adversarial_generations",
    "gate_adversarial_formations",
    "feedback_rollouts",
    # gate-eval deadline (chaos hardening, docs/chaos.md)
    "gate_timeout_s",
    # self-healing supervision (chaos/watchdog.py, docs/chaos.md)
    "watchdog",
    "watchdog_wedge_timeout_s",
    "watchdog_backoff_s",
    "watchdog_backoff_cap_s",
    # chaos plane (chaos/, docs/chaos.md): arm a seeded fault campaign
    # against THIS live run — dev/staging resilience drills.
    "chaos",
    "chaos_seed",
    "chaos_faults",
    # fleet
    "pipeline_replicas",
    "pipeline_buckets",
    "pipeline_port",
    "pipeline_poll_s",
    "pipeline_budget_s",
    "pipeline_verify_requests",
    # mesh tier (serving/mesh/, docs/mesh.md): serve through a loopback
    # multi-host mesh — host subprocesses behind the MetaRouter, the
    # MeshCoordinator driving every promotion as a global barrier commit.
    "mesh_serve",
    "mesh_hosts",
    "mesh_heartbeat_s",
    "mesh_lease_s",
    "mesh_dead_after_s",
    "mesh_prepare_timeout_s",
    "mesh_port",
    # rollback
    "rollback_metric",
    "rollback_threshold",
    "rollback_ratio",
    "rollback_direction",
    "rollback_trip_after",
    "rollback_baseline_samples",
    # observability spine (obs/, docs/observability.md)
    "obs_trace",
    "obs_ring_size",
    "obs_flightrec",
    # live-metrics plane (obs/metrics.py, docs/observability.md)
    "telemetry",
    "telemetry_port",
    "telemetry_reservoir",
    # program ledger (obs/ledger.py, docs/observability.md)
    "ledger",
    "ledger_reservoir",
    # perf-regression sentinel (obs/sentinel.py)
    "sentinel",
    "sentinel_tolerance",
    "sentinel_trip_after",
    "sentinel_bench",
    "out",
)
# Trainer knobs are the normal YAML config surface (train.py is
# struct-less); this entry point validates only because a mistyped
# pipeline key would otherwise silently run the defaults.
TRAIN_EXTRA_KEYS = (
    "save_freq", "policy", "hidden_sizes", "mesh", "num_seeds",
    "curriculum", "learning_rates", "platform", "preset", "fused_chunk",
    "iters_per_dispatch", "guard_retraces", "guard_transfers",
    "guard_nans", "profile", "profile_iterations",
    # sebulba lane (train/sebulba/, docs/sebulba.md): the split
    # acting/learning architecture; the gate then runs on its OWN
    # device slice instead of time-sharing the trainer's.
    "architecture", "actor_devices", "transfer_queue_depth",
    "max_param_staleness",
)


def _gate_config(cfg):
    from marl_distributedformation_tpu.pipeline import GateConfig

    scenarios = cfg.get("gate_scenarios") or ["wind", "sensor_noise"]
    if not isinstance(scenarios, list):
        scenarios = [scenarios]
    severities = cfg.get("gate_severities") or [0.5, 1.0]
    if not isinstance(severities, list):
        severities = [severities]
    adv_scenarios = cfg.get("gate_adversarial_scenarios") or []
    if not isinstance(adv_scenarios, list):
        adv_scenarios = [adv_scenarios]
    return GateConfig(
        scenarios=tuple(str(s) for s in scenarios),
        severities=tuple(float(s) for s in severities),
        eval_formations=int(cfg.get("gate_formations", 64)),
        eval_seed=int(cfg.get("gate_seed", 1234)),
        clean_tolerance=float(cfg.get("gate_clean_tolerance", 0.05)),
        rung_tolerance=float(cfg.get("gate_rung_tolerance", 0.10)),
        adversarial=bool(cfg.get("gate_adversarial", False)),
        adversarial_scenarios=tuple(str(s) for s in adv_scenarios),
        adversarial_min_severity=float(
            cfg.get("gate_adversarial_min_severity", 0.5)
        ),
        adversarial_drop_tolerance=float(
            cfg.get("gate_adversarial_drop_tolerance", 0.2)
        ),
        adversarial_max_severity=float(
            cfg.get("gate_adversarial_max_severity", 1.5)
        ),
        adversarial_grid=int(cfg.get("gate_adversarial_grid", 4)),
        adversarial_generations=int(
            cfg.get("gate_adversarial_generations", 3)
        ),
        adversarial_formations=int(
            cfg.get("gate_adversarial_formations", 64)
        ),
        # The eval deadline: size past the cold compile (the FIRST eval
        # includes it) or leave None; a wedged candidate then yields a
        # ``gate_timeout`` verdict instead of stalling the loop.
        gate_timeout_s=(
            float(cfg["gate_timeout_s"])
            if cfg.get("gate_timeout_s") is not None
            else None
        ),
    )


def _monitor(cfg, router):
    metric = cfg.get("rollback_metric")
    if not metric:
        return None
    from marl_distributedformation_tpu.obs import get_registry
    from marl_distributedformation_tpu.pipeline import RollbackMonitor

    direction = str(cfg.get("rollback_direction") or "above")
    # Mesh mode: the fleet families live in the HOST subprocesses and
    # reach this process only as gossip (MeshHost.metrics). A
    # fleet-snapshot metric name is resolved as the WORST value across
    # routable hosts — max for an "above"-breaching metric (latency,
    # queue depth), min for "below" (served return) — so the tripwire
    # fires when ANY host regresses, never silently reads None.
    coordinator = getattr(router, "coordinator", None)

    def sample():
        # One sampling code path fleet-wide (obs/metrics.py): the
        # router snapshot refreshes the fleet gauges in the process
        # registry (FleetMetrics.snapshot publishes as a side effect),
        # then the monitor reads the MERGED registry namespace — the
        # same numbers GET /metrics serves, and any trainer/pipeline
        # gauge is now watchable too, not just fleet keys. The fresh
        # fleet snapshot overlays the registry copy so the monitored
        # metric can never be a stale gauge; with telemetry disabled
        # the registry is empty and the monitor falls back to exactly
        # the fleet snapshot — the telemetry off-switch must never
        # blind the rollback tripwire.
        snap = router.snapshot()
        merged = get_registry().snapshot()
        merged.update(snap)
        if coordinator is not None and metric not in merged:
            values = []
            for h in coordinator.routable_hosts():
                v = (h.metrics or {}).get(metric)
                if isinstance(v, (int, float)):
                    values.append(float(v))
            if values:
                merged[metric] = (
                    max(values) if direction == "above" else min(values)
                )
        return merged

    return RollbackMonitor(
        sample,
        metric=str(metric),
        threshold=cfg.get("rollback_threshold"),
        ratio=cfg.get("rollback_ratio"),
        direction=str(cfg.get("rollback_direction") or "above"),
        baseline_samples=int(cfg.get("rollback_baseline_samples", 3)),
        trip_after=int(cfg.get("rollback_trip_after", 2)),
    )


def main(argv=None) -> dict:
    overrides = sys.argv[1:] if argv is None else argv
    validate_override_keys(
        overrides, extra_keys=PIPELINE_KEYS + TRAIN_EXTRA_KEYS
    )
    cfg = load_config(overrides)
    setup_platform(cfg.get("platform"))

    replicas = int(cfg.get("pipeline_replicas", 2))
    sebulba = str(cfg.get("architecture") or "anakin") == "sebulba"
    actor_devices = int(cfg.get("actor_devices", 1))
    # Sebulba wants real slices: actor_devices acting + 1 learning + 1
    # for the gate's own assignment (docs/sebulba.md). Anakin only needs
    # a device per serving replica.
    want_devices = max(replicas, actor_devices + 2) if sebulba else replicas
    import jax

    if (
        jax.default_backend() == "cpu"
        and len(jax.local_devices()) < want_devices
    ):
        # The forced multi-device CPU mesh (the dev/bench shape): widen
        # the device pool so each serving replica gets a real device.
        from serve_policy import _ensure_cpu_devices

        _ensure_cpu_devices(want_devices)

    import train as train_entry
    from marl_distributedformation_tpu.pipeline import (
        AlwaysLearningPipeline,
    )
    from marl_distributedformation_tpu.train import Trainer

    env_params = env_params_from_config(cfg)
    if bool(cfg.get("gate_adversarial", False)) and not cfg.get("scenarios"):
        # The adversarial rung feeds rejected candidates' falsifiers back
        # into the trainer's schedule — that needs the traced scenario
        # seam compiled into the train step. Reserve it with the identity
        # scenario; the feedback stages replace it live.
        cfg["scenarios"] = ["clean"]
    trainer = train_entry.build_trainer(cfg)
    if not isinstance(trainer, Trainer):
        raise SystemExit(
            "the always-learning pipeline drives the single-run Trainer; "
            "population sweeps / curriculum trainers checkpoint a "
            "different layout (drop num_seeds / curriculum)"
        )

    # Observability spine (obs/): the tracer records promotion spans +
    # serving batch spans into per-thread rings, and the flight recorder
    # snapshots them next to the checkpoints on incidents (circuit
    # break, rollback trip, wedged barrier). Knobs in cfg/config.yaml.
    from marl_distributedformation_tpu import obs as obs_spine

    obs_enabled = bool(cfg.get("obs_trace", True))
    obs_spine.configure(
        enabled=obs_enabled,
        ring_size=int(cfg.get("obs_ring_size", 4096)),
        flightrec_dir=(
            str(trainer.log_dir)
            if cfg.get("obs_flightrec", True)
            else ""
        ),
    )
    # Live-metrics plane (obs/metrics.py): the trainer's dispatch loop,
    # the gate, and the fleet all record into the process registry;
    # telemetry_port serves the merged namespace as Prometheus text
    # (GET /metrics) so a pipeline run exports everything ROADMAP item
    # 3's autoscaler needs without a fleet frontend.
    obs_spine.configure_metrics(
        enabled=bool(cfg.get("telemetry", True)),
        reservoir=int(cfg.get("telemetry_reservoir", 512)),
    )
    # Program ledger (obs/ledger.py): every compile site in the loop —
    # trainer dispatch, gate MatrixProgram, adversary rung, serving
    # rungs — registers its executable automatically at the
    # RetraceGuard seam; the census lands beside promotions.jsonl at
    # exit and the report carries entry-count == receipt-count.
    obs_spine.configure_ledger(
        enabled=bool(cfg.get("ledger", True)),
        reservoir=int(cfg.get("ledger_reservoir", 256)),
    )
    telemetry = None
    telemetry_port = cfg.get("telemetry_port")
    if telemetry_port is not None:
        telemetry = obs_spine.TelemetryServer(
            port=int(telemetry_port)
        ).start()
        report_telemetry_url = telemetry.url
        print(f"[always] telemetry: {telemetry.url}", file=sys.stderr)
    else:
        report_telemetry_url = None

    # Perf-regression sentinel (obs/sentinel.py): live gauges vs the
    # newest committed BENCH record; a sustained regression dumps a
    # flightrec-perf_regression-*.json and an audit line beside the
    # checkpoints.
    sentinel = None
    if bool(cfg.get("sentinel", False)):
        if not bool(cfg.get("telemetry", True)):
            # The sentinel compares LIVE registry gauges; with the
            # registry disabled every snapshot is empty and the
            # tripwire is silently blind — refuse loudly instead.
            raise SystemExit(
                "sentinel=true needs telemetry=true (the sentinel "
                "watches the live MetricsRegistry gauges; a disabled "
                "registry records nothing, so no regression could "
                "ever trip)"
            )
        sentinel_tol = float(cfg.get("sentinel_tolerance", 0.5))
        sentinel = obs_spine.RegressionSentinel(
            obs_spine.default_watches(tolerance=sentinel_tol)
            # Ledger aggregates guard against compile-time / memory-
            # footprint regressions vs the committed record; an older
            # record without the fields reports as sentinel_missing,
            # never a breach.
            + obs_spine.ledger_watches(tolerance=sentinel_tol)
            # Recovery-MTTR guard (train/recovery.py): live rollback
            # restore wall vs the committed drill; wide band (recovery
            # is rare, samples are few). Missing field = unmeasurable,
            # never a breach.
            + obs_spine.recovery_watches(),
            record_path=cfg.get("sentinel_bench"),
            trip_after=int(cfg.get("sentinel_trip_after", 3)),
            audit_dir=trainer.log_dir,
        )

    budget_s = float(cfg.get("pipeline_budget_s", 600.0))
    deadline = time.time() + budget_s
    gate_device = None
    if sebulba:
        # The gate's own slice under the sebulba partition — candidate
        # evals stop contending with the learner's update stream, and
        # the promotion span breakdown records which device served.
        from marl_distributedformation_tpu.train import assign_gate_device

        gate_device = assign_gate_device(actor_devices)
        print(
            f"[always] sebulba: actor slice {trainer.actor_slice}, "
            f"learner slice {trainer.learner_slice}, gate on "
            f"{gate_device}",
            file=sys.stderr,
        )
    pipeline = AlwaysLearningPipeline(
        trainer.log_dir,
        env_params,
        gate_config=_gate_config(cfg),
        poll_interval_s=float(cfg.get("pipeline_poll_s", 0.25)),
        feedback_rollouts=int(cfg.get("feedback_rollouts", 50)),
        gate_device=gate_device,
    )
    pipeline.attach_trainer(trainer)

    train_error: list = []

    def run_training() -> None:
        try:
            trainer.train()
        except BaseException as e:  # noqa: BLE001 — surfaced in the report
            train_error.append(repr(e))

    train_thread = threading.Thread(
        target=run_training, name="always-learning-trainer", daemon=True
    )
    print(
        f"[always] {cfg.name}: training M={cfg.num_formation} to "
        f"{trainer.total_timesteps} agent-transitions; gate "
        f"{pipeline.gate.config.scenarios} x "
        f"{pipeline.gate.config.severities}; fleet {replicas} replicas",
        file=sys.stderr,
    )
    train_thread.start()

    report: dict = {"name": str(cfg.name)}
    router = None
    frontend = None
    watchdog = None
    mesh = None
    try:
        if not pipeline.wait_first_promotion(
            timeout_s=max(deadline - time.time(), 1.0)
        ):
            raise SystemExit(
                "no candidate passed the gate within pipeline_budget_s "
                f"({budget_s:g}s) — see logs/{cfg.name}/promotions.jsonl"
            )

        buckets = cfg.get("pipeline_buckets") or [1, 8]
        mesh_serve = bool(cfg.get("mesh_serve", False))
        mesh = None
        if mesh_serve:
            # The cross-host shape (serving/mesh/, docs/mesh.md): host
            # SUBPROCESSES serve the promoted directory behind the
            # MetaRouter; the MeshCoordinator drives every promotion
            # as a coordinator-barriered global commit, and the
            # supervisor is none the wiser (duck-typed attach_fleet).
            from marl_distributedformation_tpu.serving.mesh import (
                spawn_local_mesh,
            )

            mesh_port = cfg.get("mesh_port")
            mesh = spawn_local_mesh(
                pipeline.promoted_dir,
                hosts=int(cfg.get("mesh_hosts", 2)),
                replicas_per_host=replicas,
                buckets=tuple(int(b) for b in buckets),
                num_agents=env_params.num_agents,
                heartbeat_s=float(cfg.get("mesh_heartbeat_s", 0.25)),
                lease_s=float(cfg.get("mesh_lease_s", 1.0)),
                dead_after_s=float(cfg.get("mesh_dead_after_s", 1.0)),
                prepare_timeout_s=float(
                    cfg.get("mesh_prepare_timeout_s", 30.0)
                ),
                frontend_port=(
                    int(mesh_port) if mesh_port is not None else None
                ),
                ready_timeout_s=max(deadline - time.time(), 30.0),
            )
            router, coordinator = mesh.router, mesh.coordinator
            if mesh.frontend is not None:
                report["frontend_url"] = mesh.frontend.url
                print(
                    f"[always] mesh frontend: {mesh.frontend.url}",
                    file=sys.stderr,
                )
            print(
                f"[always] mesh: {len(mesh.hosts)} host subprocesses, "
                f"coordinator {coordinator.url}",
                file=sys.stderr,
            )
        else:
            from marl_distributedformation_tpu.serving.fleet import (
                fleet_from_checkpoint_dir,
                warmup_fleet,
            )

            router, coordinator = fleet_from_checkpoint_dir(
                pipeline.promoted_dir,
                env_params=env_params,
                act_dim=env_params.act_dim,
                num_replicas=replicas,
                buckets=tuple(int(b) for b in buckets),
            )
            router.start()
            warmup_fleet(router, (env_params.obs_dim,))
            port = cfg.get("pipeline_port")
            if port is not None:
                from marl_distributedformation_tpu.serving.fleet import (
                    FleetFrontend,
                )

                frontend = FleetFrontend(router, port=int(port)).start()
                report["frontend_url"] = frontend.url
                print(
                    f"[always] frontend: {frontend.url}", file=sys.stderr
                )
        pipeline.attach_fleet(router, coordinator)
        monitor = _monitor(cfg, router)
        if monitor is not None:
            pipeline.attach_monitor(monitor)

        # Self-healing supervision (chaos/watchdog.py): the watchdog
        # restarts a crashed replica worker and the router's half-open
        # probe readmits it — the fleet regrows to full width instead
        # of bleeding replicas. (The pipeline lane here IS this main
        # thread, so only the fleet lanes are watchdogged; the
        # background-loop mode — pipeline.run() — also gets the
        # pipeline lane via watchdog.watch_pipeline.)
        if bool(cfg.get("watchdog", True)) and not mesh_serve:
            # Mesh mode has no in-process fleet lanes to watch — each
            # host subprocess supervises its own schedulers, and host
            # DEATH is the coordinator's lease taxonomy's job.
            from marl_distributedformation_tpu.chaos import LaneWatchdog

            watchdog = LaneWatchdog(
                wedge_timeout_s=float(
                    cfg.get("watchdog_wedge_timeout_s", 30.0)
                ),
                backoff_base_s=float(cfg.get("watchdog_backoff_s", 0.5)),
                backoff_cap_s=float(
                    cfg.get("watchdog_backoff_cap_s", 30.0)
                ),
            )
            watchdog.watch_fleet(router)
            if sebulba:
                # Both training lanes under the same supervision: a dead
                # actor thread restarts, a wedged learner is surfaced.
                trainer.attach_watchdog(watchdog)
            watchdog.start()

        # Chaos drill (chaos/, docs/chaos.md): arm a seeded fault
        # campaign against THIS live run. The schedule is a pure
        # function of chaos_seed, so a drill that trips an invariant
        # replays bit-identically (scripts/chaos_storm.py is the
        # self-contained harness; this knob storms the real run).
        if bool(cfg.get("chaos", False)):
            from marl_distributedformation_tpu.chaos import (
                FaultSchedule,
                get_fault_plane,
            )

            plane = get_fault_plane()
            plane.arm(
                FaultSchedule.from_seed(
                    int(cfg.get("chaos_seed", 0)),
                    faults=int(cfg.get("chaos_faults", 25)),
                )
            )
            plane.enabled = True
            print(
                f"[always] chaos armed: {plane.pending()} faults, "
                f"seed {int(cfg.get('chaos_seed', 0))}",
                file=sys.stderr,
            )

        # Supervision loop: drain candidates while the trainer runs,
        # then drain the tail after it finishes. The loop heartbeats so
        # `pipeline_loop_heartbeat_age_s` is scrapeable liveness.
        while time.time() < deadline:
            pipeline.heartbeat.beat()
            processed = pipeline.poll_once()
            if sentinel is not None:
                # Refresh the fleet families first (FleetMetrics
                # publishes on every snapshot read) so the latency
                # watch sees live numbers even when no monitor or
                # external scraper is driving reads.
                router.snapshot()
                sentinel.check()
            if not train_thread.is_alive() and processed == 0:
                # The trainer may have written its final checkpoint
                # between our poll and the liveness check (train()
                # returning guarantees the async writer drained) — one
                # post-death drain closes the race.
                if pipeline.poll_once() == 0:
                    break
                continue
            if processed == 0:
                time.sleep(0.05)
        train_thread.join(timeout=max(deadline - time.time(), 0.0))

        # Verification traffic: the served step must be the promoted one.
        import numpy as np

        n_verify = int(cfg.get("pipeline_verify_requests", 4))
        served_steps = []
        rng = np.random.default_rng(0)
        for _ in range(n_verify):
            obs = rng.standard_normal(
                (2, env_params.obs_dim), dtype=np.float32
            )
            res = router.submit(obs).result(timeout=30.0)
            served_steps.append(int(res.model_step))

        report.update(pipeline.summary())
        if sentinel is not None:
            report.update(sentinel.summary())
        if report_telemetry_url is not None:
            report["telemetry_url"] = report_telemetry_url
        report["pipeline_replicas"] = replicas
        if sebulba:
            # The transfer-plane health counters next to the promotion
            # stats: one JSON line answers "did the split lanes keep up".
            report["architecture"] = "sebulba"
            report["transfer_queue_occupancy_p95"] = round(
                trainer.occupancy_p95(), 2
            )
            report["param_staleness_p95_updates"] = round(
                trainer.staleness_p95(), 2
            )
            report["sebulba_stale_dropped"] = trainer.stale_dropped
            report["sebulba_actor_compiles"] = trainer.actor_guard.count
            report["sebulba_learner_compiles"] = trainer.learner_guard.count
        report["fleet_swap_count"] = coordinator.swap_count
        if watchdog is not None:
            report["lane_restarts"] = watchdog.restarts_total()
        from marl_distributedformation_tpu.chaos import get_fault_plane
        from marl_distributedformation_tpu.obs import get_registry

        if get_fault_plane().fired:
            report["chaos_faults_fired"] = len(
                get_fault_plane().fired_record()
            )
        live = get_registry().snapshot()
        for key in (
            "checkpoint_writes_skipped_total",
            "checkpoint_quarantined_total",
            "checkpoint_nonfinite_skipped_total",
            "checkpoint_pruned_total",
            "pipeline_gate_timeouts_total",
        ):
            if live.get(key):
                report[key] = int(live[key])
        # Self-healing train lane (train/recovery.py): surface the
        # ladder's history in the run report — a supervised loop whose
        # trainer quietly rolled back should SAY so.
        if trainer.recovery_ladder is not None:
            ladder = trainer.recovery_ladder
            report["train_recoveries"] = ladder.recoveries
            report["train_divergence_events"] = ladder.breaches
            report["train_skipped_updates"] = ladder.skipped_total
            report["train_halted"] = bool(trainer.halted)
        report["verified_served_steps"] = served_steps
        report["train_alive"] = train_thread.is_alive()
        if train_error:
            report["train_error"] = train_error[0][:300]
        if mesh_serve:
            # Per-host receipts scraped over HTTP (the compiled
            # programs live in the host subprocesses); the ledger
            # receipt equality below only covers THIS process.
            receipt_sets = router.host_compile_counts()
            report["mesh_hosts"] = len(mesh.hosts)
            report["mesh_commit_rounds"] = coordinator.commit_round
            report["mesh_host_states"] = {
                h["host_id"]: h["state"] for h in coordinator.hosts()
            }
            compile_receipts = {}
        else:
            compile_receipts = router.compile_counts()
            receipt_sets = compile_receipts
        report["serving_max_compiles_per_rung"] = max(
            (c for per in receipt_sets.values() for c in per.values()),
            default=0,
        )
        # Program ledger: every budget-1 compile site appears in the
        # census exactly once per compile — entry count must equal the
        # sum of the RetraceGuard receipts across the loop's programs
        # (trainer dispatch + scenario samplers + gate eval + adversary
        # rung + serving rungs). A mismatch means a compile escaped
        # attribution; the report carries both sides so the e2e can pin
        # the equality.
        ledger = obs_spine.get_ledger()
        if ledger.enabled:
            receipts = trainer.retrace_guard.count
            sampler_guard = getattr(trainer, "_sampler_guard", None)
            if sampler_guard is not None:
                receipts += sampler_guard.count
            if sebulba:
                # The slice programs carry their own budget-1 guards
                # (the Anakin guard above stays 0 — never dispatched).
                receipts += trainer.actor_guard.count
                receipts += trainer.learner_guard.count
            receipts += pipeline.gate.program.guard.count
            if pipeline.gate.adversary is not None:
                receipts += pipeline.gate.adversary.guard.count
            receipts += sum(
                c
                for per in compile_receipts.values()
                for c in per.values()
            )
            report["ledger_programs"] = len(ledger.entries())
            report["ledger_receipts"] = receipts
            report["ledger_compile_seconds_total"] = round(
                ledger.compile_seconds_total(), 3
            )
            try:
                report["ledger_census"] = str(
                    ledger.write_census(
                        Path(trainer.log_dir) / "program_ledger.json"
                    )
                )
            except OSError:
                pass
    finally:
        from marl_distributedformation_tpu.chaos import get_fault_plane

        get_fault_plane().enabled = False
        if watchdog is not None:
            watchdog.stop()
        if telemetry is not None:
            telemetry.stop()
        if frontend is not None:
            frontend.stop()
        if mesh is not None:
            mesh.stop()  # hosts + coordinator + mesh frontend
        elif router is not None:
            router.stop()
        pipeline.stop()

    if obs_enabled:
        # Leave the whole run's spans beside promotions.jsonl —
        # scripts/trace_report.py renders them Perfetto-loadable.
        try:
            report["trace_dump"] = str(
                obs_spine.get_tracer().dump(
                    Path(trainer.log_dir) / "trace_spans.json"
                )
            )
        except OSError:
            pass

    out = cfg.get("out")
    if out:
        Path(out).parent.mkdir(parents=True, exist_ok=True)
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
