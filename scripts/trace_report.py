#!/usr/bin/env python
"""Render a run's obs spans into a Perfetto-loadable Chrome trace file.

Input is any span dump the obs spine writes:

- ``Tracer.dump()`` output (``trace_spans.json`` — what
  ``scripts/always_learning.py`` leaves beside ``promotions.jsonl``),
- a flight-recorder snapshot (``flightrec-*.json``),
- or a bare JSON list of snapshot records.

Output is Chrome trace-event JSON (``--out``, default
``<input>.chrome.json``): one lane per recording thread, spans as
complete events, instants for events, trace IDs in ``args`` so
Perfetto's search finds every leg of one promotion or request by its
ID. Load it at https://ui.perfetto.dev or ``chrome://tracing`` — and
because timestamps are epoch microseconds it merges cleanly alongside
``TraceWindow``'s XLA captures from the same run.

    python scripts/trace_report.py logs/always/trace_spans.json
    python scripts/trace_report.py logs/always/flightrec-rollback_trip-0001.json \\
        --out /tmp/rollback.chrome.json

``--trace-id`` filters to one trace's records (plus unlabelled spans
with ``--keep-unlabelled``), which is how you pull a single promotion's
lane out of a long run.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from marl_distributedformation_tpu.obs import chrome_trace  # noqa: E402


def load_records(path: Path) -> list:
    """Snapshot records from any of the obs dump shapes."""
    payload = json.loads(path.read_text())
    if isinstance(payload, list):
        return payload
    if isinstance(payload, dict) and isinstance(
        payload.get("records"), list
    ):
        return payload["records"]
    raise SystemExit(
        f"{path} is not an obs span dump (expected a Tracer.dump / "
        "flightrec JSON with a 'records' list, or a bare record list)"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("input", type=Path, help="span dump to render")
    ap.add_argument(
        "--out",
        type=Path,
        help="output Chrome trace path (default <input>.chrome.json)",
    )
    ap.add_argument(
        "--trace-id",
        help="keep only records labelled with this trace ID",
    )
    ap.add_argument(
        "--keep-unlabelled",
        action="store_true",
        help="with --trace-id: also keep records carrying no trace ID",
    )
    args = ap.parse_args(argv)

    records = load_records(args.input)
    total = len(records)
    if args.trace_id:
        records = [
            r
            for r in records
            if r.get("trace_id") == args.trace_id
            or (args.keep_unlabelled and not r.get("trace_id"))
        ]
    out = args.out or args.input.with_suffix(".chrome.json")
    trace = chrome_trace(records, process_name=args.input.stem)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(trace))
    lanes = {
        e["tid"] for e in trace["traceEvents"] if e.get("ph") == "X"
    }
    print(
        f"[trace_report] {len(records)}/{total} records -> {out} "
        f"({len(lanes)} lane(s)); load at https://ui.perfetto.dev",
        file=sys.stderr,
    )
    print(str(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
