#!/usr/bin/env python
"""Estimate the reference's FULL SB3-PPO training throughput on this CPU.

BENCH vs_baseline honesty (VERDICT.md r2 weak #4): comparing our full
training iteration against the reference's *env-stepping-only* 1,066
formation-steps/s flatters the reference-relative speedup the wrong way —
reference training also pays policy inference and the SB3 minibatch update.
SB3 itself is not installable in this image, so this script MEASURES the
three components the SB3 on-policy loop executes (collect_rollouts +
train; SURVEY.md §3.1) with the same torch CPU stack the reference uses:

1. env stepping: the measured 1,066 formation-steps/s (BASELINE.md,
   M=1000 x N=5 replica of vectorized_env.py:71-81) -> 1.066 vec-steps/s;
2. policy inference: MlpPolicy actor-critic forward (2x64 tanh trunk,
   value head, Gaussian sample — SB3 default architecture) on the
   (M*N, 8) observation batch, once per vec-step;
3. PPO update: per rollout of n_steps=10 vec-steps, 10 epochs x
   ceil(500_000/64)... precisely: total = n_steps*M*N = 50_000
   agent-transitions, minibatch 64 -> 781 full minibatches per epoch,
   10 epochs (SB3 defaults; vectorized_env.py:126-137) of
   forward+backward+Adam on the same architecture.

Result: formation-steps/s for the full loop =
    (n_steps * M) / (n_steps * (t_env_vecstep + t_infer) + t_update)

Run: python scripts/estimate_reference_train.py
The output feeds bench.py's REFERENCE_TRAIN_FORMATION_STEPS_PER_SEC and
docs/reference_train_estimate.md.
"""

from __future__ import annotations

import json
import time

import torch
import torch.nn as nn

M, N, OBS, ACT = 1000, 5, 8, 2
N_STEPS, EPOCHS, MB = 10, 10, 64
ENV_VEC_STEPS_PER_SEC = 1.07  # BASELINE.md measured, M=1000 x N=5


class MlpPolicy(nn.Module):
    """SB3 'MlpPolicy' default shape: separate 2x64-tanh actor and critic
    trunks, Gaussian head with state-independent log_std."""

    def __init__(self) -> None:
        super().__init__()
        self.actor = nn.Sequential(
            nn.Linear(OBS, 64), nn.Tanh(), nn.Linear(64, 64), nn.Tanh()
        )
        self.critic = nn.Sequential(
            nn.Linear(OBS, 64), nn.Tanh(), nn.Linear(64, 64), nn.Tanh()
        )
        self.mu = nn.Linear(64, ACT)
        self.v = nn.Linear(64, 1)
        self.log_std = nn.Parameter(torch.zeros(ACT))

    def forward(self, obs):
        a = self.actor(obs)
        c = self.critic(obs)
        return self.mu(a), self.log_std, self.v(c)


def timeit(fn, min_s=2.0):
    fn()  # warmup
    n, t0 = 0, time.perf_counter()
    while True:
        fn()
        n += 1
        dt = time.perf_counter() - t0
        if dt > min_s:
            return dt / n


def main() -> None:
    torch.set_num_threads(1)  # the reference runs single-process CPU
    policy = MlpPolicy()
    opt = torch.optim.Adam(policy.parameters(), lr=1e-3, eps=1e-5)

    obs_batch = torch.rand(M * N, OBS)

    def infer():
        with torch.no_grad():
            mu, log_std, v = policy(obs_batch)
            actions = mu + log_std.exp() * torch.randn_like(mu)
            # log-prob, as SB3 computes during collection
            ((actions - mu) ** 2).sum(-1)

    t_infer = timeit(infer)

    mb_obs = torch.rand(MB, OBS)
    mb_act = torch.rand(MB, ACT)
    mb_adv = torch.rand(MB)
    mb_ret = torch.rand(MB)
    mb_olp = torch.rand(MB)

    def minibatch():
        mu, log_std, v = policy(mb_obs)
        lp = (
            -0.5 * (((mb_act - mu) / log_std.exp()) ** 2).sum(-1)
            - log_std.sum()
        )
        ratio = (lp - mb_olp).exp()
        adv = (mb_adv - mb_adv.mean()) / (mb_adv.std() + 1e-8)
        pl = -torch.min(
            adv * ratio, adv * ratio.clamp(0.8, 1.2)
        ).mean()
        vl = ((mb_ret - v.squeeze(-1)) ** 2).mean()
        loss = pl + 0.5 * vl + 0.01 * log_std.sum()
        opt.zero_grad()
        loss.backward()
        nn.utils.clip_grad_norm_(policy.parameters(), 0.5)
        opt.step()

    t_mb = timeit(minibatch)

    total_transitions = N_STEPS * M * N
    n_minibatches = EPOCHS * (total_transitions // MB)
    t_env_vecstep = 1.0 / ENV_VEC_STEPS_PER_SEC
    t_rollout = N_STEPS * (t_env_vecstep + t_infer)
    t_update = n_minibatches * t_mb
    t_iteration = t_rollout + t_update
    rate = N_STEPS * M / t_iteration

    out = {
        "t_infer_per_vecstep_s": round(t_infer, 5),
        "t_minibatch_s": round(t_mb, 6),
        "n_minibatches_per_iteration": n_minibatches,
        "t_env_per_vecstep_s": round(t_env_vecstep, 4),
        "t_rollout_s": round(t_rollout, 3),
        "t_update_s": round(t_update, 3),
        "t_iteration_s": round(t_iteration, 3),
        "reference_train_formation_steps_per_sec": round(rate, 1),
        "env_only_formation_steps_per_sec": ENV_VEC_STEPS_PER_SEC * M,
        "config": {
            "M": M, "N": N, "n_steps": N_STEPS, "epochs": EPOCHS,
            "minibatch": MB, "torch_threads": 1,
        },
    }
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
