#!/usr/bin/env bash
# Run every hardware-dependent validation in one go and refresh the
# committed artifacts. Run from the repo root when the TPU tunnel is up
# (probe first: the tunnel drops for hours — bench.py's subprocess probe
# pattern; a bare jax.devices() can hang forever).
#
#   bash scripts/chip_checks.sh
#
# Artifacts refreshed:
#   docs/acceptance/tpu_parity.txt   (k-NN parity, BOTH kernels, f64 anchor)
#   docs/profiling.md table input    (stdout of tpu_profile_breakdown)
#   /tmp/bench_tpu.json              (full bench line — inspect, then
#                                     mirror into docs/acceptance/ if it
#                                     supersedes tpu_bench_r3.md)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== probe =="
python - <<'EOF'
import subprocess, sys
try:
    out = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=90,
    )
except subprocess.TimeoutExpired:
    print("probe: TIMEOUT — tunnel down, aborting chip checks")
    sys.exit(1)
platform = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
print("platform:", platform or out.stderr[-200:])
sys.exit(0 if platform and platform != "cpu" else 1)
EOF

echo "== all-paths training smoke (one iteration per path) =="
python scripts/tpu_smoke.py

echo "== k-NN hardware parity (fused + chunked kernels, f64 anchor) =="
python tests/tpu_compiled_parity.py | tee /tmp/parity_out.txt
# Build the artifact in a temp file and rename atomically: a tunnel drop
# mid-pipeline once truncated the committed artifact to its header.
{
  echo "# TPU hardware k-NN parity artifact"
  echo "# command: python tests/tpu_compiled_parity.py"
  echo "# date: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
  python -c "import jax; print('# device:', jax.devices()[0].device_kind, '| backend:', jax.default_backend())" | grep '^#'
  grep PARITY /tmp/parity_out.txt
} > /tmp/tpu_parity.txt.tmp
grep -q PARITY /tmp/tpu_parity.txt.tmp  # refuse to publish a header-only file
mv /tmp/tpu_parity.txt.tmp docs/acceptance/tpu_parity.txt
cat docs/acceptance/tpu_parity.txt

echo "== training profile breakdown (parity vs preset=tpu) =="
python scripts/tpu_profile_breakdown.py 4096

echo "== population sweep amortization (K=8) =="
python scripts/tpu_sweep_bench.py 8 512

echo "== big-batch training tuning (16k/32k with lr scaling + eval guard) =="
python scripts/tpu_train_tuning.py 4096 120 | tail -1 > /tmp/train_tuning.json
cat /tmp/train_tuning.json

echo "== full bench =="
python bench.py | tail -1 > /tmp/bench_tpu.json
cat /tmp/bench_tpu.json
python scripts/mirror_bench.py /tmp/bench_tpu.json \
    docs/acceptance/tpu_bench_r4.md

echo "== done — review artifacts, then commit =="
