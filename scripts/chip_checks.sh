#!/usr/bin/env bash
# Run every hardware-dependent validation from scratch and refresh the
# committed artifacts. Since round 4 this is a thin wrapper over the
# stage-stamped chip-window burster (scripts/chip_window.sh) — clearing
# the stamp state first so everything re-runs — because the tunnel now
# surfaces in short windows and the burster's per-stage resume is the
# only design that survives a mid-run drop. For incremental/opportunistic
# runs use chip_window.sh directly (or scripts/chip_watchdog.sh to poll
# for windows automatically).
#
#   bash scripts/chip_checks.sh
#
# Artifacts refreshed (by the burster):
#   docs/acceptance/tpu_parity.txt    (k-NN parity, BOTH kernels, f64 anchor)
#   docs/acceptance/tpu_bench_r4.md   (mirrored full-bench JSON)
#   docs/acceptance/tpu_smoke.txt     (per-path hardware smoke lines)
#   /tmp/{profile,tuning,sweep_bench}_out.txt, logs/{hetero5,sweep8}_tpu/
set -euo pipefail
cd "$(dirname "$0")/.."

rm -rf /tmp/chip_state
exec bash scripts/chip_window.sh
