#!/usr/bin/env python
"""graftlint CLI: JAX-hygiene static analysis over a package tree.

Usage:
    python scripts/graftlint.py [paths...]          # report, exit 0
    python scripts/graftlint.py --check [paths...]  # exit 1 on any ERROR
    python scripts/graftlint.py --format sarif      # SARIF 2.1.0 on stdout

Default path is the ``marl_distributedformation_tpu`` package.
Configuration comes from ``[tool.graftlint]`` in pyproject.toml
(per-rule severity overrides, exclude list); suppression syntax and the
rule catalogue are documented in docs/static_analysis.md. ``--check``
gates on error-severity violations only, so a CI can adopt the linter
with rules downgraded to ``warn`` while a tree is being cleaned.

The lint itself is pure-AST — no jax session is created and no code in
the linted tree is imported or executed.
"""

from __future__ import annotations

import argparse
import json
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


def _stub_package(name: str, path: Path) -> None:
    """Register ``name`` as a namespace-style stub so its submodules
    import WITHOUT executing its ``__init__.py``. The package root pulls
    in env/models/train (and jax) — executing it would (a) crash the CLI
    on exactly the syntax-broken trees the linter has a dedicated
    ``syntax-error`` violation for, and (b) start a jax session a pure
    AST pass has no use for."""
    if name not in sys.modules:
        stub = types.ModuleType(name)
        stub.__path__ = [str(path)]
        sys.modules[name] = stub


_PKG = REPO_ROOT / "marl_distributedformation_tpu"
_stub_package("marl_distributedformation_tpu", _PKG)
_stub_package("marl_distributedformation_tpu.analysis", _PKG / "analysis")

from marl_distributedformation_tpu.analysis.config import load_config  # noqa: E402
from marl_distributedformation_tpu.analysis.linter import lint_paths  # noqa: E402
from marl_distributedformation_tpu.analysis.rules import (  # noqa: E402
    all_rules,
    rule_names,
)

_SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)
_SARIF_LEVELS = {"error": "error", "warn": "warning"}


def sarif_report(violations, root: Path) -> dict:
    """The lint result as a SARIF 2.1.0 document. Rule metadata (id +
    short description) rides in the driver so viewers can group by rule;
    each result carries the full message text — for lock-ordering
    findings that text includes the complete acquisition chain (every
    ``holding A acquires B in fn (file:line)`` edge of the cycle)."""
    rules = all_rules()
    rule_index = {r.name: i for i, r in enumerate(rules)}

    def uri(path: str) -> str:
        p = Path(path)
        try:
            p = p.resolve().relative_to(root.resolve())
        except ValueError:
            pass
        return p.as_posix()

    return {
        "$schema": _SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftlint",
                        "rules": [
                            {
                                "id": r.name,
                                "shortDescription": {"text": r.description},
                                "defaultConfiguration": {
                                    "level": _SARIF_LEVELS[r.default_severity]
                                },
                            }
                            for r in rules
                        ],
                    }
                },
                "results": [
                    {
                        "ruleId": v.rule,
                        "ruleIndex": rule_index.get(v.rule, -1),
                        "level": _SARIF_LEVELS.get(v.severity, "warning"),
                        "message": {"text": v.message},
                        "locations": [
                            {
                                "physicalLocation": {
                                    "artifactLocation": {"uri": uri(v.path)},
                                    "region": {
                                        "startLine": v.line,
                                        # SARIF columns are 1-based;
                                        # ast col_offset is 0-based.
                                        "startColumn": v.col + 1,
                                    },
                                }
                            }
                        ],
                    }
                    for v in violations
                ],
            }
        ],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "paths",
        nargs="*",
        default=[str(REPO_ROOT / "marl_distributedformation_tpu")],
        help="files or directories to lint (default: the package)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit non-zero if any error-severity violation is found",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule names and exit"
    )
    parser.add_argument(
        "--format",
        choices=("text", "sarif"),
        default="text",
        help="output format: human-readable text (default) or SARIF 2.1.0",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in rule_names():
            print(name)
        return 0

    config = load_config(REPO_ROOT)
    violations = lint_paths(args.paths, config, root=REPO_ROOT)
    errors = sum(1 for v in violations if v.severity == "error")
    if args.format == "sarif":
        # stdout is the document — the human summary goes to stderr so
        # `graftlint --format sarif > out.sarif` stays valid JSON.
        json.dump(sarif_report(violations, REPO_ROOT), sys.stdout, indent=2)
        print()
        print(
            f"graftlint: {errors} error(s), "
            f"{len(violations) - errors} warning(s)",
            file=sys.stderr,
        )
    else:
        for v in violations:
            print(v)
        print(
            f"graftlint: {errors} error(s), "
            f"{len(violations) - errors} warning(s) in "
            f"{', '.join(str(p) for p in args.paths)}"
        )
    if args.check and errors:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
