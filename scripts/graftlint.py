#!/usr/bin/env python
"""graftlint CLI: JAX-hygiene static analysis over a package tree.

Usage:
    python scripts/graftlint.py [paths...]          # report, exit 0
    python scripts/graftlint.py --check [paths...]  # exit 1 on any ERROR

Default path is the ``marl_distributedformation_tpu`` package.
Configuration comes from ``[tool.graftlint]`` in pyproject.toml
(per-rule severity overrides, exclude list); suppression syntax and the
rule catalogue are documented in docs/static_analysis.md. ``--check``
gates on error-severity violations only, so a CI can adopt the linter
with rules downgraded to ``warn`` while a tree is being cleaned.

The lint itself is pure-AST — no jax session is created and no code in
the linted tree is imported or executed.
"""

from __future__ import annotations

import argparse
import sys
import types
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))


def _stub_package(name: str, path: Path) -> None:
    """Register ``name`` as a namespace-style stub so its submodules
    import WITHOUT executing its ``__init__.py``. The package root pulls
    in env/models/train (and jax) — executing it would (a) crash the CLI
    on exactly the syntax-broken trees the linter has a dedicated
    ``syntax-error`` violation for, and (b) start a jax session a pure
    AST pass has no use for."""
    if name not in sys.modules:
        stub = types.ModuleType(name)
        stub.__path__ = [str(path)]
        sys.modules[name] = stub


_PKG = REPO_ROOT / "marl_distributedformation_tpu"
_stub_package("marl_distributedformation_tpu", _PKG)
_stub_package("marl_distributedformation_tpu.analysis", _PKG / "analysis")

from marl_distributedformation_tpu.analysis.config import load_config  # noqa: E402
from marl_distributedformation_tpu.analysis.linter import lint_paths  # noqa: E402
from marl_distributedformation_tpu.analysis.rules import rule_names  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "paths",
        nargs="*",
        default=[str(REPO_ROOT / "marl_distributedformation_tpu")],
        help="files or directories to lint (default: the package)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit non-zero if any error-severity violation is found",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print rule names and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in rule_names():
            print(name)
        return 0

    config = load_config(REPO_ROOT)
    violations = lint_paths(args.paths, config, root=REPO_ROOT)
    for v in violations:
        print(v)
    errors = sum(1 for v in violations if v.severity == "error")
    warns = len(violations) - errors
    print(
        f"graftlint: {errors} error(s), {warns} warning(s) in "
        f"{', '.join(str(p) for p in args.paths)}"
    )
    if args.check and errors:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
