#!/usr/bin/env python
"""One-iteration hardware smoke of EVERY training path on the real chip.

The round-2 lesson (VERDICT.md r2 weak #1) is that CPU tests cannot catch
device-only failures (bf16 matmul precision, Mosaic lowering rules) — and
that hardware checks only help if they actually get run. This script is
the broad companion to tests/tpu_compiled_parity.py's deep k-NN check:
it drives one full jitted training iteration of every path the framework
ships — MLP (parity + preset=tpu batch), CTDE, knn+GNN (Pallas kernel
live), the heterogeneous curriculum, a seed population, and the
hetero-curriculum candidate population (the config-5 selection
workflow) — and prints one SMOKE_OK/SMOKE_FAIL line each. Run via scripts/chip_checks.sh or:

    python scripts/tpu_smoke.py        # ~2-3 min incl. compiles
    python scripts/tpu_smoke.py cpu    # off-chip smoke of the script itself
    python scripts/tpu_smoke.py gnn_knn100 sweep_k4   # just these paths

Naming paths on the CLI runs only those — the chip-window burster
(scripts/chip_window.sh) uses this to resume after a tunnel drop killed a
partial run, instead of re-paying every compile for paths that already
passed inside an earlier window.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# Single source of truth for the path names — `--list` prints these so
# shell callers (scripts/chip_window.sh) never hardcode a drifting copy;
# run_paths() asserts its dict matches.
SMOKE_PATHS = (
    "mlp_parity",
    "mlp_tuned",
    "ctde",
    "gnn_knn100",
    "gnn_swarm1024",
    "hetero_curriculum",
    "sweep_k4",
    "hetero_pop",
)


def run_paths(m: int = 256, only: list[str] | None = None) -> dict:
    import jax
    import numpy as np

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.utils.config import PRESETS
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.models import (
        CTDEActorCritic,
        GNNActorCritic,
    )
    from marl_distributedformation_tpu.train import (
        Curriculum,
        CurriculumStage,
        HeteroSweepTrainer,
        HeteroTrainer,
        SweepTrainer,
        TrainConfig,
        Trainer,
    )

    def cfg(name: str, m: int) -> TrainConfig:
        return TrainConfig(
            num_formations=m, checkpoint=False, name=name,
            log_dir=f"/tmp/smoke-{name}",
        )

    def one_iteration(trainer):
        t0 = time.perf_counter()
        metrics = trainer.run_iteration()
        loss = metrics.get("loss", metrics.get("reward"))
        jax.block_until_ready(loss)
        arr = np.asarray(loss)
        assert np.isfinite(arr).all(), f"non-finite loss: {arr}"
        return time.perf_counter() - t0

    paths = {}

    paths["mlp_parity"] = lambda: one_iteration(
        Trainer(EnvParams(num_agents=5), config=cfg("mlp", m))
    )
    # The REAL preset (utils.config.PRESETS), not a hardcoded copy — the
    # smoke must keep covering whatever config preset=tpu actually runs.
    paths["mlp_tuned"] = lambda: one_iteration(
        Trainer(
            EnvParams(num_agents=5),
            ppo=PPOConfig(**PRESETS["tpu"]),
            config=cfg("mlp-tuned", m),
        )
    )
    paths["ctde"] = lambda: one_iteration(
        Trainer(
            EnvParams(num_agents=20),
            model=CTDEActorCritic(act_dim=2),
            config=cfg("ctde", max(m // 8, 8)),
        )
    )
    knn_params = EnvParams(num_agents=100, obs_mode="knn", knn_k=4)
    paths["gnn_knn100"] = lambda: one_iteration(
        Trainer(
            knn_params,
            model=GNNActorCritic(
                k=4, act_dim=2, goal_in_obs=knn_params.goal_in_obs
            ),
            config=cfg("gnn", max(m // 8, 8)),
        )
    )

    # N=1024 is past the fused kernel's VMEM cliff: on TPU the knn obs
    # resolve to the chunked-streaming kernel (ops/knn_pallas.py
    # knn_batch_pallas_big), so this path proves that kernel inside a
    # FULL training iteration — rollout scan + GAE + update — not just
    # the env-stepping loop bench.py times.
    swarm_params = EnvParams(num_agents=1024, obs_mode="knn", knn_k=4)
    paths["gnn_swarm1024"] = lambda: one_iteration(
        Trainer(
            swarm_params,
            model=GNNActorCritic(
                k=4, act_dim=2, goal_in_obs=swarm_params.goal_in_obs
            ),
            ppo=PPOConfig(**PRESETS["tpu"]),  # 640 batch-64 minibatches
            #   per epoch would dominate the smoke; the preset batch keeps
            #   the update a few MXU-shaped steps
            config=cfg("gnn-swarm", max(m // 64, 2)),
        )
    )

    # ONE smoke curriculum + stage walk shared by both hetero paths so
    # they cannot drift apart.
    smoke_curriculum = Curriculum(
        stages=(
            CurriculumStage(rollouts=1, agent_counts=(5,)),
            CurriculumStage(
                rollouts=1, agent_counts=(5, 20), num_obstacles=2
            ),
        )
    )

    def walk_curriculum(trainer):
        total = 0.0
        for stage in trainer.curriculum.stages:
            trainer.start_stage(stage)
            total += one_iteration(trainer)
        return total

    paths["hetero_curriculum"] = lambda: walk_curriculum(
        HeteroTrainer(
            curriculum=smoke_curriculum,
            env_params=EnvParams(num_agents=5, max_steps=64),
            config=cfg("hetero", max(m // 8, 8)),
        )
    )
    paths["sweep_k4"] = lambda: one_iteration(
        SweepTrainer(
            EnvParams(num_agents=5), config=cfg("sweep", max(m // 4, 8)),
            num_seeds=4,
        )
    )

    # Candidate-seed population of the curriculum (round 5,
    # train/hetero_sweep.py — the config-5 selection workflow), incl.
    # the noise-decay schedule it ships with and a stage transition.
    paths["hetero_pop"] = lambda: walk_curriculum(
        HeteroSweepTrainer(
            curriculum=smoke_curriculum,
            env_params=EnvParams(num_agents=5, max_steps=64),
            ppo=PPOConfig(ent_coef_final=0.0, log_std_final=-2.5),
            config=cfg("hetero-pop", max(m // 16, 4)),
            num_seeds=2,
        )
    )

    assert set(paths) == set(SMOKE_PATHS), (
        "SMOKE_PATHS is out of sync with the paths dict: "
        f"{sorted(set(paths) ^ set(SMOKE_PATHS))}"
    )
    if only:
        unknown = sorted(set(only) - set(paths))
        if unknown:
            raise SystemExit(
                f"unknown smoke path(s) {unknown}; have {sorted(paths)}"
            )
        paths = {name: fn for name, fn in paths.items() if name in only}

    device = jax.devices()[0].device_kind
    results, failed = {}, []
    for name, fn in paths.items():
        try:
            secs = fn()
            results[name] = round(secs, 3)
            print(f"SMOKE_OK: {name} on {device} ({secs:.2f}s first "
                  "iteration incl. compile)", flush=True)
        except Exception as e:  # noqa: BLE001 — report every path
            failed.append(name)
            print(f"SMOKE_FAIL: {name}: {type(e).__name__}: "
                  f"{e}"[:1500], flush=True)
    summary = {
        "metric": "tpu_smoke",
        "device": device,
        "paths_ok": sorted(set(results)),
        "paths_failed": failed,
        "first_iteration_secs": results,
    }
    print(json.dumps(summary), flush=True)
    return summary


def main() -> None:
    args = sys.argv[1:]
    if "--list" in args:
        print(" ".join(SMOKE_PATHS))
        return

    import jax

    cpu = "cpu" in args
    only = [a for a in args if a != "cpu"]
    if cpu:
        jax.config.update("jax_platforms", "cpu")
    # Off-chip self-smoke shrinks the batch: it checks the script, not
    # host-CPU throughput.
    summary = run_paths(m=32 if cpu else 256, only=only or None)
    if summary["paths_failed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
