#!/usr/bin/env python
"""Mirror a bench JSON line into a committed acceptance record.

The chip window is scarce (the tunnel drops for hours); this turns the
manual "inspect /tmp/bench_tpu.json, hand-write the markdown" step into
one command so `scripts/chip_checks.sh` output can be committed
immediately:

    python scripts/mirror_bench.py /tmp/bench_tpu.json \
        docs/acceptance/tpu_bench_r4.md

Refuses CPU-fallback JSONs by default (a fallback line is NOT hardware
evidence — pass --allow-fallback to record one anyway, clearly marked).
The date stamp comes from the file's mtime (the measurement time), not
the mirror time.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
from pathlib import Path


def _load_record(src: Path) -> dict:
    """Accept either bench.py stdout (ONE JSON line, possibly preceded by
    stderr noise) or the driver's BENCH_r*.json wrapper (whose ``tail``
    field embeds the bench line)."""
    text = src.read_text().strip()
    try:
        parsed = json.loads(text)
    except json.JSONDecodeError:
        parsed = None
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    if isinstance(parsed, dict) and "tail" in parsed:
        text = str(parsed["tail"]).strip()
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            rec = json.loads(line)
            if "metric" in rec:
                return rec
    raise SystemExit(f"no bench JSON record found in {src}")


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("src", type=Path)
    ap.add_argument("dst", type=Path)
    ap.add_argument(
        "--command",
        default="python bench.py",
        help="exact invocation to record (include BENCH_SKIP_* flags "
        "for partial-phase runs)",
    )
    ap.add_argument("--allow-fallback", action="store_true")
    args = ap.parse_args()
    src, dst, command = args.src, args.dst, args.command
    allow_fallback = args.allow_fallback
    rec = _load_record(src)
    fallback = bool(rec.get("fallback"))
    if fallback and not allow_fallback:
        raise SystemExit(
            f"{src} is a CPU-fallback record (platform="
            f"{rec.get('platform')!r}) — not hardware evidence. "
            "Re-run on the chip, or pass --allow-fallback to record it "
            "clearly marked."
        )
    measured = datetime.datetime.fromtimestamp(
        src.stat().st_mtime, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")

    lines = [
        f"# Bench record — {rec.get('device', 'unknown device')}"
        + (" (CPU FALLBACK — not hardware evidence)" if fallback else ""),
        "",
        f"- measured: {measured} (source file mtime)",
        f"- platform: {rec.get('platform')} | fallback: {fallback}",
        f"- command: `{command}` (mirrored by scripts/mirror_bench.py)",
        "",
        "| field | value |",
        "|---|---|",
    ]
    for key, value in rec.items():
        if isinstance(value, float):
            value = f"{value:,.1f}"
        lines.append(f"| `{key}` | {value} |")
    lines += [
        "",
        "Raw JSON:",
        "",
        "```json",
        json.dumps(rec, indent=2),
        "```",
        "",
    ]
    dst.parent.mkdir(parents=True, exist_ok=True)
    # Atomic tmp+rename: a stage timeout killing us mid-write must never
    # truncate a previously-banked record (same rule as bank_txt_artifact
    # and parity_stage in chip_window.sh; the burster sweeps stale .tmp).
    tmp = dst.with_suffix(dst.suffix + ".tmp")
    tmp.write_text("\n".join(lines))
    os.replace(tmp, dst)
    print(f"[mirror_bench] wrote {dst} ({len(rec)} fields)")


if __name__ == "__main__":
    main()
