#!/usr/bin/env bash
# Opportunistic chip-window burster.
#
# The TPU tunnel now surfaces in SHORT windows (minutes), not long
# uptime: a sequential 15-minute pipeline (scripts/chip_checks.sh) loses
# everything when the tunnel drops mid-stage. This script runs the same
# validation queue as a sequence of independently-stamped stages in
# VALUE order (parity artifact > bench JSON > smoke > profile > tuning >
# sweep bench > acceptance training runs), so each window makes forward
# progress and the next window resumes from the first missing stamp:
#
#   bash scripts/chip_window.sh            # run whatever is still missing
#   rm -rf /tmp/chip_state                 # force a full re-run
#
# Every stage runs under `timeout` (a tunnel drop mid-op hangs forever —
# the round-3 lesson), stamps /tmp/chip_state/<stage> only on success,
# and a failure triggers a re-probe: tunnel down => exit (window over),
# tunnel up => keep going (the stage itself failed; don't block others).
# Driven automatically by scripts/chip_watchdog.sh.
set -uo pipefail
cd "$(dirname "$0")/.."

STATE=${CHIP_STATE_DIR:-/tmp/chip_state}
export STATE  # stage functions run under `bash -c` and read it
# The burster owns the single chip and the shared /tmp artifacts: one
# instance at a time, whether fired by the watchdog or by hand. The lock
# lives HERE (not in the watchdog) so a manual run can't race a tick.
# Self-exec under flock's command form — the bare fd form does not hold
# the lock past the flock utility's exit on this system (verified) — so
# the lock spans the whole run and auto-releases when it dies. Exit 73
# means "another run holds the lock".
if [ "${CHIP_WINDOW_LOCKED:-}" != 1 ]; then
  export CHIP_WINDOW_LOCKED=1
  exec flock -n -E 73 "${CHIP_LOCK_FILE:-/tmp/chip_window.lock}" bash "$0" "$@"
fi

mkdir -p "$STATE" docs/acceptance
# A stage timeout can kill a banking helper mid-write; its atomic-rename
# `.tmp` then survives in the tracked acceptance dir. Sweep them so a
# killed run can't leave a truncated pseudo-artifact for `git add`. MUST
# stay below the flock gate: before it, a bounced-off concurrent tick
# would delete the lock-holder's in-flight tmp mid-rename.
rm -f docs/acceptance/*.tmp docs/acceptance/*/*.tmp

# The smoke stamp aggregates per-path stamps: a grown tpu_smoke.py path
# list must reopen it AND the ALL_DONE sentinel (a tunnel-down tick
# exits before the bottom sentinel loop runs, so clearing only the
# smoke stamp would leave ALL_DONE to short-circuit every future
# watchdog tick). Pure local stamp reconciliation, so it runs before
# the probe; `--list` is import-light (no jax). A failed --list must
# not silently pass a stale stamp — warn and leave state untouched.
if [ -f "$STATE/smoke" ]; then
  if smoke_list=$(python scripts/tpu_smoke.py --list) \
      && [ -n "$smoke_list" ]; then
    for p in $smoke_list; do
      if [ ! -f "$STATE/smoke_$p" ]; then
        rm -f "$STATE/smoke" "$STATE/ALL_DONE"
        break
      fi
    done
  else
    echo "WARNING: tpu_smoke.py --list failed; smoke stamp not reconciled"
  fi
fi

probe() {
  # Test hook: CHIP_PROBE_CMD replaces the device probe so the
  # orchestration (stamps, resume, sentinel) is testable off-chip.
  if [ -n "${CHIP_PROBE_CMD:-}" ]; then
    eval "$CHIP_PROBE_CMD"
    return $?
  fi
  # 45s timeout: an up tunnel answers a device query in ~5-10s; waiting
  # the old 90s on a down tunnel burned half the detection cadence and
  # windows last only minutes.
  python - <<'EOF'
import subprocess, sys
try:
    out = subprocess.run(
        [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
        capture_output=True, text=True, timeout=45,
    )
except subprocess.TimeoutExpired:
    sys.exit(1)
platform = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
sys.exit(0 if platform and platform != "cpu" else 1)
EOF
}
export -f probe  # smoke_stage re-probes between paths under bash -c

# stage <name> <timeout_s> <fn>: skip if stamped; run the exported shell
# function under timeout (timeout(1) can't exec a function, so it goes
# through bash -c); stamp on success; on failure re-probe and exit 0 if
# the window closed.
ALL_STAGES=()
stage() {
  local name="$1" tmo="$2" fn="$3"
  ALL_STAGES+=("$name")
  if [ -f "$STATE/$name" ]; then return 0; fi
  echo "== stage $name (timeout ${tmo}s) $(date -u +%H:%M:%SZ) =="
  if timeout "$tmo" bash -c "set -uo pipefail; $fn"; then
    touch "$STATE/$name"
    echo "== stage $name OK =="
  else
    echo "== stage $name FAILED/TIMED OUT — re-probing tunnel =="
    if ! probe; then
      echo "== tunnel down; window over $(date -u +%H:%M:%SZ) =="
      exit 0
    fi
  fi
}

# Never contend with a foreign bench run for the single chip (the round
# driver runs `python bench.py` for the official record; two processes
# on one TPU skew both). Our own bench children run only while the lock
# is held, i.e. after this check. CHIP_FOREIGN_BENCH_CMD substitutes the
# check for tests (like CHIP_PROBE_CMD) — otherwise a live watchdog's
# bench child makes the orchestration tests flaky, and vice versa a
# test-suite bench subprocess defers a real window.
foreign_bench() {
  if [ -n "${CHIP_FOREIGN_BENCH_CMD:-}" ]; then
    eval "$CHIP_FOREIGN_BENCH_CMD"
    return $?
  fi
  pgrep -f "python bench.py" >/dev/null 2>&1
}
if foreign_bench; then
  echo "foreign bench.py run in progress; deferring this window"
  exit 0
fi

if ! probe; then
  echo "probe: tunnel down, nothing to do"
  exit 0
fi
echo "== window open $(date -u +%Y-%m-%dT%H:%M:%SZ) =="

# -- 1. k-NN hardware parity (both kernels, f64 anchor) + artifact ------
parity_stage() {
  python tests/tpu_compiled_parity.py | tee /tmp/parity_out.txt || return 1
  {
    echo "# TPU hardware k-NN parity artifact"
    echo "# command: python tests/tpu_compiled_parity.py"
    echo "# date: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    python -c "import jax; print('# device:', jax.devices()[0].device_kind, '| backend:', jax.default_backend())" | grep '^#'
    grep PARITY /tmp/parity_out.txt
  } > /tmp/tpu_parity.txt.tmp
  grep -q PARITY /tmp/tpu_parity.txt.tmp || return 1
  mv /tmp/tpu_parity.txt.tmp docs/acceptance/tpu_parity.txt
  cat docs/acceptance/tpu_parity.txt
}
export -f parity_stage
stage parity 600 parity_stage

# -- 2. full monolithic bench, FIRST after parity (round-5 reorder,
# VERDICT r4 next-#2): the shipped tree needs a driver-grade chip record
# under the retuned batch-16384 preset, and the round-4 ordering (bench
# last) left the driver's BENCH_r04.json as a CPU fallback. Every phase
# in one run, mirrored to tpu_bench_r5.md (supersedes the r4 record in
# bench.py's replay-pointer glob). ------------------------------------
bench_stage() {
  local cmd="BENCH_BUDGET_S=540 python bench.py"
  eval "$cmd" | tail -1 > /tmp/bench_tpu.json || return 1
  cat /tmp/bench_tpu.json
  # Hardware evidence only: scripts/check_bench_record.py refuses a
  # fallback line, an errored run (e.g. bench.py's own watchdog fired
  # mid-hang — it still emits a JSON line, with an "error" field and
  # value 0), and a phase-incomplete run (bench.py degrades
  # over-deadline phases into "... skipped"/"... failed" notes —
  # mirroring such a line would enshrine a partial run as the round's
  # record; retry next window).
  python scripts/check_bench_record.py /tmp/bench_tpu.json \
      --require value train_env_steps_per_sec train_env_steps_per_sec_tuned \
                train_env_steps_per_sec_tuned_fused knn_env_steps_per_sec \
                knn_big_env_steps_per_sec || return 1
  python scripts/mirror_bench.py /tmp/bench_tpu.json \
      docs/acceptance/tpu_bench_r5.md --command "$cmd"
}
export -f bench_stage
stage bench 720 bench_stage

# -- 3. knn_big alone — the N=1024 chunked Pallas kernel past the VMEM
# cliff (first measured on hardware in round 4). A short window must be
# able to secure it without finishing the full bench. ------------------
knn_big_stage() {
  # SKIP_ENV_MAX: the shared gate rejects ANY failed/skipped phase note,
  # so don't run phases this stage doesn't require (env_max lands in the
  # full-bench record instead). `cmd` is defined ONCE and both executed
  # and recorded, so the mirror's stated command cannot drift from the
  # run (same pattern in every stage below).
  local cmd="BENCH_SKIP_TRAIN=1 BENCH_SKIP_KNN=1 BENCH_SKIP_ENV_MAX=1 BENCH_BUDGET_S=300 python bench.py"
  eval "$cmd" | tail -1 > /tmp/bench_knn_big.json || return 1
  cat /tmp/bench_knn_big.json
  python scripts/check_bench_record.py /tmp/bench_knn_big.json \
      --require knn_big_env_steps_per_sec \
      --expect knn_big_impl=pallas_big || return 1
  python scripts/mirror_bench.py /tmp/bench_knn_big.json \
      docs/acceptance/tpu_knn_big_r5.md --command "$cmd"
}
export -f knn_big_stage
stage knn_big 420 knn_big_stage

# -- 3a. train phases alone (parity + tuned + fused) — the fused number
# has never been measured on hardware. The full bench is a ~10-minute
# monolith (round-4 window 1 died inside it when the tunnel dropped);
# these per-phase runs each fit a short window, so every window banks a
# complete dated record for SOME phase group even if a long window never
# shows. The monolithic stage below remains the clean single-run record.
bench_train_stage() {
  local cmd="BENCH_SKIP_KNN=1 BENCH_SKIP_KNN_BIG=1 BENCH_SKIP_ENV_MAX=1 BENCH_BUDGET_S=420 python bench.py"
  eval "$cmd" | tail -1 > /tmp/bench_train.json || return 1
  cat /tmp/bench_train.json
  python scripts/check_bench_record.py /tmp/bench_train.json \
      --require train_env_steps_per_sec train_env_steps_per_sec_tuned \
                train_env_steps_per_sec_tuned_fused || return 1
  # NB: the mirror name must NOT match the tpu_bench_r*.md glob —
  # bench.py's _latest_chip_bench_claim() treats those as FULL-bench
  # records when composing the CPU-fallback replay pointer.
  python scripts/mirror_bench.py /tmp/bench_train.json \
      docs/acceptance/tpu_bench_train_r5.md --command "$cmd"
}
export -f bench_train_stage
stage bench_train 600 bench_train_stage

# -- 3b. knn N=100 phase alone (fused Pallas kernel at the GNN shape) ---
bench_knn_stage() {
  local cmd="BENCH_SKIP_TRAIN=1 BENCH_SKIP_KNN_BIG=1 BENCH_SKIP_ENV_MAX=1 BENCH_BUDGET_S=240 python bench.py"
  eval "$cmd" | tail -1 > /tmp/bench_knn.json || return 1
  cat /tmp/bench_knn.json
  python scripts/check_bench_record.py /tmp/bench_knn.json \
      --require knn_env_steps_per_sec --expect knn_impl=pallas || return 1
  python scripts/mirror_bench.py /tmp/bench_knn.json \
      docs/acceptance/tpu_bench_knn_r5.md --command "$cmd"
}
export -f bench_knn_stage
stage bench_knn 420 bench_knn_stage

# -- 4. remaining all-paths smoke (per-path stamps) ---------------------
smoke_stage() {
  # Path names come from the script itself (--list) — no drifting copy.
  # One process + stamp PER PATH, so a tunnel drop mid-path keeps every
  # earlier pass (a single batched run would lose all its stamps when
  # the stage timeout kills the wrapper before the stamping loop).
  local paths bad=0
  paths=$(python scripts/tpu_smoke.py --list) || return 1
  [ -n "$paths" ] || return 1  # an empty list must never stamp success
  for p in $paths; do
    [ -f "$STATE/smoke_$p" ] && continue
    if timeout 420 python scripts/tpu_smoke.py "$p" | tee /tmp/smoke_out.txt \
        && grep -q "SMOKE_OK: $p " /tmp/smoke_out.txt; then
      touch "$STATE/smoke_$p"
      grep "SMOKE_OK: $p " /tmp/smoke_out.txt \
        | sed "s/^/$(date -u +%Y-%m-%dT%H:%M:%SZ) /" >> docs/acceptance/tpu_smoke.txt
    else
      # One slow/failing path must not starve the rest — but if the
      # tunnel itself dropped, every further path would just burn its
      # timeout, so bail to the stage-level re-probe in that case.
      bad=1
      probe || return 1
    fi
  done
  return $bad
}
export -f smoke_stage
stage smoke 3000 smoke_stage

# -- 5. training profile breakdown --------------------------------------
profile_stage() {
  python scripts/tpu_profile_breakdown.py 4096 | tee /tmp/profile_out.txt
}
export -f profile_stage
stage profile 600 profile_stage

# bank_txt_artifact <captured_out> <dest> <title> <cmd>: land a script's
# teed stdout as a dated acceptance record. Atomic tmp+mv (same reason as
# parity_stage: the stage timeout can kill us mid-write, and a truncating
# `>` would destroy the previously-banked valid artifact).
bank_txt_artifact() {
  local src="$1" dest="$2" title="$3" cmd="$4"
  # Provenance gate: both scripts stamp the backend they actually ran on
  # into their summary JSON ("device": "TPU v5 lite" / "cpu"). A silent
  # mid-window CPU fallback must never be banked as chip evidence (same
  # rule check_bench_record.py / land_tpu_run enforce for their stages).
  grep -q '"device": "TPU' "$src" || return 1
  { echo "# $title"
    echo "# date: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    echo "# command: $cmd"
    # Anchored: only strip leading-WARNING log lines (jax/absl chatter),
    # never a data row that merely contains the substring.
    grep -v '^WARNING' "$src"
  } > "$dest.tmp" || { rm -f "$dest.tmp"; return 1; }
  mv "$dest.tmp" "$dest"
}
export -f bank_txt_artifact

# -- 6. big-batch tuning (lr scaling + eval quality guard) --------------
tuning_stage() {
  local cmd="python scripts/tpu_train_tuning.py 4096 120"
  eval "$cmd" | tee /tmp/tuning_out.txt || return 1
  # The summary JSON has no "metric" field (the old grep failed a GOOD
  # run); key on a NON-NULL sweep verdict — `"best_quality_ok": null`
  # means every point failed the eval quality guard and must not stamp.
  grep -q '"best_quality_ok": {' /tmp/tuning_out.txt || return 1
  bank_txt_artifact /tmp/tuning_out.txt docs/acceptance/tpu_tuning_r5.txt \
      "Big-batch tuning sweep — TPU v5 lite" "$cmd"
}
export -f tuning_stage
stage tuning 1200 tuning_stage

# -- 7. population sweep amortization -----------------------------------
sweep_bench_stage() {
  local cmd="python scripts/tpu_sweep_bench.py 8 512"
  eval "$cmd" | tee /tmp/sweep_bench_out.txt || return 1
  grep -q '"sweep_population_throughput"' /tmp/sweep_bench_out.txt || return 1
  bank_txt_artifact /tmp/sweep_bench_out.txt \
      docs/acceptance/tpu_sweep_bench_r5.txt \
      "Population-sweep amortization bench — TPU v5 lite" "$cmd"
}
export -f sweep_bench_stage
stage sweep_bench 600 sweep_bench_stage

# -- 7b. chunked k-NN kernel block-shape sweep --------------------------
knn_big_tuning_stage() {
  local cmd="python scripts/tpu_knn_big_tuning.py 512 1024 50"
  eval "$cmd" | tee /tmp/knn_big_tuning_out.txt || return 1
  # `"best": {` is null when no candidate matched XLA (indices exact +
  # distances within atol; see tpu_knn_big_tuning.py) — that is
  # a kernel bug, not a tuning result; never stamp it.
  grep -q '"best": {' /tmp/knn_big_tuning_out.txt || return 1
  bank_txt_artifact /tmp/knn_big_tuning_out.txt \
      docs/acceptance/tpu_knn_big_tuning_r5.txt \
      "Chunked k-NN kernel block-shape sweep — TPU v5 lite" "$cmd"
}
export -f knn_big_tuning_stage
stage knn_big_tuning 900 knn_big_tuning_stage

# land_tpu_run <run_name> <dest_dir> <artifacts_line>: verify the run's
# RESOLVED backend from its config snapshot (train.py _snapshot_config —
# a silent CPU fallback mid-window must never be banked as hardware
# acceptance evidence), then copy the learning curve and write the
# TPU_RUN.md record. EVERY command is guarded: a partial landing must
# fail the stage so the next window retries it rather than stamping a
# half-written record as done.
land_tpu_run() {
  local name="$1" dest="$2" artifacts="$3" device summary
  device=$(python - "$name" <<'EOF'
import json, sys
snap = json.load(open(f"logs/{sys.argv[1]}/config.json"))
got = snap.get("resolved_platform")
assert got == "tpu", f"run executed on {got!r}, not tpu"
print(snap.get("resolved_device"))
EOF
  ) || return 1
  cp "logs/$name/metrics.jsonl" "$dest/metrics_tpu.jsonl" || return 1
  summary=$(python scripts/summarize_acceptance.py \
      "logs/$name/metrics.jsonl") || return 1
  {
    echo "# TPU hardware run (landed by scripts/chip_window.sh, run name: $name)"
    echo
    echo "- date: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    echo "- device: $device"
    echo "- command: the CPU record's command without platform=cpu, name=$name (see chip_window.sh)"
    echo "- artifacts: $artifacts"
    echo
    echo "$summary"
  } > "$dest/TPU_RUN.md" || return 1
  cat "$dest/TPU_RUN.md"
}
export -f land_tpu_run

# -- 7c. N=1024 GNN learning END-TO-END on hardware (VERDICT r4 next-#4):
# the chunked-streaming Pallas kernel (ops/knn_pallas.py N>640 path) has
# chip evidence inside the bench loop and a single smoke iteration; this
# stage proves it inside the FULL training graph by banking a short
# learning run (reward must improve over ~12 iterations) with its curve
# and throughput. 12 iterations of M=8 x N=1024 x n_steps=10 = 983,040
# agent-transitions under the tpu preset. ------------------------------
gnn1024_learn_stage() {
  # Fresh run dir: the metrics logger appends, so a retry after a
  # timeout/tunnel-drop would otherwise mix rows from the dead attempt
  # into the banked curve (and the learning gate would compare across
  # runs).
  rm -rf logs/gnn1024_tpu
  python train.py name=gnn1024_tpu policy=gnn obs_mode=knn \
    num_agents_per_formation=1024 num_formation=8 preset=tpu \
    total_timesteps=983040 use_wandb=false || return 1
  # Learning gate: a flat/degrading curve must not stamp — the point of
  # the stage is evidence the kernel composes with the optimizer, not
  # just that the graph executes.
  python - <<'EOF' || return 1
import json
rows = [json.loads(l) for l in open("logs/gnn1024_tpu/metrics.jsonl") if l.strip()]
assert len(rows) >= 10, f"only {len(rows)} iterations"
first, last = rows[0]["reward"], rows[-1]["reward"]
assert last > first, f"no learning: reward {first:.2f} -> {last:.2f}"
print(f"[gnn1024] reward {first:.2f} -> {last:.2f} over {len(rows)} iters")
EOF
  mkdir -p docs/acceptance/gnn1024
  land_tpu_run gnn1024_tpu docs/acceptance/gnn1024 \
      "metrics_tpu.jsonl (N=1024 chunked-Pallas full-training learning curve)"
}
export -f gnn1024_learn_stage
stage gnn1024_learn 1800 gnn1024_learn_stage

# -- 8. config-5 hetero curriculum acceptance on the chip ---------------
# One knob for both hetero5 stages: candidates per training attempt.
# K=8: the CPU study measured ~1/4-1/3 of candidates passing every det
# row, so a block clears the gate with ~0.9+ probability; the vmapped
# population cost at 64x64-MLP widths is marginal on the MXU.
export HETERO5_CANDIDATES=8
hetero5_stage() {
  # RESUME an interrupted block instead of retraining: the K-candidate
  # curriculum is the longest stage in the queue, and a tunnel drop
  # mid-train leaves a sweep_state_* population checkpoint behind (the
  # stage timeout kills the wrapper, so the partial state survives;
  # HeteroSweepTrainer restores it bit-exactly, incl. mid-stage).
  # Fresh starts wipe the dir (append-mode metrics: no cross-retry
  # mixing); an in-window train FAILURE (not a kill) also wipes, so a
  # corrupt/mismatched state can't wedge every future attempt.
  local resume_flag=""
  if ls logs/hetero5_tpu/sweep_state_*.msgpack >/dev/null 2>&1; then
    resume_flag="resume=true"
    echo "[hetero5] resuming interrupted candidate block"
  else
    rm -rf logs/hetero5_tpu
  fi
  # Round-5 recipe (VERDICT r4 next-#1, measured on CPU — see
  # docs/acceptance/hetero5/README.md): a 100-rollout fine-tune stage on
  # the final environment (spans a FULL 1000-step episode, so long-horizon
  # station-keeping is on-distribution) with the action noise annealed
  # out over the back half (log_std_final=-2.5, decay_start=0.5), the
  # entropy bonus annealed to 0, and the mixed stages REBALANCED to 2/3
  # N=5 formations (padded N=5 formations carry 1/4 the agent-transitions
  # of N=20 ones, so an even split lets the N=20-optimal collapse-at-goal
  # solution dominate the shared policy). Result: the DETERMINISTIC mode
  # action beats the scripted baseline in all three eval rows.
  #
  # Outcome quality is seed-variant (the CPU study measured ~1/3-1/2 of
  # seeds passing every det row), so ONE window trains a whole CANDIDATE
  # POPULATION — K seeds of the full curriculum as one vmapped program
  # (train/hetero_sweep.py) — and hetero5_eval selects the winner by
  # held-out deterministic evaluation. If EVERY candidate fails the
  # gate, the rotation counter advances by one and the next window
  # trains the next K-seed block (counter lives in the tracked
  # acceptance dir: /tmp wipes can't reset it onto known-failing
  # blocks; an infra failure — tunnel drop, timeout — retries the SAME
  # never-judged block).
  local attempt
  attempt=$(cat docs/acceptance/hetero5/seed_attempt 2>/dev/null || echo 0)
  echo "[hetero5] training candidate block $attempt" \
       "(seeds $((attempt * HETERO5_CANDIDATES))..$(((attempt + 1) * HETERO5_CANDIDATES - 1)))"
  # save_freq=500 (~every 50 rollouts): the default (10 vec-steps =
  # every rollout) would pay ~200 population device-pulls over the
  # tunnel, while too-sparse saves cost a dropped window more replayed
  # rollouts — 500 balances checkpoint overhead (~4 pulls) against the
  # resume anchor spacing.
  python train.py name=hetero5_tpu num_seeds="$HETERO5_CANDIDATES" \
    seed=$((attempt * HETERO5_CANDIDATES)) num_formation=64 \
    num_agents_per_formation=20 preset=tpu total_timesteps=2560000 \
    ent_coef_final=0.0 log_std_final=-2.5 log_std_decay_start=0.5 \
    use_wandb=false save_freq=500 $resume_flag \
    "curriculum=[{rollouts: 30, agent_counts: [5]}, {rollouts: 40, agent_counts: [5, 5, 20]}, {rollouts: 30, agent_counts: [5, 5, 20], num_obstacles: 4}, {rollouts: 100, agent_counts: [5, 5, 20], num_obstacles: 4}]" \
    || { rm -rf logs/hetero5_tpu; return 1; }
  # Platform gate only — the stamp means "candidates trained on the
  # chip". Banking (land_tpu_run) is DEFERRED to hetero5_eval's det
  # gate, so a rejected block's curve never overwrites the banked
  # record.
  # A platform-gate failure must ALSO wipe: a completed-on-CPU block's
  # sweep_state would otherwise make every future attempt a no-op
  # resume (all rollouts done) that re-fails this same gate forever.
  python - <<'EOF' || { rm -rf logs/hetero5_tpu; return 1; }
import json
snap = json.load(open("logs/hetero5_tpu/config.json"))
got = snap.get("resolved_platform")
assert got == "tpu", f"candidates trained on {got!r}, not tpu"
EOF
}
export -f hetero5_stage
stage hetero5 2700 hetero5_stage

# -- 8b. hetero5 eval-vs-baseline matrix (own stamp: a tunnel drop here
# must not force re-training the curriculum). Quality evals are
# platform-independent, and CPU-run evals of chip-trained checkpoints
# are the repo's accepted convention (ctde20/gnn100 record "eval CPU")
# — so unlike land_tpu_run this stage does NOT require tpu, but every
# banked record must CARRY its resolved_platform (the promote gate
# below rejects records whose provenance is absent). -------------------
hetero5_eval_stage() {
  # Completion guard, not just existence: sweep_summary.json is written
  # only when the population train() FINISHES — judging a
  # partially-trained block (timeout mid-curriculum leaves per-member
  # checkpoints behind) would advance the seed rotation on candidates
  # that were never fully trained.
  [ -f logs/hetero5_tpu/sweep_summary.json ] || return 1
  local n5="num_agents_per_formation=5"
  local n20="num_agents_per_formation=20"
  local obs="num_agents_per_formation=20 num_obstacles=4 obstacle_mode=fixed"
  local cfg dest best ckpt
  # 1. Candidate selection: evaluate.py's SWEEP mode ranks every member
  # of the candidate population on identical held-out states — one
  # process (one compile) per eval row, deterministic actions. A winner
  # must beat the baseline in ALL THREE det rows.
  local rank="python evaluate.py name=hetero5_tpu eval_formations=512"
  for spec in "n5:$n5" "n20:$n20" "n20_obs:$obs"; do
    cfg="${spec#*:}"
    dest="${spec%%:*}"
    eval "$rank $cfg" | tail -1 > "/tmp/h5rank_${dest}.json" || return 1
    # Stage the ranking for banking through the SAME two-pass
    # provenance gate as the matrix records (the eval_*.json.tmp glob
    # below matches it; rankings carry eval_deterministic /
    # beats_baseline / resolved_platform like every eval JSON).
    cp "/tmp/h5rank_${dest}.json" \
        "docs/acceptance/hetero5/eval_member_ranking_${dest}.json.tmp" \
        || return 1
  done
  best=$(python - <<'EOF'
import json
rows = [
    json.load(open(f"/tmp/h5rank_{n}.json"))
    for n in ("n5", "n20", "n20_obs")
]
passers = None
for r in rows:
    assert r.get("eval_deterministic") is True, r
    ok = {
        m for m, ret in r["member_returns"].items()
        if ret > r["baseline_return"]
    }
    passers = ok if passers is None else (passers & ok)
if not passers:
    print("NONE")
else:
    # Best by the historically-hard row (N=5 det).
    n5 = rows[0]["member_returns"]
    print(max(passers, key=lambda m: n5[m]))
EOF
  ) || return 1
  if [ "$best" = "NONE" ]; then
    echo "[hetero5_eval] no candidate beats the baseline in every det row"
    _hetero5_reseed
    return 1
  fi
  echo "[hetero5_eval] selected candidate: $best"
  ckpt=$(python - "$best" <<'EOF'
import sys
from marl_distributedformation_tpu.utils import latest_checkpoint
p = latest_checkpoint(f"logs/hetero5_tpu/{sys.argv[1]}")
assert p is not None
print(p)
EOF
  ) || return 1
  # 2. The full 2x3 record matrix on the WINNER's checkpoint (same
  # record shape every round has banked).
  local base="python evaluate.py checkpoint=$ckpt eval_formations=512"
  for spec in "n5:$n5" "n20:$n20" "n20_obs:$obs"; do
    cfg="${spec#*:}"
    dest="${spec%%:*}"
    eval "$base $cfg" | tail -1 \
        > "docs/acceptance/hetero5/eval_${dest}_det.json.tmp" || return 1
    eval "$base $cfg eval_deterministic=false" | tail -1 \
        > "docs/acceptance/hetero5/eval_${dest}_stoch.json.tmp" || return 1
  done
  python - <<'EOF'
import json, pathlib, sys
d = pathlib.Path("docs/acceptance/hetero5")
tmps = sorted(d.glob("eval_*.json.tmp"))
# Two passes: validate EVERYTHING, then rename — a gate failure on a
# later row must not have already banked earlier rows over the
# committed evidence (the whole point of the gate is that a failed
# retrain leaves the prior records standing).
for p in tmps:
    rec = json.loads(p.read_text())
    assert "eval_deterministic" in rec and "beats_baseline" in rec, p
    assert rec.get("resolved_platform"), f"no backend provenance: {p}"
    # Round-5 gate (VERDICT r4 next-#1 done-criterion): the
    # DETERMINISTIC mode action must beat the baseline in every det
    # row (stoch rows are recorded but not gated — the criterion is
    # about the mode action). Exit 3 = candidate REJECTED (quality),
    # distinct from infra failure: the caller must then unstamp the
    # training stage so the next window trains the next seed.
    if rec["eval_deterministic"] and not rec["beats_baseline"]:
        print(f"[hetero5_eval] GATE FAIL: mode loses to baseline: {p}")
        sys.exit(3)
for p in tmps:
    rec = json.loads(p.read_text())
    p.rename(p.with_suffix(""))  # strip .tmp -> eval_*.json, atomic
    print(
        f"[hetero5_eval] {p.stem}: beats_baseline={rec['beats_baseline']}"
        f" ({rec['resolved_platform']})"
    )
EOF
  local rc=$?
  if [ "$rc" -eq 3 ]; then
    # Safety net (selection above should make this unreachable): the
    # winner's banked records contradict the ranking. Treat as a
    # quality rejection.
    _hetero5_reseed
    return 1
  fi
  [ "$rc" -eq 0 ] || return "$rc"
  # Candidates ACCEPTED: bank the training record over the previous one
  # (deferred from hetero5_stage so rejected blocks never land). The
  # rankings already landed through the two-pass gate above; the
  # summary is a training artifact (platform proven by land_tpu_run's
  # config-snapshot check) — atomic tmp+mv like every banked file.
  cp logs/hetero5_tpu/sweep_summary.json \
      docs/acceptance/hetero5/sweep_summary_tpu.json.tmp \
      && mv docs/acceptance/hetero5/sweep_summary_tpu.json.tmp \
            docs/acceptance/hetero5/sweep_summary_tpu.json || return 1
  land_tpu_run hetero5_tpu docs/acceptance/hetero5 \
      "metrics_tpu.jsonl (population curve), sweep_summary_tpu.json, eval_member_ranking_*.json (det candidate selection), eval_*.json (winner's 2x3 matrix)"
}
# Quality rejection helper (NOT for infra failures): advance the
# candidate-block rotation and unstamp the training stage so the next
# window trains the next K-seed block. Only quality paths advance the
# counter — an interrupted block was never judged and must retry.
_hetero5_reseed() {
  local attempt
  attempt=$(cat docs/acceptance/hetero5/seed_attempt 2>/dev/null || echo 0)
  echo $((attempt + 1)) > docs/acceptance/hetero5/seed_attempt
  echo "[hetero5_eval] candidate block $attempt rejected; rotating"
  rm -f "$STATE/hetero5"
  # Clear the judged block's run dir: leaving its sweep_state behind
  # would make the next attempt RESUME the rejected block instead of
  # training the next seed block.
  rm -rf logs/hetero5_tpu
}
export -f _hetero5_reseed
export -f hetero5_eval_stage
stage hetero5_eval 1500 hetero5_eval_stage

# -- 9. sweep workflow acceptance on the chip ---------------------------
sweep8_stage() {
  rm -rf logs/sweep8_tpu  # append-mode metrics: no cross-retry mixing
  # ent_coef_final=0.0 (round 5): the round-4 population's late-training
  # decline traces to the constant entropy bonus inflating log_std all
  # run (entropy 2.85 -> 3.16, per-dim std > 1 = near-uniform actions);
  # annealing the bonus holds entropy flat. Root-cause analysis with CPU
  # repro curves: docs/acceptance/sweep8/REGRESSION.md.
  python train.py name=sweep8_tpu num_seeds=8 \
    num_formation=16 num_agents_per_formation=3 \
    strict_parity=false max_steps=64 \
    n_steps=16 batch_size=192 n_epochs=4 ent_coef_final=0.0 \
    total_timesteps=153600 save_freq=3200 use_wandb=false || return 1
  python evaluate.py name=sweep8_tpu num_formation=16 \
    num_agents_per_formation=3 strict_parity=false max_steps=64 \
    | tee /tmp/eval_sweep8.txt || return 1
  tail -1 /tmp/eval_sweep8.txt > /tmp/eval_sweep8.json || return 1
  # The eval is its own process: it must prove ITS backend too (the
  # tunnel can drop between train and eval; evaluate.py stamps
  # resolved_platform into its JSON line).
  python - <<'EOF' || return 1
import json
rec = json.load(open("/tmp/eval_sweep8.json"))
assert rec.get("sweep_members") == 8, rec
assert "beats_baseline" in rec, rec
assert rec.get("resolved_platform") == "tpu", rec.get("resolved_platform")
EOF
  cp logs/sweep8_tpu/sweep_summary.json \
      docs/acceptance/sweep8/sweep_summary_tpu.json || return 1
  cp /tmp/eval_sweep8.json \
      docs/acceptance/sweep8/eval_all_members_tpu.json || return 1
  land_tpu_run sweep8_tpu docs/acceptance/sweep8 \
      "metrics_tpu.jsonl, sweep_summary_tpu.json, eval_all_members_tpu.json (all 8 members vs baseline/zero on 1024 held-out formations)"
}
export -f sweep8_stage
stage sweep8 1800 sweep8_stage

echo "== window pass complete $(date -u +%Y-%m-%dT%H:%M:%SZ); state: =="
ls "$STATE"

# Sentinel for the watchdog: the stage list lives only in THIS file, so
# done-ness is decided here, not by a drifting copy in the watchdog.
done=1
for s in "${ALL_STAGES[@]}"; do
  [ -f "$STATE/$s" ] || done=0
done
if [ "$done" -eq 1 ]; then
  touch "$STATE/ALL_DONE"
  echo "== ALL stages stamped =="
else
  # A grown stage list (or a deliberately un-stamped stage) must reopen
  # the queue: a stale ALL_DONE would short-circuit every watchdog tick
  # and the new stage would silently never run.
  rm -f "$STATE/ALL_DONE"
fi
