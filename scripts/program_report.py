#!/usr/bin/env python
"""Render a program-ledger census (obs/ledger.py) as a cost report.

    python scripts/program_report.py logs/run/program_ledger.json
    python scripts/program_report.py logs/run/program_ledger.json --json
    python scripts/program_report.py --log-dir logs/run --top 5

The census is the per-executable record every compile site registers
into the ProgramLedger (cost_analysis flops/bytes, memory footprint,
build timings, dispatch-latency summaries); entry points dump it to
``logs/{name}/program_ledger.json``. This report answers the operator
questions directly: which programs dominate flops, bytes, compile wall,
and dispatch tail latency — text tables by default, one JSON object
with ``--json`` (stable keys: ``totals``, ``top``, ``programs``).

``scripts/check_bench_record.py --census`` is the companion GATE (diff
a committed census against a live one); this script is the human view.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from marl_distributedformation_tpu.obs.ledger import (  # noqa: E402
    load_census,
)

# (column header, census field, unit divisor, unit suffix)
RANKINGS = (
    ("flops", "flops", 1e6, "Mflop"),
    ("bytes", "bytes_accessed", 1e6, "MB"),
    ("compile", "compile_seconds", 1.0, "s"),
    ("dispatch_p95", "dispatch_seconds_p95", 1e-3, "ms"),
)


def _num(value) -> float:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return float("-inf")
    return v


def rank(programs: list, field: str, top: int) -> list:
    """Programs carrying ``field``, largest first (absent fields sort
    out, never crash — CPU records legitimately lack memory facts)."""
    present = [p for p in programs if _num(p.get(field)) > float("-inf")]
    present.sort(key=lambda p: _num(p.get(field)), reverse=True)
    return present[:top]


def summarize(census: dict, top: int) -> dict:
    programs = list(census.get("programs") or [])
    out = {
        "schema": census.get("schema"),
        "totals": dict(census.get("totals") or {}),
        "program_count": len(programs),
        "top": {
            name: [
                {"key": p.get("key"), name: p.get(field)}
                for p in rank(programs, field, top)
            ]
            for name, field, _, _ in RANKINGS
        },
        "programs": programs,
    }
    return out


def render_text(census: dict, top: int) -> str:
    programs = list(census.get("programs") or [])
    totals = census.get("totals") or {}
    lines = [
        f"program ledger census — {len(programs)} programs, "
        f"{totals.get('traces', '?')} compiles, "
        f"{_fmt(totals.get('compile_seconds'), 1.0, 's')} total compile",
    ]
    wm = totals.get("watermark_bytes")
    if wm is not None:
        lines.append(
            f"device-memory watermark: {_fmt(wm, 1e6, 'MB')}"
        )
    for name, field, div, unit in RANKINGS:
        ranked = rank(programs, field, top)
        if not ranked:
            lines.append(f"\ntop by {name}: (no {field} recorded)")
            continue
        lines.append(f"\ntop by {name}:")
        width = max(len(str(p.get("key"))) for p in ranked)
        for p in ranked:
            src = p.get("analysis_source", "?")
            lines.append(
                f"  {str(p.get('key')).ljust(width)}  "
                f"{_fmt(p.get(field), div, unit).rjust(12)}  "
                f"[{p.get('subsystem', '?')}, {src}]"
            )
    unavailable = [
        p["key"]
        for p in programs
        if p.get("analysis_source") == "unavailable"
    ]
    if unavailable:
        lines.append(
            "\ncost/memory analysis unavailable for: "
            + ", ".join(str(k) for k in unavailable)
        )
    return "\n".join(lines)


def _fmt(value, div: float, unit: str) -> str:
    try:
        return f"{float(value) / div:,.2f} {unit}"
    except (TypeError, ValueError):
        return "n/a"


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "census", nargs="?", type=Path,
        help="path to a program_ledger.json census",
    )
    ap.add_argument(
        "--log-dir", type=Path, default=None,
        help="read {log-dir}/program_ledger.json instead",
    )
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument(
        "--json", action="store_true",
        help="emit the structured summary as one JSON object",
    )
    args = ap.parse_args()
    if args.census is None and args.log_dir is None:
        ap.error("give a census path or --log-dir")
    path = args.census or (args.log_dir / "program_ledger.json")
    try:
        census = load_census(path)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"[program_report] cannot read {path}: {e}", file=sys.stderr)
        sys.exit(1)
    if args.json:
        print(json.dumps(summarize(census, args.top)))
    else:
        print(render_text(census, args.top))


if __name__ == "__main__":
    main()
