#!/usr/bin/env python
"""Record Trainer.profile_breakdown() on the real chip at the north-star
shape, for parity and TPU-tuned hyperparameters (docs/profiling.md table;
VERDICT.md r2 next-#3).

Run: python scripts/tpu_profile_breakdown.py [M]
Prints two markdown table rows + a JSON line.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

# Standalone-invocation bootstrap: `python scripts/tpu_profile_breakdown.py`
# puts scripts/ (not the repo root) on sys.path, and the package may not be
# pip-installed on a fresh machine.
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> None:
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 4096

    import jax

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import TrainConfig, Trainer
    from marl_distributedformation_tpu.utils.config import PRESETS

    device = jax.devices()[0].device_kind
    rows = {}
    tuned_batch = PRESETS["tpu"]["batch_size"]
    for label, ppo in (
        ("parity (batch=64)", PPOConfig()),
        (
            f"preset=tpu (batch={tuned_batch})",
            PPOConfig(batch_size=tuned_batch),
        ),
    ):
        trainer = Trainer(
            EnvParams(num_agents=5),
            ppo=ppo,
            config=TrainConfig(
                num_formations=m, checkpoint=False, name="profile"
            ),
        )
        b = trainer.profile_breakdown(iters=5)
        rows[label] = b
        rate = ppo.n_steps * m / b["total"]
        print(
            f"| M={m} {label} | {b['total']*1e3:,.1f} ms | "
            f"{b['env']*1e3:,.1f} ms | {b['policy']*1e3:,.1f} ms | "
            f"{b['update']*1e3:,.1f} ms | {b['frac_update']*100:.1f}% | "
            f"{rate:,.0f} |"
        )
    print(json.dumps({"device": device, "m": m, "breakdown": rows}))


if __name__ == "__main__":
    main()
