#!/usr/bin/env python
"""Worst-case severity search: minimal-severity falsifiers, one JSON.

Attack a run's checkpoints with the grid-refine falsifier search
(``scenarios/adversary.py``, docs/adversarial.md): per scenario family,
find the SMALLEST severity at which the policy's return drops more than
``drop_tolerance`` (relative) below its own clean cell. Every search
generation is ONE vmapped compiled eval over the whole candidate
population — model params and scenario knobs both traced, so the
program compiles exactly once across every generation AND every
checkpoint (budget-1 RetraceGuard receipt recorded in the report).

Usage (same key=value CLI as every entry point):
    python scripts/adversarial_search.py name=myrun
    python scripts/adversarial_search.py name=myrun \\
        scenarios=[wind,storm] drop_tolerance=0.15 max_severity=2 \\
        search_grid=6 search_generations=5 eval_formations=64
    python scripts/adversarial_search.py checkpoint=logs/x/rl_model_200_steps.msgpack

Writes ``logs/{name}/falsifiers.json`` (per-checkpoint falsifier
reports, schema-stamped) plus the same report as one JSON line on
stdout. The falsifier records feed straight into
``scenarios.from_falsifiers`` (an auto-curriculum training stage) and
match what the promotion gate's adversarial rung logs to
``promotions.jsonl``. Unknown scenario names and mistyped config keys
fail fast naming the valid entries.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from marl_distributedformation_tpu.utils import (  # noqa: E402
    env_params_from_config,
    load_config,
    repo_root,
    setup_platform,
    validate_override_keys,
)

SEARCH_KEYS = (
    "checkpoint",
    "search_checkpoints",
    "drop_tolerance",
    "max_severity",
    "search_grid",
    "search_generations",
    "search_resolution",
    "eval_formations",
    "eval_seed",
    "eval_deterministic",
    "out",
)


def _checkpoints(cfg) -> list:
    """Explicit ``checkpoint=`` (one path or a YAML list), else the last
    ``search_checkpoints`` (default 1) of the named run."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        checkpoint_step,
    )

    explicit = cfg.get("checkpoint")
    if explicit:
        paths = explicit if isinstance(explicit, list) else [explicit]
        return [str(p) for p in paths]
    log_dir = repo_root() / "logs" / str(cfg.name)
    ckpts = sorted(
        log_dir.glob("rl_model_*_steps.*"), key=checkpoint_step
    )
    if not ckpts:
        raise SystemExit(
            f"no checkpoints under {log_dir}; pass checkpoint=... or "
            "name=<trained run>"
        )
    keep = max(1, int(cfg.get("search_checkpoints", 1)))
    return [str(p) for p in ckpts[-keep:]]


def _scenarios(cfg) -> tuple:
    from marl_distributedformation_tpu.scenarios import get_scenario

    raw = cfg.get("scenarios")
    if not raw:
        return ()  # AdversaryConfig default: every family except clean
    names = raw if isinstance(raw, list) else [raw]
    try:
        return tuple(get_scenario(str(n)).name for n in names)
    except ValueError as e:  # unknown name -> clean CLI error w/ registry
        raise SystemExit(str(e)) from e


def main(argv=None) -> dict:
    overrides = sys.argv[1:] if argv is None else argv
    validate_override_keys(overrides, extra_keys=SEARCH_KEYS)
    cfg = load_config(overrides)
    setup_platform(cfg.get("platform"))

    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.scenarios import (
        AdversaryConfig,
        AdversarySearch,
    )
    from marl_distributedformation_tpu.scenarios.adversary import (
        FALSIFIERS_SCHEMA,
    )

    params = env_params_from_config(cfg)
    checkpoints = _checkpoints(cfg)
    search_cfg = AdversaryConfig(
        scenarios=_scenarios(cfg),
        drop_tolerance=float(cfg.get("drop_tolerance", 0.2)),
        max_severity=float(cfg.get("max_severity", 1.5)),
        grid=int(cfg.get("search_grid", 6)),
        generations=int(cfg.get("search_generations", 4)),
        resolution=float(cfg.get("search_resolution", 0.02)),
        num_formations=int(cfg.get("eval_formations", 64)),
        seed=int(cfg.get("eval_seed", 1234)),
        deterministic=bool(cfg.get("eval_deterministic", True)),
    )

    policies = [
        LoadedPolicy.from_checkpoint(
            str(p), act_dim=params.act_dim, env_params=params
        )
        for p in checkpoints
    ]
    search = AdversarySearch(policies[0].model, params, search_cfg)
    # Validate EVERY architecture before the first eval, so a mismatched
    # file fails the run up front, by name (the matrix CLI's rule).
    for path, pol in zip(checkpoints, policies):
        search.check_params(pol.params, origin=str(path))

    searches = {}
    for path, pol in zip(checkpoints, policies):
        searches[str(path)] = search.search(pol.params, origin=str(path))

    report = {
        "schema": FALSIFIERS_SCHEMA,
        "name": str(cfg.name),
        "checkpoints": checkpoints,
        "scenarios": list(search.specs and [s.name for s in search.specs]),
        "drop_tolerance": search_cfg.drop_tolerance,
        "max_severity": search_cfg.max_severity,
        "num_agents": params.num_agents,
        "eval_formations": search_cfg.num_formations,
        "seed": search_cfg.seed,
        "searches": searches,
        "eval_compiles": search.compile_count,
        "candidates_per_sec": round(search.candidates_per_sec(), 1),
    }
    try:
        import jax

        dev = jax.devices()[0]
        report["resolved_platform"] = dev.platform
        report["resolved_device"] = dev.device_kind
    except Exception:  # noqa: BLE001 — provenance never kills a report
        pass

    # Human-readable slice: the minimal break point per checkpoint.
    print(
        f"[adversary] {len(checkpoints)} checkpoints x "
        f"{len(search.specs)} scenario families, "
        f"M={search_cfg.num_formations}, "
        f"compiles={report['eval_compiles']}, "
        f"{report['candidates_per_sec']:,.0f} candidates/s"
    )
    for ckpt, rep in searches.items():
        fals = {
            f["scenario"]: f["severity"] for f in rep["falsifiers"]
        }
        print(
            f"[adversary] {Path(ckpt).name}: falsified "
            f"{json.dumps(fals)} robust {rep['robust']} "
            f"({rep['generations']} generations)"
        )

    out = cfg.get("out") or str(
        repo_root() / "logs" / str(cfg.name) / "falsifiers.json"
    )
    Path(out).parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    report["out"] = str(out)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
