#!/usr/bin/env python
"""Validate a bench JSON line as committable chip evidence.

The chip-window burster stamps a stage only when its bench record is
real hardware evidence. Each stage needs the same gate — no CPU
fallback, no watchdog error, no degraded ("skipped"/"failed") phases —
plus a per-stage list of required rate fields. This is that gate in ONE
place, so the acceptance criteria cannot drift between stages:

    python scripts/check_bench_record.py /tmp/bench_tpu.json \
        --require train_env_steps_per_sec knn_env_steps_per_sec \
        --expect knn_impl=pallas

Exit 0 iff the record passes. ``--require F`` asserts float(rec[F]) > 0;
``--expect K=V`` asserts str(rec[K]) == V. Input parsing is shared with
scripts/mirror_bench.py (bench.py stdout or a driver BENCH_r*.json
wrapper), so the gate and the mirror can never disagree on a file.

Census mode — the chip-window acceptance gate for the program ledger
(obs/ledger.py, ROADMAP item 5):

    python scripts/check_bench_record.py COMMITTED_census.json \
        --census logs/run/program_ledger.json [--census-tolerance 0.25]

diffs a COMMITTED census (the positional file) against the LIVE one a
fresh run just wrote: programs that vanished or appeared, and
flops/bytes/memory-footprint drift past the tolerance, are rejections —
a chip re-measure must attribute every cost change, not discover it in
a throughput regression later.
"""

from __future__ import annotations

import argparse
import math
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from mirror_bench import _load_record as load_record  # noqa: E402

# bench.py writes this sentinel into the rate fields of phases disabled
# by BENCH_SKIP_* env vars — "explicitly not run", distinct from both a
# healthy number and a silently-absent field. Structural validators
# treat sentinel fields as absent; --require rejects them with a message
# that says WHY the field is empty.
SKIPPED = "skipped"


def _present(rec: dict, key: str):
    """Field value, with None for both absent and explicitly-skipped."""
    v = rec.get(key)
    return None if v == SKIPPED else v


def _pipeline_problems(rec: dict) -> list[str]:
    """Structural validation of the always-learning pipeline fields
    (bench phase 7): whenever a record carries them, they must be
    internally consistent — a latency percentile pair that is not a
    percentile pair, or a gate that compiled more than once, is a
    malformed record regardless of which stage required the fields."""
    problems = []
    p50 = _present(rec, "promotion_latency_s_p50")
    p95 = _present(rec, "promotion_latency_s_p95")
    if (p50 is None) != (p95 is None):
        problems.append(
            "promotion_latency_s_p50/p95 must be recorded together"
        )
    if p50 is not None and p95 is not None:
        try:
            p50, p95 = float(p50), float(p95)
            if not 0.0 < p50 <= p95:
                problems.append(
                    f"promotion latency percentiles malformed: "
                    f"p50={p50} p95={p95} (need 0 < p50 <= p95)"
                )
        except (TypeError, ValueError):
            problems.append("promotion latency fields are not numbers")
        gate = rec.get("gate_eval_steps_per_sec")
        try:
            gate_ok = gate is not None and float(gate) > 0.0
        except (TypeError, ValueError):
            gate_ok = False
        if not gate_ok:
            problems.append(
                f"gate_eval_steps_per_sec missing/zero/non-numeric "
                f"beside promotion latency: {gate!r}"
            )
        compiles = rec.get("pipeline_gate_compiles")
        if compiles != 1:
            problems.append(
                f"pipeline_gate_compiles={compiles!r} — the gate's eval "
                "program must compile exactly once across all candidates"
            )
        rung = rec.get("pipeline_serving_max_compiles_per_rung")
        try:
            rung_ok = rung is None or int(rung) <= 1
        except (TypeError, ValueError):
            rung_ok = False
        if not rung_ok:
            problems.append(
                f"pipeline_serving_max_compiles_per_rung={rung!r} "
                "(need an int <= 1)"
            )
    return problems


def _obs_problems(rec: dict) -> list[str]:
    """Structural validation of the obs tracing fields (bench phase 8):
    a tracing overhead that is not a finite number, or a promotion span
    breakdown whose stages overshoot the latency they decompose, is a
    malformed record."""
    problems = []
    pct = _present(rec, "tracing_overhead_pct")
    if pct is not None:
        try:
            if not math.isfinite(float(pct)):
                problems.append(
                    f"tracing_overhead_pct not finite: {pct!r}"
                )
        except (TypeError, ValueError):
            problems.append(
                f"tracing_overhead_pct is not a number: {pct!r}"
            )
    breakdown = rec.get("promotion_span_breakdown")
    if breakdown is not None:
        if not isinstance(breakdown, dict) or not breakdown:
            problems.append(
                f"promotion_span_breakdown must be a non-empty dict of "
                f"stage->seconds: {breakdown!r}"
            )
            return problems
        try:
            stages = {str(k): float(v) for k, v in breakdown.items()}
        except (TypeError, ValueError):
            problems.append(
                f"promotion_span_breakdown has non-numeric stages: "
                f"{breakdown!r}"
            )
            return problems
        bad = {k: v for k, v in stages.items() if v < 0.0}
        if bad:
            problems.append(
                f"promotion_span_breakdown stages negative: {bad!r}"
            )
        # The stage p50s decompose the promotion latency: their sum may
        # not exceed the recorded p95 by more than clock-noise tolerance
        # (stages summing PAST the latency they claim to explain means
        # the decomposition double-counts). deferred_wait_s is excluded:
        # it exists only on deferred promotions, so its p50 conditions
        # on a different promotion subset than the latency percentile —
        # a handful of long defers among many fast promotions would push
        # the sum past a p95 that legitimately never saw them.
        p95 = rec.get("promotion_latency_s_p95")
        try:
            p95 = float(p95) if p95 is not None else None
        except (TypeError, ValueError):
            p95 = None  # already reported by _pipeline_problems
        if p95 is not None:
            total = sum(
                v for k, v in stages.items() if k != "deferred_wait_s"
            )
            tolerance = max(0.5, 0.1 * p95)
            if total > p95 + tolerance:
                problems.append(
                    f"promotion_span_breakdown sums to {total:.3f}s, "
                    f"exceeding promotion_latency_s_p95={p95:.3f}s "
                    f"+ tolerance {tolerance:.3f}s"
                )
    return problems


def _telemetry_problems(rec: dict) -> list[str]:
    """Structural validation of the live-metrics-plane fields (bench
    phase 11): a telemetry overhead that is not a finite number, or a
    sentinel poll rate that is zero/negative, is a malformed record
    whenever present."""
    problems = []
    pct = _present(rec, "telemetry_overhead_pct")
    if pct is not None:
        try:
            if not math.isfinite(float(pct)):
                problems.append(
                    f"telemetry_overhead_pct not finite: {pct!r}"
                )
        except (TypeError, ValueError):
            problems.append(
                f"telemetry_overhead_pct is not a number: {pct!r}"
            )
    rate = _present(rec, "sentinel_checks_per_sec")
    if rate is not None:
        try:
            if not float(rate) > 0.0:
                problems.append(
                    f"sentinel_checks_per_sec={rate!r} (need > 0)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"sentinel_checks_per_sec is not a number: {rate!r}"
            )
    return problems


def _serving_slo_problems(rec: dict) -> list[str]:
    """Structural validation of the SLO serving fields (bench phase 9):
    whenever a record carries the req/s-at-SLO headline, the load-gen
    rate and both 512-rung percentiles must be positive numbers, the
    bf16 delta a finite number, and the compile receipts budget-1."""
    problems = []
    rate = _present(rec, "serving_req_per_sec_at_p95_slo")
    if rate is None:
        return problems
    try:
        if not float(rate) > 0.0:
            problems.append(
                f"serving_req_per_sec_at_p95_slo={rate!r} (need > 0: a "
                "0 rate means even the lowest probe violated the SLO)"
            )
    except (TypeError, ValueError):
        problems.append(
            f"serving_req_per_sec_at_p95_slo is not a number: {rate!r}"
        )
    for key in (
        "serving_sharded_512_p95_ms",
        "serving_replicated_512_p95_ms",
    ):
        v = _present(rec, key)
        try:
            ok = v is not None and float(v) > 0.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            problems.append(
                f"{key}={v!r} beside the SLO rate (need a positive p95)"
            )
    bf16 = _present(rec, "serving_bf16_speedup_pct")
    try:
        bf16_ok = bf16 is not None and math.isfinite(float(bf16))
    except (TypeError, ValueError):
        bf16_ok = False
    if not bf16_ok:
        problems.append(
            f"serving_bf16_speedup_pct={bf16!r} (need a finite number; "
            "negative is legitimate on CPU)"
        )
    receipts = _present(rec, "serving_slo_max_compiles_per_rung")
    if receipts != 1:
        problems.append(
            f"serving_slo_max_compiles_per_rung={receipts!r} — every "
            "rung (sharded and bf16 included) must compile exactly once"
        )
    return problems


def _adversarial_problems(rec: dict) -> list[str]:
    """Structural validation of the adversarial-robustness fields (bench
    phase 10): whenever a record carries the search throughput, the
    compile receipt must be budget-1 and the worst-case gap a finite
    number (NEGATIVE is legitimate — at bench-sized training budgets the
    curriculum payoff is directional, and an honest record keeps the
    sign it measured)."""
    problems = []
    rate = _present(rec, "adversarial_candidates_per_sec")
    if rate is not None:
        try:
            if not float(rate) > 0.0:
                problems.append(
                    f"adversarial_candidates_per_sec={rate!r} (need > 0)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"adversarial_candidates_per_sec is not a number: {rate!r}"
            )
        compiles = _present(rec, "adversarial_search_compiles")
        if compiles != 1:
            problems.append(
                f"adversarial_search_compiles={compiles!r} — the "
                "falsifier search's population program must compile "
                "exactly once across every generation and checkpoint"
            )
    gap = _present(rec, "worst_case_return_gap_pct")
    if gap is not None:
        try:
            if not math.isfinite(float(gap)):
                problems.append(
                    f"worst_case_return_gap_pct not finite: {gap!r}"
                )
        except (TypeError, ValueError):
            problems.append(
                f"worst_case_return_gap_pct is not a number: {gap!r}"
            )
    return problems


def _chaos_problems(rec: dict) -> list[str]:
    """Structural validation of the chaos-plane fields (bench phase
    12): whenever present, invariant violations must be exactly 0 (a
    nonzero count is a broken recovery story, not a slow one), MTTR a
    finite positive number, and the disabled-plane overhead a finite
    number under the 5% bar (the plane is one attribute read when
    disabled — anything near the bar means injection leaked into a hot
    path). ``"skipped"`` sentinels are honored as structurally
    absent."""
    problems = []
    violations = _present(rec, "chaos_invariant_violations")
    if violations is not None:
        try:
            if int(violations) != 0:
                problems.append(
                    f"chaos_invariant_violations={violations!r} — a "
                    "campaign with ANY invariant violation is a broken "
                    "recovery path, not evidence"
                )
        except (TypeError, ValueError):
            problems.append(
                f"chaos_invariant_violations is not an int: {violations!r}"
            )
    mttr = _present(rec, "chaos_mttr_s")
    if mttr is not None:
        try:
            v = float(mttr)
            if not math.isfinite(v) or v <= 0.0:
                problems.append(
                    f"chaos_mttr_s={mttr!r} (need a finite number > 0: "
                    "zero means no disruptive fault was actually "
                    "recovered from)"
                )
        except (TypeError, ValueError):
            problems.append(f"chaos_mttr_s is not a number: {mttr!r}")
    overhead = _present(rec, "fault_plane_overhead_pct")
    if overhead is not None:
        try:
            v = float(overhead)
            if not math.isfinite(v):
                problems.append(
                    f"fault_plane_overhead_pct not finite: {overhead!r}"
                )
            elif v >= 5.0:
                problems.append(
                    f"fault_plane_overhead_pct={v} breaches the 5% bar "
                    "— the disabled plane must cost one attribute read"
                )
        except (TypeError, ValueError):
            problems.append(
                f"fault_plane_overhead_pct is not a number: {overhead!r}"
            )
    return problems


def _recovery_problems(rec: dict) -> list[str]:
    """Structural validation of the train-lane recovery fields (bench
    phase 15), whenever present: the in-program health word's overhead
    must be a finite number under the 5% bar (it is a handful of
    reductions + selects fused into a program that already runs a full
    PPO update), recovery MTTR a finite positive number (zero means no
    divergence was actually recovered from), and the drill's divergence
    count >= 1 (the bench INJECTS a bomb — a zero count is a broken
    detector, not a clean run). ``"skipped"`` sentinels are honored as
    structurally absent."""
    problems = []
    overhead = _present(rec, "health_overhead_pct")
    if overhead is not None:
        try:
            v = float(overhead)
            if not math.isfinite(v):
                problems.append(
                    f"health_overhead_pct not finite: {overhead!r}"
                )
            elif v >= 5.0:
                problems.append(
                    f"health_overhead_pct={v} breaches the 5% bar — "
                    "the health word must stay a few fused reductions "
                    "and selects, not a program of its own"
                )
        except (TypeError, ValueError):
            problems.append(
                f"health_overhead_pct is not a number: {overhead!r}"
            )
    mttr = _present(rec, "recovery_mttr_s")
    if mttr is not None:
        try:
            v = float(mttr)
            if not math.isfinite(v) or v <= 0.0:
                problems.append(
                    f"recovery_mttr_s={mttr!r} (need a finite number "
                    "> 0: zero means the drill's bomb was never "
                    "recovered from)"
                )
        except (TypeError, ValueError):
            problems.append(f"recovery_mttr_s is not a number: {mttr!r}")
    events = _present(rec, "train_divergence_events")
    if events is not None:
        try:
            if int(events) < 1:
                problems.append(
                    f"train_divergence_events={events!r} — the drill "
                    "injects a bomb, so a measured run must detect at "
                    "least one sustained breach"
                )
        except (TypeError, ValueError):
            problems.append(
                f"train_divergence_events is not an int: {events!r}"
            )
    return problems


LINT_WALL_CEILING_S = 120.0


def _lint_problems(rec: dict) -> list[str]:
    """Structural validation of the graftlint field (bench phase 16),
    whenever present: one cold-process ``--check`` pass over the
    package must be a finite positive wall under the ceiling. The
    engine's whole-repo analyses (lock-edge DFS, guarded-write reach)
    are package-global — this is the tripwire that keeps them from
    quietly going super-linear as the repo grows (measured wall is a
    few seconds; the ceiling leaves ~25x headroom for slow CI hosts).
    ``"skipped"`` sentinels are honored as structurally absent."""
    problems = []
    wall = _present(rec, "graftlint_wall_s")
    if wall is not None:
        try:
            v = float(wall)
            if not math.isfinite(v) or v <= 0.0:
                problems.append(
                    f"graftlint_wall_s={wall!r} (need a finite number "
                    "> 0)"
                )
            elif v > LINT_WALL_CEILING_S:
                problems.append(
                    f"graftlint_wall_s={v} breaches the "
                    f"{LINT_WALL_CEILING_S:.0f}s ceiling — a package-"
                    "global analysis in the call-graph engine has "
                    "gone super-linear"
                )
        except (TypeError, ValueError):
            problems.append(
                f"graftlint_wall_s is not a number: {wall!r}"
            )
    return problems


def _ledger_problems(rec: dict) -> list[str]:
    """Structural validation of the program-ledger fields (bench phase
    13), whenever present: the enabled-ledger overhead must be a finite
    number under the 5% bar (dispatch recording is a perf_counter pair
    plus a shard append), the census must carry at least one program (a
    zero count means registration silently broke at every compile
    site), and the total compile seconds must be a finite non-negative
    number. ``"skipped"`` sentinels are honored as structurally
    absent."""
    problems = []
    overhead = _present(rec, "ledger_overhead_pct")
    if overhead is not None:
        try:
            v = float(overhead)
            if not math.isfinite(v):
                problems.append(
                    f"ledger_overhead_pct not finite: {overhead!r}"
                )
            elif v >= 5.0:
                problems.append(
                    f"ledger_overhead_pct={v} breaches the 5% bar — "
                    "dispatch recording must stay a perf_counter pair "
                    "plus a per-thread shard append"
                )
        except (TypeError, ValueError):
            problems.append(
                f"ledger_overhead_pct is not a number: {overhead!r}"
            )
    count = _present(rec, "ledger_program_count")
    if count is not None:
        try:
            if int(count) <= 0:
                problems.append(
                    f"ledger_program_count={count!r} — a measured run "
                    "with zero registered programs means the compile-"
                    "seam registration is broken, not that nothing "
                    "compiled"
                )
        except (TypeError, ValueError):
            problems.append(
                f"ledger_program_count is not an int: {count!r}"
            )
    compile_s = _present(rec, "ledger_compile_seconds_total")
    if compile_s is not None:
        try:
            v = float(compile_s)
            if not math.isfinite(v) or v < 0.0:
                problems.append(
                    f"ledger_compile_seconds_total={compile_s!r} "
                    "(need a finite number >= 0)"
                )
        except (TypeError, ValueError):
            problems.append(
                "ledger_compile_seconds_total is not a number: "
                f"{compile_s!r}"
            )
    return problems


# -- census diff mode (the program-ledger acceptance gate) ---------------

# Structural cost/memory facts whose drift the census gate bounds.
# Build timings are deliberately excluded: compile wall is environment-
# dependent and the RegressionSentinel already watches it live.
CENSUS_DRIFT_FIELDS = (
    "flops",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
)


def _census_index(census: dict) -> dict:
    """Programs grouped by dispatch key (stable across replica-suffixed
    entry keys): dispatch_key -> {count, max-per-field}."""
    index: dict = {}
    for prog in census.get("programs") or []:
        key = prog.get("dispatch_key") or prog.get("key")
        if key is None:
            continue
        slot = index.setdefault(key, {"count": 0})
        slot["count"] += 1
        for field in CENSUS_DRIFT_FIELDS:
            try:
                v = float(prog.get(field))
            except (TypeError, ValueError):
                continue
            if field not in slot or v > slot[field]:
                slot[field] = v
    return index


def census_diff(
    committed: dict, live: dict, tolerance: float = 0.25
) -> list[str]:
    """Violations of the live census against the committed one: new or
    vanished programs, and per-field relative drift past ``tolerance``.
    Empty list == the run's compiled-program population still matches
    the committed cost story."""
    problems = []
    committed_idx = _census_index(committed)
    live_idx = _census_index(live)
    for key in sorted(set(committed_idx) - set(live_idx)):
        problems.append(
            f"program vanished from the live census: {key} (committed "
            "record has it — a compile site stopped registering or a "
            "subsystem stopped compiling)"
        )
    for key in sorted(set(live_idx) - set(committed_idx)):
        problems.append(
            f"new program not in the committed census: {key} (commit "
            "an updated census if the addition is intentional)"
        )
    for key in sorted(set(committed_idx) & set(live_idx)):
        ref, cur = committed_idx[key], live_idx[key]
        if ref["count"] != cur["count"]:
            problems.append(
                f"{key}: program count changed ({ref['count']} "
                f"committed -> {cur['count']} live) — a replica or "
                "compile site stopped (or started) registering under "
                "this dispatch key"
            )
        for field in CENSUS_DRIFT_FIELDS:
            a, b = ref.get(field), cur.get(field)
            if a is None or b is None or a <= 0.0:
                continue
            drift = abs(b - a) / a
            if drift > tolerance:
                problems.append(
                    f"{key}: {field} drifted {drift * 100.0:.0f}% "
                    f"({a:,.0f} committed -> {b:,.0f} live; tolerance "
                    f"{tolerance * 100.0:.0f}%)"
                )
    return problems


def _mesh_problems(rec: dict) -> list[str]:
    """Structural validation of the mesh-tier fields (bench phase 14),
    whenever present: throughput a finite positive number; global-swap
    latency percentiles finite, positive, and ordered (p50 <= p95);
    ``mesh_failover_lost_requests`` EXACTLY 0 (losing an accepted
    request across a host kill is a broken failover story, not a slow
    one); and every per-host compile receipt at most 1 (the budget-1
    invariant restated per host). ``"skipped"`` sentinels are honored
    as structurally absent."""
    problems = []
    rate = _present(rec, "mesh_req_per_sec")
    if rate is not None:
        try:
            v = float(rate)
            if not math.isfinite(v) or v <= 0.0:
                problems.append(
                    f"mesh_req_per_sec={rate!r} (need a finite number "
                    "> 0 — a zero-throughput mesh measured nothing)"
                )
        except (TypeError, ValueError):
            problems.append(f"mesh_req_per_sec is not a number: {rate!r}")
    p50 = _present(rec, "mesh_global_swap_latency_s_p50")
    p95 = _present(rec, "mesh_global_swap_latency_s_p95")
    for name, value in (
        ("mesh_global_swap_latency_s_p50", p50),
        ("mesh_global_swap_latency_s_p95", p95),
    ):
        if value is None:
            continue
        try:
            v = float(value)
            if not math.isfinite(v) or v <= 0.0:
                problems.append(
                    f"{name}={value!r} (need a finite number > 0: a "
                    "global swap crosses at least one RPC round trip)"
                )
        except (TypeError, ValueError):
            problems.append(f"{name} is not a number: {value!r}")
    if p50 is not None and p95 is not None:
        try:
            if float(p50) > float(p95):
                problems.append(
                    f"mesh swap p50 {p50!r} > p95 {p95!r} — percentile "
                    "order violated"
                )
        except (TypeError, ValueError):
            pass  # already reported above
    lost = _present(rec, "mesh_failover_lost_requests")
    if lost is not None:
        try:
            if int(lost) != 0:
                problems.append(
                    f"mesh_failover_lost_requests={lost!r} — an "
                    "accepted request lost across a host kill is a "
                    "broken no-request-lost invariant, not a slow one"
                )
        except (TypeError, ValueError):
            problems.append(
                f"mesh_failover_lost_requests is not an int: {lost!r}"
            )
    step_violations = _present(rec, "mesh_step_violations")
    if step_violations is not None:
        try:
            if int(step_violations) != 0:
                problems.append(
                    f"mesh_step_violations={step_violations!r} — "
                    "model_step went backward in response completion "
                    "order across hosts; the global barrier is broken"
                )
        except (TypeError, ValueError):
            problems.append(
                f"mesh_step_violations is not an int: {step_violations!r}"
            )
    receipts = _present(rec, "mesh_host_compile_receipts_max")
    if receipts is not None:
        try:
            if float(receipts) > 1.0:
                problems.append(
                    f"mesh_host_compile_receipts_max={receipts!r} "
                    "breaches the per-host budget-1 receipt"
                )
        except (TypeError, ValueError):
            problems.append(
                "mesh_host_compile_receipts_max is not a number: "
                f"{receipts!r}"
            )
    return problems


def _sebulba_problems(rec: dict) -> list[str]:
    """Structural validation of the sebulba-lane fields (bench phase
    17), whenever present: both throughput headlines finite positive
    numbers; queue occupancy p95 a number in [0, depth] (> 0 would be
    vacuous, but negative or non-numeric is malformed); staleness p95 a
    finite non-negative number; BOTH per-slice compile receipts exactly
    1 (the actor rollout and the learner chunk are one program each,
    whatever the transfer weather did); and the gate's under-load eval
    p50 a finite positive number whenever recorded beside them.
    ``"skipped"`` sentinels are honored as structurally absent."""
    problems = []
    for key in (
        "sebulba_env_steps_per_sec",
        "sebulba_learner_steps_per_sec",
    ):
        v = _present(rec, key)
        if v is None:
            continue
        try:
            f = float(v)
            if not math.isfinite(f) or f <= 0.0:
                problems.append(
                    f"{key}={v!r} (need a finite number > 0 — a zero "
                    "rate means that slice never ran)"
                )
        except (TypeError, ValueError):
            problems.append(f"{key} is not a number: {v!r}")
    occupancy = _present(rec, "transfer_queue_occupancy_p95")
    if occupancy is not None:
        try:
            f = float(occupancy)
            if not math.isfinite(f) or f < 0.0:
                problems.append(
                    f"transfer_queue_occupancy_p95={occupancy!r} "
                    "(need a finite number >= 0)"
                )
        except (TypeError, ValueError):
            problems.append(
                "transfer_queue_occupancy_p95 is not a number: "
                f"{occupancy!r}"
            )
    staleness = _present(rec, "param_staleness_p95_updates")
    if staleness is not None:
        try:
            f = float(staleness)
            if not math.isfinite(f) or f < 0.0:
                problems.append(
                    f"param_staleness_p95_updates={staleness!r} "
                    "(need a finite number >= 0)"
                )
        except (TypeError, ValueError):
            problems.append(
                "param_staleness_p95_updates is not a number: "
                f"{staleness!r}"
            )
    for key in ("sebulba_actor_compiles", "sebulba_learner_compiles"):
        receipts = _present(rec, key)
        if receipts is None:
            continue
        if receipts != 1:
            problems.append(
                f"{key}={receipts!r} — each slice's program must "
                "compile exactly once across the whole pipelined run "
                "(the per-slice budget-1 receipt)"
            )
    gate_p50 = _present(rec, "gate_eval_p50_under_load_s")
    if gate_p50 is not None:
        try:
            f = float(gate_p50)
            if not math.isfinite(f) or f <= 0.0:
                problems.append(
                    f"gate_eval_p50_under_load_s={gate_p50!r} (need a "
                    "finite number > 0: the gate evaluates a real "
                    "candidate while the learner is saturated)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"gate_eval_p50_under_load_s is not a number: {gate_p50!r}"
            )
        gate_compiles = _present(rec, "sebulba_gate_compiles")
        if gate_compiles is not None and gate_compiles != 1:
            problems.append(
                f"sebulba_gate_compiles={gate_compiles!r} — the gate's "
                "matrix program on its own slice must compile exactly "
                "once across the warm eval and every under-load eval"
            )
    return problems


def _envs_problems(rec: dict) -> list[str]:
    """Structural validation of the registered-env ladder fields (bench
    phase 1d), whenever present: every per-env rate a finite positive
    number (a zero rate means that env never stepped); the per-env pair
    recorded together (the phase times every registered env, so one rate
    without the other means the loop died mid-ladder); and
    obstacle_overhead_pct a finite number in [0, 100] (the occlusion
    layer can only cost, never accelerate, and cannot eat more than the
    whole rate). ``"skipped"`` sentinels honored as structurally
    absent."""
    problems = []
    env_keys = (
        "env_steps_per_sec_formation",
        "env_steps_per_sec_pursuit_evasion",
    )
    present = {}
    for key in env_keys:
        v = _present(rec, key)
        if v is None:
            continue
        present[key] = v
        try:
            f = float(v)
            if not math.isfinite(f) or f <= 0.0:
                problems.append(
                    f"{key}={v!r} (need a finite number > 0 — a zero "
                    "rate means that env never stepped)"
                )
        except (TypeError, ValueError):
            problems.append(f"{key} is not a number: {v!r}")
    if len(present) == 1:
        problems.append(
            "registered-env ladder incomplete: got only "
            f"{sorted(present)} — the phase times every registered env, "
            "so a lone rate means the ladder died mid-loop"
        )
    overhead = _present(rec, "obstacle_overhead_pct")
    if overhead is not None:
        try:
            f = float(overhead)
            if not math.isfinite(f) or not 0.0 <= f <= 100.0:
                problems.append(
                    f"obstacle_overhead_pct={overhead!r} (need a finite "
                    "number in [0, 100]: the occlusion layer can only "
                    "cost, never accelerate)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"obstacle_overhead_pct is not a number: {overhead!r}"
            )
    return problems


def _tenancy_problems(rec: dict) -> list[str]:
    """Structural validation of the multi-tenant serving fields
    (serving/tenancy, bench tenant smoke), whenever present:

    - ``tenant_isolation_p95_ratio`` a finite number >= 1 wherever a
      quiet lane's storm-phase p95 is floored at its own baseline (a
      sub-1 or non-finite ratio means the two phases were not actually
      measured), recorded beside at least one per-tenant rate;
    - every ``model_{id}__requests_per_sec`` a finite number > 0 — a
      lane with zero throughput during the storm never actually served;
    - ``shared_rung_compiles`` a non-empty ``{"{arch}:rung{B}": n}``
      dict with every count EXACTLY 1: same-arch lanes must share one
      compile per (arch, rung) and each distinct arch must pay exactly
      its own budget-1 compile — 0 means the rung was never warmed,
      2+ means a lane retraced;
    - per-lane ``model_{id}__step_monotonic_violations`` exactly 0.

    ``"skipped"`` sentinels are honored as structurally absent."""
    problems = []
    ratio = _present(rec, "tenant_isolation_p95_ratio")
    if ratio is not None:
        try:
            v = float(ratio)
            if not math.isfinite(v) or v < 1.0:
                problems.append(
                    f"tenant_isolation_p95_ratio={ratio!r} (need a "
                    "finite number >= 1: the quiet lane's storm-phase "
                    "p95 is floored at its own baseline)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"tenant_isolation_p95_ratio is not a number: {ratio!r}"
            )
        rate_keys = [
            k for k in rec
            if k.startswith("model_") and k.endswith("__requests_per_sec")
        ]
        if not rate_keys:
            problems.append(
                "tenant_isolation_p95_ratio recorded without any "
                "model_{id}__requests_per_sec lane rates beside it"
            )
    for key in sorted(rec):
        if not key.startswith("model_"):
            continue
        v = _present(rec, key)
        if v is None:
            continue
        if key.endswith("__requests_per_sec"):
            try:
                f = float(v)
                if not math.isfinite(f) or f <= 0.0:
                    problems.append(
                        f"{key}={v!r} (need a finite number > 0 — a "
                        "zero-rate lane never actually served)"
                    )
            except (TypeError, ValueError):
                problems.append(f"{key} is not a number: {v!r}")
        elif key.endswith("__step_monotonic_violations"):
            try:
                if int(float(v)) != 0:
                    problems.append(
                        f"{key}={v!r} — a lane's model_step went "
                        "backward in response completion order; "
                        "per-model monotonicity is broken"
                    )
            except (TypeError, ValueError):
                problems.append(f"{key} is not an int: {v!r}")
    shared = _present(rec, "shared_rung_compiles")
    if shared is not None:
        if not isinstance(shared, dict) or not shared:
            problems.append(
                "shared_rung_compiles must be a non-empty dict of "
                f"'{{arch}}:rung{{B}}' -> compile count: {shared!r}"
            )
        else:
            for rung_key in sorted(shared):
                count = shared[rung_key]
                try:
                    bad = int(count) != 1
                except (TypeError, ValueError):
                    bad = True
                if bad:
                    problems.append(
                        f"shared_rung_compiles[{rung_key!r}]={count!r} "
                        "— every (arch, rung) must compile exactly "
                        "once (0 = never warmed, 2+ = a lane retraced "
                        "instead of sharing the executable)"
                    )
    return problems


def _elastic_problems(rec: dict) -> list[str]:
    """Structural validation of the elastic-capacity fields
    (serving/elastic, bench phase "elastic"), whenever present:

    - ``serving_req_per_sec_at_p95_slo_elastic`` and ``..._static``
      both finite numbers > 0 — the comparison is only evidence when
      BOTH fleets actually sustained a rate at the p95 target on the
      storm half;
    - ``elastic_resplit_pause_ms`` a finite number in (0, 250]: the
      barrier-commit pause is the WHOLE serving interruption a
      re-split costs, and an unbounded (or zero — unmeasured) pause
      means prewarm work leaked inside the gates;
    - ``elastic_prewarm_compiles`` an int >= 1 (a re-split that
      compiled nothing never built new rungs) recorded beside
      ``elastic_storm_new_programs`` == 0 — the ledger census diff
      proving every post-warm compile is attributed to prewarm, never
      the measured request path;
    - ``elastic_max_compiles_per_rung`` <= 1 (budget-1 receipts per
      (arch, rung) after warm-up) and ``elastic_resplits_committed``
      an int >= 1 wherever a pause was recorded.

    ``"skipped"`` sentinels are honored as structurally absent."""
    problems = []
    for key in (
        "serving_req_per_sec_at_p95_slo_elastic",
        "serving_req_per_sec_at_p95_slo_static",
    ):
        v = _present(rec, key)
        if v is None:
            continue
        try:
            f = float(v)
            if not math.isfinite(f) or f <= 0.0:
                problems.append(
                    f"{key}={v!r} (need a finite number > 0 — a fleet "
                    "that sustained no rate at the p95 target was "
                    "never actually measured on the storm half)"
                )
        except (TypeError, ValueError):
            problems.append(f"{key} is not a number: {v!r}")
    pause = _present(rec, "elastic_resplit_pause_ms")
    if pause is not None:
        try:
            f = float(pause)
            if not math.isfinite(f) or f <= 0.0 or f > 250.0:
                problems.append(
                    f"elastic_resplit_pause_ms={pause!r} (need a "
                    "finite number in (0, 250]: the barrier-commit "
                    "pause is the whole serving interruption — zero "
                    "means unmeasured, above 250ms means prewarm or "
                    "drain work leaked inside the closed gates)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"elastic_resplit_pause_ms is not a number: {pause!r}"
            )
        committed = _present(rec, "elastic_resplits_committed")
        try:
            if committed is None or int(float(committed)) < 1:
                problems.append(
                    "elastic_resplit_pause_ms recorded without "
                    "elastic_resplits_committed >= 1 beside it (a "
                    "pause nothing committed measured nothing)"
                )
        except (TypeError, ValueError):
            problems.append(
                "elastic_resplits_committed is not an int: "
                f"{committed!r}"
            )
    compiles = _present(rec, "elastic_prewarm_compiles")
    if compiles is not None:
        try:
            if int(float(compiles)) < 1:
                problems.append(
                    f"elastic_prewarm_compiles={compiles!r} (a "
                    "re-split that compiled nothing never built new "
                    "rungs — the prewarm receipt is missing)"
                )
        except (TypeError, ValueError):
            problems.append(
                f"elastic_prewarm_compiles is not an int: {compiles!r}"
            )
        storm_new = _present(rec, "elastic_storm_new_programs")
        try:
            if storm_new is None or int(float(storm_new)) != 0:
                problems.append(
                    f"elastic_storm_new_programs={storm_new!r} (need "
                    "exactly 0 beside elastic_prewarm_compiles: the "
                    "census diff must prove no program registered "
                    "during the measured storm — every compile "
                    "attributed to prewarm, never the request path)"
                )
        except (TypeError, ValueError):
            problems.append(
                "elastic_storm_new_programs is not an int: "
                f"{storm_new!r}"
            )
    max_compiles = _present(rec, "elastic_max_compiles_per_rung")
    if max_compiles is not None:
        try:
            if int(float(max_compiles)) > 1:
                problems.append(
                    f"elastic_max_compiles_per_rung={max_compiles!r} "
                    "— a rung retraced after warm-up; budget-1 "
                    "receipts are broken"
                )
        except (TypeError, ValueError):
            problems.append(
                "elastic_max_compiles_per_rung is not an int: "
                f"{max_compiles!r}"
            )
    return problems


def check(rec: dict, require: list[str], expect: list[str]) -> list[str]:
    """Return the list of violations (empty = evidence-grade record)."""
    problems = []
    if rec.get("fallback"):
        problems.append("fallback: true — CPU run, not hardware evidence")
    if rec.get("platform") == "cpu":
        problems.append("platform is cpu")
    if "error" in rec:
        problems.append(f"error field present: {rec['error']!r}")
    notes = str(rec.get("notes", ""))
    if "skipped" in notes or "failed" in notes:
        problems.append(f"degraded phases in notes: {notes!r}")
    problems.extend(_pipeline_problems(rec))
    problems.extend(_obs_problems(rec))
    problems.extend(_telemetry_problems(rec))
    problems.extend(_serving_slo_problems(rec))
    problems.extend(_adversarial_problems(rec))
    problems.extend(_chaos_problems(rec))
    problems.extend(_recovery_problems(rec))
    problems.extend(_ledger_problems(rec))
    problems.extend(_mesh_problems(rec))
    problems.extend(_lint_problems(rec))
    problems.extend(_sebulba_problems(rec))
    problems.extend(_envs_problems(rec))
    problems.extend(_tenancy_problems(rec))
    problems.extend(_elastic_problems(rec))
    for field in require:
        if rec.get(field) == SKIPPED:
            problems.append(
                f"required field explicitly skipped (phase disabled "
                f"via BENCH_SKIP_*): {field}"
            )
            continue
        try:
            ok = float(rec.get(field, 0.0)) > 0.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            problems.append(f"required field missing/zero: {field}")
    for pair in expect:
        key, _, want = pair.partition("=")
        got = rec.get(key)
        if str(got) != want:
            problems.append(f"{key}={got!r}, expected {want!r}")
    return problems


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("file", type=Path)
    ap.add_argument("--require", nargs="*", default=[], metavar="FIELD")
    ap.add_argument("--expect", nargs="*", default=[], metavar="KEY=VALUE")
    ap.add_argument(
        "--census", type=Path, default=None, metavar="LIVE_CENSUS",
        help="census mode: diff the committed census (the positional "
        "file) against this live program_ledger.json",
    )
    ap.add_argument("--census-tolerance", type=float, default=0.25)
    args = ap.parse_args()
    if args.census is not None:
        repo = str(Path(__file__).resolve().parents[1])
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from marl_distributedformation_tpu.obs.ledger import load_census

        try:
            committed = load_census(args.file)
            live = load_census(args.census)
        except (OSError, ValueError) as e:
            print(f"[check_bench_record] REJECT: {e}", file=sys.stderr)
            sys.exit(1)
        problems = census_diff(
            committed, live, tolerance=args.census_tolerance
        )
    else:
        problems = check(load_record(args.file), args.require, args.expect)
    for p in problems:
        print(f"[check_bench_record] REJECT: {p}", file=sys.stderr)
    if problems:
        sys.exit(1)
    print(f"[check_bench_record] OK: {args.file}")


if __name__ == "__main__":
    main()
