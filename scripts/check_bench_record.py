#!/usr/bin/env python
"""Validate a bench JSON line as committable chip evidence.

The chip-window burster stamps a stage only when its bench record is
real hardware evidence. Each stage needs the same gate — no CPU
fallback, no watchdog error, no degraded ("skipped"/"failed") phases —
plus a per-stage list of required rate fields. This is that gate in ONE
place, so the acceptance criteria cannot drift between stages:

    python scripts/check_bench_record.py /tmp/bench_tpu.json \
        --require train_env_steps_per_sec knn_env_steps_per_sec \
        --expect knn_impl=pallas

Exit 0 iff the record passes. ``--require F`` asserts float(rec[F]) > 0;
``--expect K=V`` asserts str(rec[K]) == V. Input parsing is shared with
scripts/mirror_bench.py (bench.py stdout or a driver BENCH_r*.json
wrapper), so the gate and the mirror can never disagree on a file.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))
from mirror_bench import _load_record as load_record  # noqa: E402


def check(rec: dict, require: list[str], expect: list[str]) -> list[str]:
    """Return the list of violations (empty = evidence-grade record)."""
    problems = []
    if rec.get("fallback"):
        problems.append("fallback: true — CPU run, not hardware evidence")
    if rec.get("platform") == "cpu":
        problems.append("platform is cpu")
    if "error" in rec:
        problems.append(f"error field present: {rec['error']!r}")
    notes = str(rec.get("notes", ""))
    if "skipped" in notes or "failed" in notes:
        problems.append(f"degraded phases in notes: {notes!r}")
    for field in require:
        try:
            ok = float(rec.get(field, 0.0)) > 0.0
        except (TypeError, ValueError):
            ok = False
        if not ok:
            problems.append(f"required field missing/zero: {field}")
    for pair in expect:
        key, _, want = pair.partition("=")
        got = rec.get(key)
        if str(got) != want:
            problems.append(f"{key}={got!r}, expected {want!r}")
    return problems


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("file", type=Path)
    ap.add_argument("--require", nargs="*", default=[], metavar="FIELD")
    ap.add_argument("--expect", nargs="*", default=[], metavar="KEY=VALUE")
    args = ap.parse_args()
    problems = check(load_record(args.file), args.require, args.expect)
    for p in problems:
        print(f"[check_bench_record] REJECT: {p}", file=sys.stderr)
    if problems:
        sys.exit(1)
    print(f"[check_bench_record] OK: {args.file}")


if __name__ == "__main__":
    main()
