#!/usr/bin/env python
"""On-chip population-training throughput: K fused PPO runs vs K x one.

Times one full training iteration of (a) a single Trainer at M formations
and (b) a SweepTrainer with K members at the same per-member M — both at
the TPU-tuned hyperparameters — and reports the population amortization:
how close the fused sweep gets to K-for-free. Run on the real chip when
the tunnel is up:

    python scripts/tpu_sweep_bench.py [K=8] [M=512]

Prints a markdown row + one JSON line (mirror into docs/acceptance/ when
recording).
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def timed_iteration(trainer, iters: int = 10) -> float:
    import jax

    metrics = trainer.run_iteration()  # compile + warmup
    jax.block_until_ready(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        metrics = trainer.run_iteration()
    jax.block_until_ready(metrics["loss"])
    return (time.perf_counter() - t0) / iters


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "cpu"]
    k = int(args[0]) if args else 8
    m = int(args[1]) if len(args) > 1 else 512

    import jax

    if "cpu" in sys.argv[1:]:  # smoke-testing off-chip (env vars are too
        jax.config.update("jax_platforms", "cpu")  # late; see cfg platform)

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import (
        SweepTrainer,
        TrainConfig,
        Trainer,
    )

    from marl_distributedformation_tpu.utils.config import PRESETS

    device = jax.devices()[0].device_kind
    # The REAL preset=tpu batch (docs/profiling.md), not a drifting copy.
    ppo = PPOConfig(batch_size=PRESETS["tpu"]["batch_size"])
    env = EnvParams(num_agents=5)

    def cfg(name: str) -> TrainConfig:
        return TrainConfig(
            num_formations=m, checkpoint=False, name=name,
            log_dir=f"/tmp/sweep-bench-{name}",
        )

    single_s = timed_iteration(Trainer(env, ppo=ppo, config=cfg("single")))
    sweep_s = timed_iteration(
        SweepTrainer(env, ppo=ppo, config=cfg("pop"), num_seeds=k)
    )

    n_steps = ppo.n_steps
    single_rate = n_steps * m / single_s
    sweep_rate = n_steps * m * k / sweep_s
    amortization = sweep_rate / (single_rate * k)  # 1.0 = K for free

    print(
        f"| {device} | M={m}/member | single {single_s * 1e3:.1f} ms/iter "
        f"({single_rate:,.0f} fs/s) | K={k} sweep {sweep_s * 1e3:.1f} "
        f"ms/iter ({sweep_rate:,.0f} fs/s aggregate) | "
        f"{amortization:.0%} of K-for-free |"
    )
    print(json.dumps({
        "metric": "sweep_population_throughput",
        "device": device,
        "k": k,
        "m_per_member": m,
        "single_iter_ms": round(single_s * 1e3, 1),
        "sweep_iter_ms": round(sweep_s * 1e3, 1),
        "single_formation_steps_per_sec": round(single_rate, 1),
        "sweep_formation_steps_per_sec": round(sweep_rate, 1),
        "amortization_vs_k_singles": round(amortization, 3),
        "batch_size": ppo.batch_size,
    }))


if __name__ == "__main__":
    main()
