#!/usr/bin/env python
"""Chip experiment: push tuned training past batch 8192 with a learning-
quality guard (VERDICT r3 #6).

Sweeps (batch_size, learning_rate) points at M=4096 — the preset=tpu
point plus 16k/32k batches with sqrt-scaled rates (Krizhevsky-style: lr
x sqrt(batch/base) keeps per-sample gradient noise comparable) — trains
each for the same agent-transition budget, then evaluates the result on
held-out initial states against the scripted baseline and zero actions
(marl_distributedformation_tpu/eval.py). A point only counts as a
throughput win if its evaluation reward still beats the baseline by at
least GUARD x the preset point's margin — faster-but-dumber batches are
flagged, not crowned.

Usage (chip window; `cpu` forces the CPU backend for a self-smoke at
tiny sizes — the env var route does not beat this image's eagerly
registered device plugin):
    python scripts/tpu_train_tuning.py [M] [iters] [cpu]
    TUNE_POINTS="8192:1e-3,16384:1.4e-3" python scripts/tpu_train_tuning.py

Prints a table + one JSON line; mirror into docs/profiling.md when run
on hardware.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

GUARD = 0.9  # eval margin-over-baseline must stay within 10% of preset's


def default_points():
    # lr scaling: sqrt(batch / 8192) on the base rate 1e-3 — plus an
    # unscaled control per batch so the lr effect is separable. batch 0
    # means "the full rollout buffer" (ONE minibatch per epoch): the
    # profiling breakdown attributes the tuned iteration to the
    # sequential minibatch chain, and the full-buffer point measures the
    # per-minibatch overhead floor directly — if throughput scales with
    # the step-count reduction, the chain is overhead-bound and a fused
    # update kernel (or bigger batches) is the next lever; if not, it is
    # compute/bandwidth-bound and batch size is done as a lever.
    return [
        (8192, 1.0e-3),
        (16384, 1.0e-3),
        (16384, 1.4e-3),
        (32768, 1.0e-3),
        (32768, 2.0e-3),
        (0, 1.0e-3),
        (0, 5.0e-3),  # sqrt-scaled for the 25x batch jump at M=4096
    ]


def parse_points(spec: str):
    return [
        (int(b), float(lr))
        for b, lr in (p.split(":") for p in spec.split(","))
    ]


def main() -> None:
    args = [a for a in sys.argv[1:] if a != "cpu"]
    m = int(args[0]) if len(args) > 0 else 4096
    iters = int(args[1]) if len(args) > 1 else 120
    points = (
        parse_points(os.environ["TUNE_POINTS"])
        if "TUNE_POINTS" in os.environ
        else default_points()
    )

    import jax

    if "cpu" in sys.argv[1:]:
        jax.config.update("jax_platforms", "cpu")

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.eval import (
        baseline_act_fn,
        evaluate,
        policy_act_fn,
        zero_act_fn,
    )
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    params = EnvParams(num_agents=5)
    eval_m = min(1024, max(64, m // 4))
    base = evaluate(baseline_act_fn(params), params, eval_m)
    zero = evaluate(zero_act_fn(), params, eval_m)
    print(
        f"[tune] eval anchors (M={eval_m}): baseline return "
        f"{base['episode_return_per_agent']:.2f}, "
        f"zero {zero['episode_return_per_agent']:.2f}",
        file=sys.stderr,
    )

    def time_rate(trainer, dispatches: int, steps_per_dispatch: int):
        """Shared timing harness for both sweeps: 2 warmup dispatches
        (compile + the donated-shardings retrace), then one timed window
        synced on the final loss."""
        for _ in range(2):
            metrics = trainer.run_iteration()
        jax.block_until_ready(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(dispatches):
            metrics = trainer.run_iteration()
        jax.block_until_ready(metrics["loss"])
        dt = time.perf_counter() - t0
        return dispatches * steps_per_dispatch * m / dt

    rows = []
    for batch, lr in points:
        buffer_size = PPOConfig().n_steps * m * params.num_agents
        if batch == 0:
            batch = buffer_size  # full buffer: one minibatch per epoch
        ppo = PPOConfig(batch_size=batch, learning_rate=lr)
        trainer = Trainer(
            params,
            ppo=ppo,
            config=TrainConfig(
                num_formations=m, checkpoint=False, use_wandb=False,
                name="tune",
            ),
        )
        rate = time_rate(trainer, iters, ppo.n_steps)

        act = policy_act_fn(
            trainer.model, trainer.train_state.params, params
        )
        ev = evaluate(act, params, eval_m)
        margin = ev["episode_return_per_agent"] - base["episode_return_per_agent"]
        rows.append(
            {
                "batch_size": batch,
                "minibatches_per_epoch": max(1, buffer_size // batch),
                "learning_rate": lr,
                "train_steps_per_sec": round(rate, 1),
                "eval_return": round(ev["episode_return_per_agent"], 3),
                "margin_vs_baseline": round(margin, 3),
            }
        )
        print(
            f"[tune] batch={batch} lr={lr:g}: {rate:,.0f} "
            f"formation-steps/s, eval return {ev['episode_return_per_agent']:.2f} "
            f"(baseline {base['episode_return_per_agent']:.2f})",
            file=sys.stderr,
        )

    # Anchor the guard on the REAL preset point (utils.config.PRESETS —
    # not a drifting copy); a custom TUNE_POINTS list without it falls
    # back to its first row — say so, since quality_ok then means "vs
    # that row", not "vs the preset".
    from marl_distributedformation_tpu.utils.config import PRESETS

    preset_batch = PRESETS["tpu"]["batch_size"]
    anchor = next(
        (
            r for r in rows
            if r["batch_size"] == preset_batch
            and r["learning_rate"] == 1.0e-3
        ),
        rows[0],
    )
    if anchor is rows[0] and (
        anchor["batch_size"] != preset_batch
        or anchor["learning_rate"] != 1.0e-3
    ):
        print(
            f"[tune] note: preset point ({preset_batch}, 1e-3) not in "
            f"TUNE_POINTS; quality guard anchors on "
            f"batch={anchor['batch_size']} "
            f"lr={anchor['learning_rate']:g} instead",
            file=sys.stderr,
        )
    preset_margin = anchor["margin_vs_baseline"]
    for r in rows:
        # Rewards are negative-cost shaped; "keeps quality" = margin not
        # materially below the preset point's.
        r["quality_ok"] = bool(
            r["margin_vs_baseline"]
            >= preset_margin - abs(preset_margin) * (1 - GUARD)
        )
    ok = [r for r in rows if r["quality_ok"]]
    best = max(ok, key=lambda r: r["train_steps_per_sec"]) if ok else None

    # Fused-dispatch R sweep at the preset batch: find the
    # RTT-amortization plateau for iters_per_dispatch. Throughput only —
    # fused numerics are pinned bit-equal to single dispatch
    # (tests/test_trainer.py::test_iters_per_dispatch_matches_single_
    # dispatch), so no quality leg is needed. Ceil division gives every
    # point a timing window of AT LEAST `iters` iterations; the R=1
    # baseline is the main sweep's preset anchor row above (same config,
    # already compiled and timed — not re-measured here).
    fused_rows = []
    for r_fuse in (4, 8, 16, 32):
        if iters < r_fuse:
            continue
        trainer = Trainer(
            params,
            ppo=PPOConfig(batch_size=preset_batch),
            config=TrainConfig(
                num_formations=m, checkpoint=False, use_wandb=False,
                name="tune-fused", iters_per_dispatch=r_fuse,
            ),
        )
        dispatches = -(-iters // r_fuse)
        rate = time_rate(trainer, dispatches, r_fuse * PPOConfig().n_steps)
        fused_rows.append(
            {
                "iters_per_dispatch": r_fuse,
                "train_steps_per_sec": round(rate, 1),
            }
        )
        print(
            f"[tune] fused R={r_fuse}: {rate:,.0f} formation-steps/s "
            f"(batch={preset_batch})",
            file=sys.stderr,
        )
    best_fused = (
        max(fused_rows, key=lambda r: r["train_steps_per_sec"])
        if fused_rows else None
    )

    out = {
        "m": m,
        "iters_per_point": iters,
        "eval_m": eval_m,
        "baseline_return": round(base["episode_return_per_agent"], 3),
        "zero_return": round(zero["episode_return_per_agent"], 3),
        "device": jax.devices()[0].device_kind,
        "guard_anchor": {
            "batch_size": anchor["batch_size"],
            "learning_rate": anchor["learning_rate"],
        },
        "points": rows,
        "best_quality_ok": best,
        "fused_points": fused_rows,
        "best_fused": best_fused,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
