#!/usr/bin/env bash
# Tunnel watchdog: probe every POLL_S seconds and fire the chip-window
# burster (scripts/chip_window.sh) whenever the TPU tunnel is up, until
# every stage is stamped or MAX_LIFE_S elapses. Detach it so it outlives
# any one shell:
#
#   setsid nohup bash scripts/chip_watchdog.sh >> /tmp/chip_watchdog.log 2>&1 &
#
# The burster takes its own flock (auto-released on process death), so
# concurrent ticks or a manual run simply bounce off it. All progress
# lands in /tmp/chip_watchdog.log and /tmp/chip_state/.
set -uo pipefail
cd "$(dirname "$0")/.."

POLL_S=${POLL_S:-60}
MAX_LIFE_S=${MAX_LIFE_S:-39600}  # 11h
STATE=/tmp/chip_state
start=$(date +%s)

echo "[watchdog] start $(date -u +%Y-%m-%dT%H:%M:%SZ) poll=${POLL_S}s"
while true; do
  # The burster owns the stage list; it stamps ALL_DONE when every stage
  # it defines is stamped — no stage-name copy here to drift.
  if [ -f "$STATE/ALL_DONE" ]; then
    echo "[watchdog] all stages stamped — done $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    exit 0
  fi
  if [ $(( $(date +%s) - start )) -gt "$MAX_LIFE_S" ]; then
    echo "[watchdog] lifetime exceeded; stamps present:"
    ls "$STATE" 2>/dev/null
    exit 1
  fi
  bash scripts/chip_window.sh
  rc=$?
  if [ "$rc" -eq 73 ]; then
    echo "[watchdog] burster already running; skipping tick"
  elif [ "$rc" -ne 0 ]; then
    echo "[watchdog] burster failed (rc=$rc); will retry next tick"
  fi
  sleep "$POLL_S"
done
