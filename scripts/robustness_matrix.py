#!/usr/bin/env python
"""Robustness eval matrix: scenarios x severities x checkpoints, one JSON.

The quantitative stress test of the paper's locality claim: sweep a run's
checkpoint series over every registered disturbance scenario at several
severities, on identical initial states, in ONE compiled eval program
(model params and scenario params are traced inputs; the zero-recompile
contract is enforced with a budget-1 RetraceGuard and the compile count
is recorded in the report).

This CLI is a thin wrapper: the compiled program lives in
``scenarios.matrix`` (``run_matrix`` for a one-shot checkpoint sweep,
``MatrixProgram`` for a long-lived reusable instance) — the
always-learning promotion gate (``pipeline/gate.py``) holds ONE
MatrixProgram for an entire run instead of shelling out here or
re-jitting per candidate.

Usage (same key=value CLI as every entry point):
    python scripts/robustness_matrix.py name=myrun
    python scripts/robustness_matrix.py name=myrun scenarios=[wind,storm] \
        severities=[0,0.5,1] matrix_checkpoints=3 eval_formations=256
    python scripts/robustness_matrix.py checkpoint=logs/x/rl_model_200_steps.ckpt

By default the matrix covers ALL registered scenarios at severities
0 / 0.5 / 1.0 for the run's last 2 checkpoints (training progress vs
robustness), and writes ``logs/{name}/robustness_matrix.json`` plus the
same report as one JSON line on stdout. Unknown scenario names and
mistyped config keys fail fast naming the valid entries.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from marl_distributedformation_tpu.utils import (  # noqa: E402
    env_params_from_config,
    load_config,
    repo_root,
    setup_platform,
    validate_override_keys,
)

MATRIX_KEYS = (
    "checkpoint",
    "eval_formations",
    "eval_seed",
    "eval_deterministic",
    "severities",
    "matrix_checkpoints",
    "out",
)


def _checkpoints(cfg) -> list:
    """Resolve the checkpoint list: explicit ``checkpoint=`` (one path or
    a YAML list), else the last ``matrix_checkpoints`` (default 2) of the
    named run."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        checkpoint_step,
    )

    explicit = cfg.get("checkpoint")
    if explicit:
        paths = explicit if isinstance(explicit, list) else [explicit]
        return [str(p) for p in paths]
    log_dir = repo_root() / "logs" / str(cfg.name)
    ckpts = sorted(
        log_dir.glob("rl_model_*_steps.*"), key=checkpoint_step
    )
    if not ckpts:
        raise SystemExit(
            f"no checkpoints under {log_dir}; pass checkpoint=... or "
            "name=<trained run>"
        )
    keep = max(1, int(cfg.get("matrix_checkpoints", 2)))
    return [str(p) for p in ckpts[-keep:]]


def _scenarios(cfg) -> list:
    from marl_distributedformation_tpu.scenarios import (
        get_scenario,
        registered_scenarios,
    )

    raw = cfg.get("scenarios")
    if not raw:
        return list(registered_scenarios())
    names = raw if isinstance(raw, list) else [raw]
    try:
        return [get_scenario(str(n)).name for n in names]
    except ValueError as e:  # unknown name -> clean CLI error w/ registry
        raise SystemExit(str(e)) from e


def main(argv=None) -> dict:
    overrides = sys.argv[1:] if argv is None else argv
    validate_override_keys(overrides, extra_keys=MATRIX_KEYS)
    cfg = load_config(overrides)
    setup_platform(cfg.get("platform"))
    from marl_distributedformation_tpu.scenarios import run_matrix

    params = env_params_from_config(cfg)
    severities = [
        float(s) for s in (cfg.get("severities") or (0.0, 0.5, 1.0))
    ]
    report = run_matrix(
        _checkpoints(cfg),
        params,
        scenarios=_scenarios(cfg),
        severities=severities,
        num_formations=int(cfg.get("eval_formations", 256)),
        seed=int(cfg.get("eval_seed", 1234)),
        deterministic=bool(cfg.get("eval_deterministic", True)),
    )
    report["name"] = str(cfg.name)
    try:
        import jax

        dev = jax.devices()[0]
        report["resolved_platform"] = dev.platform
        report["resolved_device"] = dev.device_kind
    except Exception:  # noqa: BLE001 — provenance never kills a report
        pass

    # Human-readable slice: per checkpoint x scenario, return at the
    # highest severity vs clean (degradation is the robustness headline).
    key = "episode_return_per_agent"
    hi = f"{max(severities):g}"
    print(
        f"[matrix] {len(report['checkpoints'])} checkpoints x "
        f"{len(report['scenarios'])} scenarios x {len(severities)} "
        f"severities, M={report['eval_formations']}, "
        f"compiles={report['eval_compiles']}"
    )
    for ckpt, per_scenario in report["matrix"].items():
        print(f"[matrix] {Path(ckpt).name}:")
        for scenario, per_sev in per_scenario.items():
            vals = " ".join(
                f"s={sev}:{metrics[key]:,.0f}"
                for sev, metrics in per_sev.items()
            )
            print(f"  {scenario:<16} {vals}")

    out = cfg.get("out") or str(
        repo_root() / "logs" / str(cfg.name) / "robustness_matrix.json"
    )
    Path(out).parent.mkdir(parents=True, exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    report["out"] = str(out)
    print(json.dumps(report))
    return report


if __name__ == "__main__":
    main()
