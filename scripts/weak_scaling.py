#!/usr/bin/env python
"""Measured scaling curves for the dp/sweep sharded paths on a virtual
CPU mesh (VERDICT r3 #7: numbers, not just green dryruns).

No multi-chip hardware exists in this environment, so each device count
D in 1..8 runs in a subprocess with
``--xla_force_host_platform_device_count=D`` — the same virtual mesh the
test suite and the driver's ``dryrun_multichip`` use. What this CAN
measure honestly: that the sharded programs execute at every D and what
the partitioner/collective machinery costs on top of the same total
work. What it CANNOT measure: real weak scaling — all D virtual devices
share this host's CPU cores (2 here), so past D=cores the devices
serialize and wall-clock grows with total work by construction. The doc
table (docs/weak_scaling.md) therefore reports:

- ``dp_env`` / ``dp_train`` (fixed TOTAL load): sharding the same work
  over more virtual devices. Ideal is flat; growth above the D=1 row is
  partitioning/collective overhead (the psum gradient all-reduce in
  dp_train), which IS the transferable number.
- ``sweep`` (fixed PER-DEVICE load, one member per device): total work
  grows with D. On shared cores the serialization bound is
  time >= t1 * D / min(D, cores); the table reports measured
  member-iterations/s and that bound so overhead is visible as the gap.

Usage: python scripts/weak_scaling.py            # parent: all D, writes doc
       python scripts/weak_scaling.py --child D  # one D, prints JSON lines
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


DEVICE_COUNTS = tuple(
    int(d)
    for d in os.environ.get("WS_DEVICES", "1,2,4,8").split(",")
)
M_TOTAL = _env_int("WS_M_TOTAL", 256)  # fixed-total formations for dp_env
M_TRAIN = _env_int("WS_M_TRAIN", 64)  # fixed-total formations for dp_train
M_PER_MEMBER = _env_int("WS_M_MEMBER", 32)  # per-device load, sweep phase
N_AGENTS = 5
ENV_CHUNK = _env_int("WS_ENV_CHUNK", 64)  # env steps per timed dispatch
MIN_TIMED_S = float(os.environ.get("WS_MIN_TIMED_S", 2.0))


def _time_calls(fn, *args):
    """Warm up TWICE, then average over >= MIN_TIMED_S of calls.

    Two warmups, not one: the trainer paths recompile on their second
    call (the first execution's donated outputs carry the compiled
    program's shardings, which differ from the host-placed init — the
    retrace is once-only). Timing after a single warmup measures that
    second compile, not the steady state."""
    import jax

    for _ in range(2):
        out = fn(*args)
        jax.block_until_ready(out)
    calls, start = 0, time.perf_counter()
    while time.perf_counter() - start < MIN_TIMED_S:
        out = fn(*args)
        jax.block_until_ready(out)
        calls += 1
    return (time.perf_counter() - start) / calls


def child(n_dev: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) == n_dev, (
        f"expected {n_dev} virtual devices, got {len(jax.devices())} — "
        "XLA_FLAGS must be set before backend init"
    )
    import jax.numpy as jnp

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.env.formation import reset_batch
    from marl_distributedformation_tpu.parallel import (
        make_dp_step,
        make_mesh,
        make_shard_fn,
        shard_batch,
    )
    from marl_distributedformation_tpu.train import (
        SweepTrainer,
        TrainConfig,
        Trainer,
    )

    params = EnvParams(num_agents=N_AGENTS)
    mesh = make_mesh({"dp": n_dev})
    ppo = PPOConfig(n_steps=4, batch_size=8 * M_TRAIN, n_epochs=2)

    def emit(phase: str, seconds: float, work_steps: float) -> None:
        print(
            json.dumps(
                {
                    "phase": phase,
                    "devices": n_dev,
                    "seconds_per_call": seconds,
                    "steps_per_sec": work_steps / seconds,
                }
            ),
            flush=True,
        )

    # -- dp_env: fixed-total env stepping, shard_map over 'dp' ----------
    dp_step = make_dp_step(params, mesh)
    state = shard_batch(reset_batch(jax.random.PRNGKey(0), params, M_TOTAL),
                        mesh)
    vel = shard_batch(
        jnp.zeros((M_TOTAL, N_AGENTS, 2), jnp.float32) + 1.0, mesh
    )

    @jax.jit
    def run_chunk(state, vel):
        def body(s, _):
            s, tr = dp_step(s, vel)
            return s, tr.reward.mean()

        return jax.lax.scan(body, state, None, length=ENV_CHUNK)

    emit("dp_env", _time_calls(run_chunk, state, vel),
         M_TOTAL * ENV_CHUNK)

    # -- dp_train: fixed-total full PPO iteration (psum grad all-reduce) -
    trainer = Trainer(
        params,
        ppo=ppo,
        config=TrainConfig(
            num_formations=M_TRAIN, name="ws", checkpoint=False,
            log_dir="/tmp/ws_train",
        ),
        shard_fn=make_shard_fn(mesh=mesh),
    )
    emit("dp_train", _time_calls(trainer.run_iteration),
         ppo.n_steps * M_TRAIN)

    # -- sweep: one member per device, fixed per-device load -------------
    sweep = SweepTrainer(
        params,
        ppo=PPOConfig(n_steps=4, batch_size=8 * M_PER_MEMBER, n_epochs=2),
        config=TrainConfig(
            num_formations=M_PER_MEMBER, name="ws", checkpoint=False,
            log_dir="/tmp/ws_sweep",
        ),
        num_seeds=n_dev,
        mesh=mesh,
    )
    emit("sweep", _time_calls(sweep.run_iteration),
         4 * M_PER_MEMBER * n_dev)


def parent() -> None:
    rows = []
    for n_dev in DEVICE_COUNTS:
        env = dict(
            os.environ,
            XLA_FLAGS=(
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n_dev}"
            ),
        )
        print(f"[weak_scaling] D={n_dev} ...", file=sys.stderr, flush=True)
        out = subprocess.run(
            [sys.executable, __file__, "--child", str(n_dev)],
            env=env,
            capture_output=True,
            text=True,
            check=False,
        )
        if out.returncode != 0:
            print(out.stdout, file=sys.stderr)
            print(out.stderr, file=sys.stderr)
            raise SystemExit(f"child D={n_dev} failed")
        for line in out.stdout.splitlines():
            if line.startswith("{"):
                rows.append(json.loads(line))
    write_doc(rows)
    print(json.dumps(rows, indent=2))


def write_doc(rows) -> None:
    import multiprocessing

    cores = multiprocessing.cpu_count()
    by_phase: dict = {}
    for r in rows:
        by_phase.setdefault(r["phase"], {})[r["devices"]] = r

    lines = [
        "# Sharded-path scaling on the virtual CPU mesh",
        "",
        "Measured by `scripts/weak_scaling.py` (subprocess per device",
        "count, `--xla_force_host_platform_device_count=D`, CPU backend,",
        f"{cores} host cores). **These numbers bound the partitioner +",
        "collective overhead of the sharded programs — they are NOT",
        "multi-chip performance**: all D virtual devices share the same",
        "host cores, so past D=cores the devices serialize by",
        "construction. On real chips the dp/sweep programs have zero or",
        "one collective (see parallel/), so the transferable signal is",
        "the overhead column staying small.",
        "",
    ]
    captions = {
        "dp_env": (
            f"## dp_env — fixed total load ({M_TOTAL} formations, "
            "shard_map env step)\n\nIdeal: flat. Overhead = slowdown vs "
            "D=1 for identical total work."
        ),
        "dp_train": (
            f"## dp_train — fixed total load ({M_TRAIN} formations, full "
            "PPO iteration incl. psum gradient all-reduce)\n\nIdeal: "
            "flat. This is the collective-bearing path. Note the "
            f"per-device slice shrinks to {M_TRAIN} / D formations, so at "
            "D=8 the fixed per-device dispatch + emulated-collective cost "
            "dominates a tiny compute slice — on real chips the same "
            "program runs thousands of formations per device and the "
            "psum rides ICI."
        ),
        "sweep": (
            f"## sweep — fixed per-device load (1 member x {M_PER_MEMBER} "
            "formations per device)\n\nTotal work grows with D; the "
            "serialization bound on shared cores is t >= t1 * D / "
            "min(D, cores). Overhead = slowdown vs that bound."
        ),
    }
    for phase in ("dp_env", "dp_train", "sweep"):
        data = by_phase.get(phase)
        if not data:
            continue
        # Baseline = smallest measured D (WS_DEVICES may omit 1).
        d_base = min(data)
        t1 = data[d_base]["seconds_per_call"]
        lines += [captions[phase], "",
                  "| D | s/call | steps/s | overhead |", "|---|---|---|---|"]
        for d in sorted(data):
            r = data[d]
            if phase == "sweep":
                # Serialization bound normalized to the baseline D.
                serial = lambda k: k / min(k, cores)  # noqa: E731
                bound = t1 * serial(d) / serial(d_base)
            else:
                bound = t1
            over = r["seconds_per_call"] / bound - 1.0
            lines.append(
                f"| {d} | {r['seconds_per_call']:.3f} | "
                f"{r['steps_per_sec']:,.0f} | {over:+.1%} |"
            )
        lines.append("")
    doc = Path(
        os.environ.get(
            "WS_DOC",
            Path(__file__).resolve().parent.parent
            / "docs" / "weak_scaling.md",
        )
    )
    doc.write_text("\n".join(lines))
    print(f"[weak_scaling] wrote {doc}", file=sys.stderr)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(int(sys.argv[2]))
    else:
        parent()
