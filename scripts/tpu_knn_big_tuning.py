#!/usr/bin/env python
"""Chip experiment: sweep the chunked-streaming k-NN kernel's block shape.

``knn_batch_pallas_big`` (ops/knn_pallas.py) ships with defaults
``block_r=256, chunk_c=512, block_m=1`` that were chosen analytically
(~3 MB of VMEM tile intermediates per program), never measured against
alternatives on hardware. This sweeps a small grid of lane-aligned block
shapes at the bench shape (M=512, N=1024, k=4 — the `knn_big` bench
phase), checks each candidate's indices bit-match the XLA path (the
kernel's contract), and times the compiled call.

Every candidate that compiles is recorded; Mosaic rejections (VMEM
overflow for fat blocks) are recorded as failed so the sweep doubles as
a map of the kernel's feasibility envelope on this chip generation.

Run: python scripts/tpu_knn_big_tuning.py [M] [N] [iters]
     TUNE_BLOCKS="256:512:1,128:512:8" overrides the candidate list
     (block_r:chunk_c:block_m triples).
Prints one table row per candidate + a summary JSON line (keyed
``"metric": "knn_big_block_tuning"``; ``best`` = fastest candidate whose
neighbor indices match XLA exactly AND distances within atol=1e-4 — the
two checks are recorded separately as ``indices_exact``/``dist_close``).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def default_blocks():
    # Around the shipped default (256, 512, 1): halve/double each axis
    # independently, plus multi-formation programs (block_m > 1
    # amortizes grid/dispatch overhead if VMEM allows — each program's
    # intermediates scale linearly in block_m).
    return [
        (256, 512, 1),  # shipped default — the anchor
        (128, 512, 1),
        (512, 512, 1),
        (256, 256, 1),
        (256, 1024, 1),
        (256, 512, 2),
        (256, 512, 4),
        (256, 512, 8),
        (128, 256, 8),
    ]


def parse_blocks(spec: str):
    return [
        tuple(int(v) for v in p.split(":"))
        for p in spec.split(",")
        if p.strip()
    ]


def main() -> None:
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 50
    k = 4
    # Off-chip plumbing self-test: interpret-mode Pallas on tiny shapes
    # (timings are meaningless there; the chip run never sets this).
    interpret = os.environ.get("KNN_TUNE_INTERPRET") == "1"

    import jax
    import jax.numpy as jnp

    from marl_distributedformation_tpu.ops.knn import knn_batch
    from marl_distributedformation_tpu.ops.knn_pallas import (
        knn_batch_pallas_big,
    )

    device = jax.devices()[0].device_kind
    points = jax.random.uniform(
        jax.random.PRNGKey(0), (m, n, 2), jnp.float32, 0.0, 800.0
    )
    ref_idx, ref_off, ref_dist = jax.block_until_ready(
        knn_batch(points, k, impl="xla")
    )

    blocks = (
        parse_blocks(os.environ["TUNE_BLOCKS"])
        if os.environ.get("TUNE_BLOCKS")
        else default_blocks()
    )
    rows = []
    print(f"| block_r | chunk_c | block_m | us/call | idx-exact+dist-close |")
    print(f"|---|---|---|---|---|")
    for block_r, chunk_c, block_m in blocks:
        rec = {
            "block_r": block_r,
            "chunk_c": chunk_c,
            "block_m": block_m,
        }
        try:
            run = lambda: knn_batch_pallas_big(  # noqa: E731
                points, k,
                block_r=block_r, chunk_c=chunk_c, block_m=block_m,
                interpret=interpret,
            )
            idx, off, dist = jax.block_until_ready(run())  # compile+warm
            # Two distinct checks, recorded as two distinct fields (the
            # old single "bit_exact" flag overstated the distance leg):
            # neighbor INDICES must match XLA exactly; distances only to
            # atol=1e-4 (the chunked kernel accumulates in a different
            # order, so the last float bit can differ legitimately).
            indices_exact = bool(jnp.array_equal(idx, ref_idx))
            dist_close = bool(jnp.allclose(dist, ref_dist, atol=1e-4))
            exact = indices_exact and dist_close
            t0 = time.perf_counter()
            for _ in range(iters):
                out = run()
            jax.block_until_ready(out)
            us = (time.perf_counter() - t0) / iters * 1e6
            rec.update(
                us_per_call=round(us, 1),
                indices_exact=indices_exact,
                dist_close=dist_close,
                ok=True,
            )
            print(
                f"| {block_r} | {chunk_c} | {block_m} | {us:,.1f} |"
                f" {exact} |"
            )
        except Exception as e:  # noqa: BLE001 — feasibility map, not crash
            rec.update(ok=False, error=repr(e)[:160])
            print(
                f"| {block_r} | {chunk_c} | {block_m} | FAILED |"
                f" {repr(e)[:60]} |"
            )
        rows.append(rec)

    good = [
        r for r in rows
        if r.get("ok") and r.get("indices_exact") and r.get("dist_close")
    ]
    best = min(good, key=lambda r: r["us_per_call"]) if good else None
    anchor = next(
        (
            r for r in good
            if (r["block_r"], r["chunk_c"], r["block_m"]) == (256, 512, 1)
        ),
        None,
    )
    out = {
        "metric": "knn_big_block_tuning",
        "device": device,
        "m": m,
        "n": n,
        "k": k,
        "iters": iters,
        "rows": rows,
        "anchor_default": anchor,
        "best": best,
    }
    if best and anchor:
        out["best_speedup_vs_default"] = round(
            anchor["us_per_call"] / best["us_per_call"], 3
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
