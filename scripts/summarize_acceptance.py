#!/usr/bin/env python
"""Summarize a training run's ``metrics.jsonl`` into acceptance markdown.

Chip windows are short; landing a hardware acceptance run should be one
command, not hand-edited tables. Reads ``logs/{name}/metrics.jsonl``
(or any metrics file) and prints the markdown table the
``docs/acceptance/*/README.md`` records use:

    python scripts/summarize_acceptance.py logs/hetero5_tpu/metrics.jsonl
    python scripts/summarize_acceptance.py logs/sweep8_tpu/metrics.jsonl

- For curriculum runs (rows carry ``curriculum_stage``): one row per
  stage boundary (first/last iteration of each stage) — reward +
  avg_dist_to_goal, matching docs/acceptance/hetero5/README.md.
- For sweep runs (rows carry ``reward_best``/``best_seed``): population
  mean trajectory (first/mid/last) plus the final best/worst spread,
  matching docs/acceptance/sweep8/README.md.
- Otherwise: first/mid/last iteration rows.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


def load(path: Path) -> list[dict]:
    rows = []
    with path.open() as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                rows.append(json.loads(ln))
    if not rows:
        raise SystemExit(f"{path}: no metric rows")
    return rows


def fmt(x, nd=2):
    return f"{x:.{nd}f}" if isinstance(x, (int, float)) else str(x)


def curriculum_table(rows: list[dict]) -> str:
    out = ["| iteration | stage | reward | avg_dist_to_goal |", "|---|---|---|---|"]
    prev_stage = None
    for i, r in enumerate(rows, 1):
        stage = int(r.get("curriculum_stage", 0))
        boundary = stage != prev_stage  # first row of a stage
        last_of_stage = (
            i == len(rows)
            or int(rows[i].get("curriculum_stage", 0)) != stage
        )
        if boundary or last_of_stage:
            out.append(
                f"| {i} | {stage} | {fmt(r['reward'])} | "
                f"{fmt(r['avg_dist_to_goal'], 1)} |"
            )
        prev_stage = stage
    return "\n".join(out)


def sweep_table(rows: list[dict]) -> str:
    picks = sorted({1, len(rows) // 2, len(rows)})
    out = [
        "| iteration | population mean reward | best | worst | best_seed |",
        "|---|---|---|---|---|",
    ]
    for i in picks:
        r = rows[i - 1]
        out.append(
            f"| {i} | {fmt(r['reward'])} | {fmt(r.get('reward_best'))} | "
            f"{fmt(r.get('reward_worst'))} | {int(r.get('best_seed', -1))} |"
        )
    return "\n".join(out)


def plain_table(rows: list[dict]) -> str:
    picks = sorted({1, len(rows) // 2, len(rows)})
    out = ["| iteration | step | reward | avg_dist_to_goal |", "|---|---|---|---|"]
    for i in picks:
        r = rows[i - 1]
        out.append(
            f"| {i} | {int(r.get('step', 0))} | {fmt(r['reward'])} | "
            f"{fmt(r['avg_dist_to_goal'], 1)} |"
        )
    return "\n".join(out)


def main() -> None:
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    path = Path(sys.argv[1])
    rows = load(path)
    last = rows[-1]
    if any("curriculum_stage" in r for r in rows):
        kind, table = "curriculum", curriculum_table(rows)
    elif any("reward_best" in r for r in rows):
        kind, table = "sweep", sweep_table(rows)
    else:
        kind, table = "single", plain_table(rows)
    print(f"<!-- {kind} summary of {path} ({len(rows)} iterations, "
          f"final step {int(last.get('step', 0))}) -->")
    print(table)
    env_rate = last.get("env_steps_per_sec")
    if env_rate:
        print(f"\nFinal training throughput: "
              f"{env_rate:,.0f} formation-steps/s.")


if __name__ == "__main__":
    main()
