#!/usr/bin/env python
"""Summarize a training run's ``metrics.jsonl`` into acceptance markdown.

Chip windows are short; landing a hardware acceptance run should be one
command, not hand-edited tables. Reads ``logs/{name}/metrics.jsonl``
(or any metrics file) and prints the markdown table the
``docs/acceptance/*/README.md`` records use:

    python scripts/summarize_acceptance.py logs/hetero5_tpu/metrics.jsonl
    python scripts/summarize_acceptance.py logs/sweep8_tpu/metrics.jsonl

- For curriculum runs (rows carry ``curriculum_stage``): one row per
  stage boundary (first/last iteration of each stage) — reward +
  avg_dist_to_goal, matching docs/acceptance/hetero5/README.md.
- For sweep runs (rows carry ``reward_best``/``best_seed``): population
  mean trajectory (first/mid/last) plus the final best/worst spread,
  matching docs/acceptance/sweep8/README.md.
- Otherwise: first/mid/last iteration rows.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


def load(path: Path) -> list[dict]:
    rows = []
    with path.open() as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                rows.append(json.loads(ln))
    if not rows:
        raise SystemExit(f"{path}: no metric rows")
    return rows


def fmt(x, nd=2):
    return f"{x:.{nd}f}" if isinstance(x, (int, float)) else str(x)


def curriculum_table(rows: list[dict]) -> str:
    out = ["| iteration | stage | reward | avg_dist_to_goal |", "|---|---|---|---|"]
    prev_stage = None
    for i, r in enumerate(rows, 1):
        stage = int(r.get("curriculum_stage", 0))
        boundary = stage != prev_stage  # first row of a stage
        last_of_stage = (
            i == len(rows)
            or int(rows[i].get("curriculum_stage", 0)) != stage
        )
        if boundary or last_of_stage:
            out.append(
                f"| {i} | {stage} | {fmt(r['reward'])} | "
                f"{fmt(r['avg_dist_to_goal'], 1)} |"
            )
        prev_stage = stage
    return "\n".join(out)


def sweep_table(rows: list[dict]) -> str:
    """Population trajectory as WINDOWED means (trailing 25 iterations),
    not single-iteration samples: at small sweep scales the per-iteration
    noise is large (±6 at the sweep8 config), and point samples misread a
    plateau as a regression — the round-4 lesson recorded in
    docs/acceptance/sweep8/REGRESSION.md."""
    win = 25
    # Every column is windowed (trailing <=25 rows ending at the pick) so
    # mean/best/worst are mutually consistent; picks of 0 (len//2 of a
    # 1-row file) are dropped rather than averaging an empty window.
    picks = sorted({1, len(rows) // 2, len(rows)} - {0})
    out = [
        f"<!-- mean/best/worst each over the trailing <={win}-iter "
        "window ending at the pick -->",
        "| iteration | population mean reward | best | worst | best_seed |",
        "|---|---|---|---|---|",
    ]
    for i in picks:
        w = rows[max(0, i - win) : i]
        wmean = sum(r["reward"] for r in w) / len(w)
        bests = [r["reward_best"] for r in w if r.get("reward_best") is not None]
        worsts = [
            r["reward_worst"] for r in w if r.get("reward_worst") is not None
        ]
        out.append(
            f"| {i} | {fmt(wmean)} | "
            f"{fmt(max(bests) if bests else None)} | "
            f"{fmt(min(worsts) if worsts else None)} | "
            f"{int(rows[i - 1].get('best_seed', -1))} |"
        )
    return "\n".join(out)


def plain_table(rows: list[dict]) -> str:
    picks = sorted({1, len(rows) // 2, len(rows)})
    out = ["| iteration | step | reward | avg_dist_to_goal |", "|---|---|---|---|"]
    for i in picks:
        r = rows[i - 1]
        out.append(
            f"| {i} | {int(r.get('step', 0))} | {fmt(r['reward'])} | "
            f"{fmt(r['avg_dist_to_goal'], 1)} |"
        )
    return "\n".join(out)


def main() -> None:
    if len(sys.argv) != 2:
        raise SystemExit(__doc__)
    path = Path(sys.argv[1])
    rows = load(path)
    last = rows[-1]
    if any("curriculum_stage" in r for r in rows):
        kind, table = "curriculum", curriculum_table(rows)
    elif any("reward_best" in r for r in rows):
        kind, table = "sweep", sweep_table(rows)
    else:
        kind, table = "single", plain_table(rows)
    print(f"<!-- {kind} summary of {path} ({len(rows)} iterations, "
          f"final step {int(last.get('step', 0))}) -->")
    print(table)
    env_rate = last.get("env_steps_per_sec")
    if env_rate:
        print(f"\nFinal training throughput: "
              f"{env_rate:,.0f} formation-steps/s.")


if __name__ == "__main__":
    main()
