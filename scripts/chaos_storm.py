#!/usr/bin/env python
"""Chaos storm: trainer -> gate -> fleet under a seeded fault campaign.

One command runs the whole always-learning loop at tiny scale while a
seeded :class:`chaos.FaultSchedule` injects crashes, wedges, checkpoint
corruption, ENOSPC, and delays at the host seams the code declares
(``chaos.INJECTION_POINTS``), then checks every cross-PR invariant
(step monotonicity, no-request-lost, budget-1 receipts, audit-log and
checkpoint-dir consistency) and reports MTTR + violations as ONE JSON
line:

    python scripts/chaos_storm.py --seed 0 --faults 25
    python scripts/chaos_storm.py --mesh --seed 0   # 2-host loopback mesh

``--mesh`` points the same storm at the cross-host tier
(serving/mesh/, docs/mesh.md): the control-plane faults arm in this
process and one host subprocess eats a REAL ``kill -9`` mid-storm —
the invariant suite below runs unchanged over the mesh (ROADMAP item
1's transfer test), plus two mesh checkers (the killed host must be
declared dead; at least one coordinator-driven global swap must land).

The campaign is DETERMINISTIC from its seed: ``--print-schedule`` emits
the armed fault schedule (a pure function of the CLI args) without
running anything, and the report's ``deterministic`` section replays
bit-identically — a failing campaign is re-runnable, not an anecdote.
Wall-clock fields (``chaos_mttr_s``, rates) are measurements and live
OUTSIDE that section.

Phases:

1. **train** — a tiny fused-scan Trainer writes checkpoints through the
   AsyncCheckpointWriter while crash/ENOSPC/corruption faults hit the
   write path; training must SURVIVE (skip-with-audit) and leave a
   crash-consistent directory.
2. **resume** — ``restore_latest_partial`` walks back over quarantined
   damage to the newest valid checkpoint.
3. **serve** — bootstrap the promotion pipeline, attach a 2-replica
   fleet + LaneWatchdog, then run the supervised loop under the
   pipeline/serving half of the schedule while a prober measures
   recovery (kill -> first served response = MTTR).
4. **verify** — the chaos invariant suite over everything the campaign
   left on disk and in memory.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Points armed during the TRAIN leg vs the SERVE leg (the two halves of
# one campaign; per-leg pacing waits for that leg's cells to fire).
TRAIN_POINTS = (
    "checkpoint.write",
    "checkpoint.pre_rename",
    "checkpoint.post_rename",
    "ckpt_writer.submit",
)
SERVE_POINTS = (
    "stream.poll",
    "gate.eval",
    "pipeline.poll",
    "fleet.barrier",
    "registry.swap",
    "scheduler.dispatch",
)
# The --mesh campaign's serve leg: the control-plane seams that live in
# THIS process (coordinator + pipeline). The per-host seams live in the
# host SUBPROCESSES — their disruption is the real `kill -9` below,
# which no fault plane can simulate from here.
MESH_SERVE_POINTS = (
    "stream.poll",
    "gate.eval",
    "pipeline.poll",
    "mesh.rpc",
    "mesh.heartbeat",
)
# The --train campaign's divergence seams (train/recovery.py,
# docs/recovery.md): carry poison / grad bombs at the dispatch
# boundary plus checkpoint-time snapshot corruption, layered over the
# write-path weather the PR-12 train leg already arms.
TRAIN_LANE_POINTS = (
    "train.carry_poison",
    "train.grad_bomb",
    "train.snapshot",
)
# The --sebulba campaign's seams (train/sebulba/queues.py,
# docs/sebulba.md): the three host transfer points between the actor
# and learner slices. Each armed 'raise' is interpreted by its seam as
# that seam's characteristic transport failure — enqueue DROPs the
# batch (a seq gap), dequeue DUPLICATEs the delivery (the seq guard
# must absorb it), param_publish holds the publish back (actors act on
# STALE params until the next version lands).
SEBULBA_POINTS = (
    "sebulba.enqueue",
    "sebulba.dequeue",
    "sebulba.param_publish",
)
# The --elastic campaign's seams (serving/elastic, docs/serving.md
# "Elastic capacity"): the three legs of a live re-split. A raise at
# prewarm aborts the round before anything routes (old split keeps
# serving); at commit it fires inside the closed barrier before the
# membership swap (one list assignment — nothing to untear); at retire
# it hits the post-commit drain worker (the retired replica stops
# undrained, its queued requests fail over to the new split).
ELASTIC_POINTS = (
    "elastic.prewarm",
    "elastic.commit",
    "elastic.retire",
)

# Hit windows per point: high-frequency seams (polls, worker loops) can
# absorb faults deep into the campaign; rare seams (one hit per commit
# or per candidate) need their faults armed early or they never fire.
WINDOWS = {
    "checkpoint.write": 3,
    "checkpoint.pre_rename": 3,
    "checkpoint.post_rename": 3,
    "ckpt_writer.submit": 3,
    "gate.eval": 2,
    "fleet.barrier": 3,
    "registry.swap": 2,
    "stream.poll": 12,
    "pipeline.poll": 12,
    "scheduler.dispatch": 12,
    # mesh: rpc legs fire a few times per commit round, heartbeats
    # continuously — same rare-vs-frequent split.
    "mesh.rpc": 4,
    "mesh.heartbeat": 12,
    # train lane: the poison points hit once per dispatch and the
    # snapshot point once per save — both frequent enough for mid-run
    # windows, but each recovery REWINDS progress, so faults must land
    # early enough that the rewound run still absorbs them all.
    "train.carry_poison": 10,
    "train.grad_bomb": 10,
    "train.snapshot": 4,
    # sebulba: enqueue/dequeue hit once per rollout, param_publish once
    # per learner chunk (rollouts / K) — windows sized so every armed
    # cell lands well inside a ~40-rollout campaign even after drops
    # shrink the consumed stream.
    "sebulba.enqueue": 10,
    "sebulba.dequeue": 10,
    "sebulba.param_publish": 6,
    # elastic: prewarm crosses once per replica build (~2 per re-split
    # round), commit once per round that survives prewarm, retire once
    # per retired replica on committed rounds — windows sized so a
    # ~6-round campaign with a few aborted rounds still fires every
    # armed cell (the flush rounds extend the campaign until it does).
    "elastic.prewarm": 8,
    "elastic.commit": 4,
    "elastic.retire": 6,
}


def build_schedule(
    seed: int,
    faults: int,
    wedge_s: float = 3.0,
    delay_s: float = 0.02,
    point_names: Optional[Tuple[str, ...]] = None,
):
    """The campaign's armed faults — a pure function of the arguments
    (the determinism the acceptance criterion pins). ``point_names``
    defaults to the single-host campaign's seams; the --mesh campaign
    passes its own set."""
    from marl_distributedformation_tpu.chaos import (
        FaultSchedule,
        INJECTION_POINTS,
    )

    if point_names is None:
        point_names = TRAIN_POINTS + SERVE_POINTS
    points = {p: INJECTION_POINTS[p] for p in point_names}
    return FaultSchedule.from_seed(
        seed,
        faults=faults,
        points=points,
        windows=WINDOWS,
        delay_s=delay_s,
        wedge_s=wedge_s,
    )


def _split(schedule, points: Tuple[str, ...]):
    from marl_distributedformation_tpu.chaos import FaultSchedule

    wanted = set(points)
    return FaultSchedule(
        [s for s in schedule.specs if s.point in wanted],
        seed=schedule.seed,
    )


class _Prober:
    """Background request stream through the router: the campaign's
    recovery witness. Each probe resolves to a success (with the served
    step) or a typed error; a future that never resolves is exactly the
    lost-request invariant violation."""

    def __init__(self, router, obs_dim: int, interval_s: float = 0.05):
        import numpy as np

        self.router = router
        self.obs = np.zeros((1, obs_dim), np.float32)
        self.interval_s = interval_s
        self.outcomes: List[dict] = []
        self.steps: List[Tuple[float, int]] = []  # (t_done, served step)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _probe_once(self) -> None:
        from concurrent.futures import TimeoutError as FutureTimeout

        t0 = time.perf_counter()
        try:
            future = self.router.submit(self.obs, timeout_s=2.0)
        except Exception as e:  # noqa: BLE001 — typed reject = resolved
            self.outcomes.append(
                {"ok": False, "hung": False, "error": type(e).__name__}
            )
            return
        try:
            result = future.result(timeout=10.0)
        except FutureTimeout:
            self.outcomes.append(
                {"ok": False, "hung": True, "error": "unresolved future"}
            )
            return
        except Exception as e:  # noqa: BLE001 — typed failure = resolved
            self.outcomes.append(
                {"ok": False, "hung": False, "error": type(e).__name__}
            )
            return
        done = time.perf_counter()
        self.outcomes.append({"ok": True, "hung": False, "error": None})
        self.steps.append((done, int(result.model_step)))
        del t0

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._probe_once()
            self._stop.wait(self.interval_s)

    def start(self) -> "_Prober":
        self._thread = threading.Thread(
            target=self._loop, name="chaos-prober", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=15.0)

    def mttr_samples(self, disruptions: List[float]) -> List[float]:
        """For each disruptive-fault time, seconds until the first
        LATER successful probe."""
        samples = []
        for t_fault in disruptions:
            after = [t for t, _ in self.steps if t > t_fault]
            if after:
                samples.append(after[0] - t_fault)
        return samples


def _measure_overhead(router, obs_dim: int, probes: int = 30) -> float:
    """Cost of the DISABLED fault plane on a served request, measured
    the only way a nanosecond-scale effect can be: the per-call cost of
    ``fault_point`` over a large tight loop (minus the same loop's own
    cost), scaled by the injection points a request crosses, relative
    to the measured request latency on the warm fleet. An A/B of whole
    request latencies cannot resolve this — scheduler coalescing noise
    is 5-6 orders of magnitude larger than one attribute read."""
    import numpy as np

    from marl_distributedformation_tpu.chaos import (
        fault_point,
        get_fault_plane,
    )

    plane = get_fault_plane()
    was_enabled = plane.enabled
    plane.enabled = False
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        fault_point("storm.overhead_probe")
    t_call = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        pass
    t_loop = time.perf_counter() - t0
    per_call_s = max(0.0, (t_call - t_loop) / n)
    # One request crosses the frontend handler, the scheduler loop, and
    # the registry-adjacent seams — call it four points, generously.
    points_per_request = 4
    obs = np.zeros((1, obs_dim), np.float32)
    latencies = []
    for _ in range(probes):
        t0 = time.perf_counter()
        router.submit(obs).result(timeout=10.0)
        latencies.append(time.perf_counter() - t0)
    lat = sorted(latencies)[len(latencies) // 2]
    plane.enabled = was_enabled
    if lat <= 0.0:
        return 0.0
    return 100.0 * points_per_request * per_call_s / lat


def run_campaign(
    seed: int = 0,
    faults: int = 25,
    workdir: Optional[str] = None,
    budget_s: float = 300.0,
    num_agents: int = 3,
    num_formations: int = 4,
    train_iterations: int = 16,
    eval_formations: int = 8,
    wedge_s: float = 3.0,
    gate_timeout_s: float = 1.5,
    probe_interval_s: float = 0.05,
) -> Dict[str, Any]:
    """One full campaign; returns the report dict (the CLI prints it as
    one JSON line). Import-safe: tests drive this directly."""
    import tempfile

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.chaos import (
        DISRUPTIVE_KINDS,
        LaneWatchdog,
        check_audit_log,
        check_budget_one,
        check_checkpoint_dir,
        check_no_request_lost,
        check_step_monotonic,
        get_fault_plane,
        report_violations,
    )
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.pipeline import (
        AlwaysLearningPipeline,
        GateConfig,
    )
    from marl_distributedformation_tpu.serving.fleet import (
        fleet_from_checkpoint_dir,
        warmup_fleet,
    )
    from marl_distributedformation_tpu.train import TrainConfig, Trainer
    from marl_distributedformation_tpu.utils.checkpoint import (
        checkpoint_step,
        restore_latest_partial,
    )

    t_start = time.perf_counter()
    deadline = t_start + budget_s
    workdir = Path(
        workdir if workdir is not None else tempfile.mkdtemp(prefix="chaos_")
    )
    log_dir = workdir / "run"
    env = EnvParams(num_agents=num_agents, max_steps=20)
    schedule = build_schedule(seed, faults, wedge_s=wedge_s)
    plane = get_fault_plane()
    plane.reset()
    report: Dict[str, Any] = {
        "deterministic": {
            "chaos_seed": int(seed),
            "chaos_faults_armed": len(schedule),
            "schedule": schedule.record(),
        },
    }
    violations = []

    # ---- phase 1: train under checkpoint-path faults -------------------
    per_iter = num_formations * num_agents * 5
    trainer = Trainer(
        env,
        ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=32),
        config=TrainConfig(
            num_formations=num_formations,
            total_timesteps=train_iterations * per_iter,
            save_freq=5,
            fused_chunk=2,
            name="chaos_storm",
            log_dir=str(log_dir),
            seed=0,
        ),
    )
    plane.arm(_split(schedule, TRAIN_POINTS))
    plane.enabled = True
    trainer.train()  # must SURVIVE the injected write failures
    plane.enabled = False
    report["train_writes_skipped"] = None  # filled from registry below

    # ---- phase 2: crash-consistent resume ------------------------------
    found = restore_latest_partial(log_dir, trainer._checkpoint_target())
    report["resume_ok"] = bool(found)
    if found is not None:
        report["resume_step"] = int(checkpoint_step(found[0]))

    # ---- phase 3: pipeline + fleet under serve-path faults -------------
    gate_cfg = GateConfig(
        scenarios=("wind",),
        severities=(1.0,),
        eval_formations=eval_formations,
        clean_tolerance=10.0,
        rung_tolerance=10.0,
    )
    pipeline = AlwaysLearningPipeline(
        log_dir, env, gate_config=gate_cfg, poll_interval_s=0.05
    )
    if not pipeline.wait_first_promotion(timeout_s=max(
        30.0, deadline - time.perf_counter()
    )):
        report["error"] = "no candidate passed the bootstrap gate"
        report["chaos_invariant_violations"] = -1
        return report
    router, coordinator = fleet_from_checkpoint_dir(
        pipeline.promoted_dir,
        env_params=env,
        act_dim=env.act_dim,
        num_replicas=2,
        buckets=(1, 8),
    )
    prober = None
    watchdog = LaneWatchdog(
        wedge_timeout_s=1.0,
        backoff_base_s=0.1,
        backoff_cap_s=2.0,
        poll_interval_s=0.1,
    )
    try:
        router.start()
        warmup_fleet(router, (env.obs_dim,))
        pipeline.attach_fleet(router, coordinator)
        # The disabled-plane overhead, measured on the warm fleet BEFORE
        # the serve-leg faults arm (both passes fault-free).
        report["fault_plane_overhead_pct"] = round(
            _measure_overhead(router, env.obs_dim), 2
        )
        # Steady-state gate evals are milliseconds at this scale; the
        # deadline only needed to outlast the bootstrap compile, which
        # already happened — now the wedge faults get a real timeout.
        pipeline.gate.config = dataclasses.replace(
            gate_cfg, gate_timeout_s=gate_timeout_s
        )
        watchdog.watch_pipeline(pipeline)
        watchdog.watch_fleet(router)
        watchdog.start()
        prober = _Prober(
            router, env.obs_dim, interval_s=probe_interval_s
        ).start()
        plane.arm(_split(schedule, SERVE_POINTS))
        plane.enabled = True
        pipeline.run(interval_s=0.05)
        # Pace: run until every serve-leg fault fired or the budget
        # ends. High-frequency seams (polls, worker loops) absorb their
        # faults on their own; the CANDIDATE-DRIVEN seams (gate eval,
        # fleet commit) only hit when a checkpoint flows — and a seed
        # whose gate faults reject every real candidate would starve
        # the commit-path cells forever. So while those cells are
        # pending, the storm keeps the candidate stream fed: byte
        # copies of the newest valid checkpoint at advancing steps
        # (exactly what a still-running trainer would provide).
        import shutil

        from marl_distributedformation_tpu.utils.checkpoint import (
            checkpoint_path,
            latest_checkpoint,
        )

        candidate_points = ("gate.eval", "fleet.barrier", "registry.swap")
        synth_src = found[0] if found is not None else None
        newest = latest_checkpoint(log_dir)
        synth_step = checkpoint_step(newest) if newest is not None else 0
        synth_last, synth_count = time.perf_counter(), 0
        while (
            plane.pending(SERVE_POINTS) > 0
            and time.perf_counter() < deadline
        ):
            time.sleep(0.1)
            if (
                synth_src is not None
                and plane.pending(candidate_points) > 0
                and time.perf_counter() - synth_last > 1.5
                and synth_count < 24
            ):
                synth_step += per_iter
                dst = checkpoint_path(log_dir, synth_step)
                tmp = dst.with_name(f".{dst.name}.tmp")
                shutil.copyfile(synth_src, tmp)
                tmp.replace(dst)
                pipeline.stream.nudge()
                synth_last = time.perf_counter()
                synth_count += 1
        # Grace so recovery from the LAST fault is observable.
        time.sleep(max(2.0, wedge_s * 0.75))
        plane.enabled = False
        pipeline.stop()
        watchdog.stop()
        prober.stop()
    finally:
        plane.enabled = False
        if prober is not None:
            prober.stop()
        watchdog.stop()
        pipeline.stop()
        router.stop()

    # ---- phase 4: invariants -------------------------------------------
    fired = plane.fired_record()
    disruptions = [
        f["t"]
        for f in plane.fired
        if f["kind"] in DISRUPTIVE_KINDS and f["point"] in SERVE_POINTS
    ]
    mttr = prober.mttr_samples(disruptions)
    violations += check_step_monotonic(
        prober.steps,
        rollback_to_steps=[r["to_step"] for r in pipeline.rollbacks],
    )
    violations += check_no_request_lost(prober.outcomes)
    compiles = {
        "gate_matrix": (
            pipeline.gate.program.compile_count
            if pipeline.gate.program is not None
            else 0
        ),
    }
    for replica, per_rung in router.compile_counts().items():
        for rung, count in per_rung.items():
            compiles[f"replica{replica}_rung{rung}"] = count
    violations += check_budget_one(compiles)
    violations += check_audit_log(log_dir / "promotions.jsonl")
    violations += check_checkpoint_dir(log_dir)
    violations += check_checkpoint_dir(pipeline.promoted_dir)
    from marl_distributedformation_tpu.chaos import Violation

    if disruptions and not mttr:
        violations.append(
            Violation(
                "recovery",
                f"{len(disruptions)} disruptive fault(s) fired but no "
                "probe ever succeeded afterwards — the fleet never "
                "recovered",
            )
        )
    report["chaos_violations"] = report_violations(violations, plane)
    report["chaos_invariant_violations"] = len(violations)
    report["chaos_faults_fired"] = len(fired)
    report["chaos_faults_unfired"] = plane.pending()
    if mttr:
        report["chaos_mttr_s"] = round(max(mttr), 3)
        report["chaos_mttr_p50_s"] = round(sorted(mttr)[len(mttr) // 2], 3)
    report["chaos_disruptions"] = len(disruptions)
    report["probes_total"] = len(prober.outcomes)
    report["probes_ok"] = sum(1 for o in prober.outcomes if o["ok"])
    report["promotions"] = len(pipeline.promotions)
    report["rejections"] = len(pipeline.rejections)
    report["gate_timeouts"] = sum(
        1 for v in pipeline.rejections if v.timed_out
    )
    report["pipeline_restarts"] = watchdog.restarts_total()
    from marl_distributedformation_tpu.obs import get_registry

    snap = get_registry().snapshot()
    report["train_writes_skipped"] = int(
        snap.get("checkpoint_writes_skipped_total", 0)
    )
    report["checkpoints_quarantined"] = int(
        snap.get("checkpoint_quarantined_total", 0)
    )
    report["campaign_seconds"] = round(time.perf_counter() - t_start, 2)
    return report


def run_train_campaign(
    seed: int = 0,
    faults: int = 10,
    workdir: Optional[str] = None,
    budget_s: float = 240.0,
    num_agents: int = 3,
    num_formations: int = 4,
    train_iterations: int = 40,
    fused_chunk: int = 2,
    mttr_bound_s: float = 60.0,
) -> Dict[str, Any]:
    """The storm pointed at the TRAIN lane (train/recovery.py,
    docs/recovery.md): a fused-scan Trainer with the in-program health
    word and the recovery ladder armed runs to completion while the
    seeded schedule drives NaN carry bombs, finite grad bombs, and
    checkpoint-time snapshot corruption through the dispatch boundary
    (plus the PR-12 write-path weather). The campaign then checks the
    lane's invariants: crash-consistent checkpoint dir, NO non-finite
    checkpoint ever visible to discovery, the run terminated on finite
    params without halting, recovery MTTR bounded, budget-1 compile
    receipts with health + chaos both ON. One JSON line out."""
    import tempfile

    import numpy as np

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.chaos import (
        Violation,
        check_budget_one,
        check_checkpoint_dir,
        check_final_params_finite,
        check_finite_checkpoints,
        check_recovery_log,
        get_fault_plane,
        report_violations,
    )
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import (
        TrainConfig,
        Trainer,
        read_recovery_log,
    )

    t_start = time.perf_counter()
    workdir = Path(
        workdir
        if workdir is not None
        else tempfile.mkdtemp(prefix="chaos_train_")
    )
    log_dir = workdir / "run"
    env = EnvParams(num_agents=num_agents, max_steps=20)
    train_points = TRAIN_LANE_POINTS + TRAIN_POINTS
    schedule = build_schedule(
        seed,
        faults,
        point_names=train_points,
    )
    plane = get_fault_plane()
    plane.reset()
    report: Dict[str, Any] = {
        "deterministic": {
            "chaos_seed": int(seed),
            "chaos_faults_armed": len(schedule),
            "schedule": schedule.record(),
        },
    }
    violations: List[Violation] = []

    # One campaign leg: the REAL fused driver (dispatch N+1, drain N,
    # detect at the drain, roll back, keep going) runs its whole budget
    # under the armed schedule. Every rollback REWINDS num_timesteps, so
    # the loop self-extends past each recovery — the hit windows above
    # guarantee every fault lands well inside the budget.
    per_iter = num_formations * num_agents * 5
    max_rollbacks = max(8, faults)
    trainer = Trainer(
        env,
        ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=32),
        config=TrainConfig(
            num_formations=num_formations,
            total_timesteps=train_iterations * per_iter,
            save_freq=5,
            fused_chunk=fused_chunk,
            name="chaos_train_storm",
            log_dir=str(log_dir),
            seed=0,
            health=True,
            recovery=True,
            recovery_breach_iters=2,
            recovery_max_rollbacks=max_rollbacks,
            keep_last_n=6,
        ),
    )
    plane.arm(schedule)
    plane.enabled = True
    try:
        trainer.train()  # must SURVIVE every bomb and finish finite
    finally:
        # An escaping exception must not leave the PROCESS-GLOBAL plane
        # live — anything running after this campaign (another leg, an
        # embedding caller) would silently train under fault injection.
        plane.enabled = False

    # ---- invariants ----------------------------------------------------
    fired = plane.fired_record()
    unfired = plane.pending()
    ladder = trainer.recovery_ladder
    events = read_recovery_log(log_dir / "recovery.jsonl")
    mttr = [
        float(e["mttr_s"]) for e in events if e["event"] == "rollback"
    ]
    violations += check_checkpoint_dir(log_dir)
    violations += check_finite_checkpoints(log_dir)
    violations += check_recovery_log(
        log_dir / "recovery.jsonl",
        # +1: the run-end finite-params guarantee may restore once past
        # the retry budget (Trainer._ensure_finite_final_state) — a
        # legitimate terminal rollback, not a breached budget.
        max_rollbacks=max_rollbacks + 1,
        mttr_bound_s=mttr_bound_s,
    )
    violations += check_final_params_finite(
        jax_device_get_params(trainer)
    )
    violations += check_budget_one(
        {"train_iteration": trainer.retrace_guard.count}
    )
    if trainer.halted:
        violations.append(
            Violation(
                "train_halt",
                "the campaign's faults are all recoverable but the run "
                "HALTED — the ladder burned its rollback budget on "
                "faults it should have absorbed",
            )
        )
    poison_fired = [
        f for f in fired
        if f["point"] in ("train.carry_poison", "train.grad_bomb")
        and f["kind"] == "raise"
    ]
    if poison_fired and (ladder is None or ladder.recoveries == 0):
        violations.append(
            Violation(
                "recovery",
                f"{len(poison_fired)} poison fault(s) fired but the "
                "ladder never rolled back — divergence went undetected",
            )
        )
    if unfired:
        violations.append(
            Violation(
                "campaign_coverage",
                f"{unfired} armed fault(s) never fired — the campaign "
                "ended before exercising its whole schedule (raise "
                "train_iterations or lower the hit windows)",
            )
        )
    report["chaos_violations"] = report_violations(violations, plane)
    report["chaos_invariant_violations"] = len(violations)
    report["chaos_faults_fired"] = len(fired)
    report["chaos_faults_unfired"] = unfired
    report["train_recoveries"] = ladder.recoveries if ladder else 0
    report["train_divergence_events"] = ladder.breaches if ladder else 0
    report["train_skipped_updates"] = (
        ladder.skipped_total if ladder else 0
    )
    report["train_halted"] = bool(trainer.halted)
    if mttr:
        report["recovery_mttr_s"] = round(max(mttr), 3)
        report["recovery_mttr_p50_s"] = round(
            sorted(mttr)[len(mttr) // 2], 3
        )
    from marl_distributedformation_tpu.obs import get_registry

    snap = get_registry().snapshot()
    report["train_writes_skipped"] = int(
        snap.get("checkpoint_writes_skipped_total", 0)
    )
    report["checkpoints_nonfinite_skipped"] = int(
        snap.get("checkpoint_nonfinite_skipped_total", 0)
    )
    report["checkpoints_quarantined"] = int(
        snap.get("checkpoint_quarantined_total", 0)
    )
    report["checkpoints_pruned"] = int(
        snap.get("checkpoint_pruned_total", 0)
    )
    report["final_timesteps"] = int(trainer.num_timesteps)
    report["campaign_seconds"] = round(time.perf_counter() - t_start, 2)
    del budget_s  # the fused run is budget-bound by its iteration count
    return report


def jax_device_get_params(trainer):
    """Host copy of the trainer's params (the final-finiteness
    witness)."""
    import jax

    return jax.device_get(trainer.train_state.params)


def run_sebulba_campaign(
    seed: int = 0,
    faults: int = 10,
    workdir: Optional[str] = None,
    budget_s: float = 240.0,
    num_agents: int = 3,
    num_formations: int = 4,
    train_iterations: int = 40,
    fused_chunk: int = 2,
    transfer_queue_depth: int = 2,
    max_param_staleness: int = 2,
) -> Dict[str, Any]:
    """The storm pointed at the SEBULBA transfer seams (train/sebulba/,
    docs/sebulba.md): a pipelined actor/learner run completes its whole
    timestep budget while the seeded schedule drops trajectory batches
    at the enqueue seam, redelivers them at the dequeue seam, and holds
    params publishes back at the bus — then the lane's contracts are
    checked over the run's host artifacts: no trajectory consumed
    twice, params versions monotone at the consumer, staleness of every
    CONSUMED batch bounded by ``max_param_staleness``, budget-1 compile
    receipts per slice, crash-consistent checkpoint dir, finite final
    params. One JSON line out."""
    import tempfile

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.chaos import (
        Violation,
        check_bounded_staleness,
        check_budget_one,
        check_checkpoint_dir,
        check_final_params_finite,
        check_no_duplicate_consume,
        check_params_version_monotone,
        get_fault_plane,
        report_violations,
    )
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import (
        SebulbaDriver,
        TrainConfig,
    )

    t_start = time.perf_counter()
    workdir = Path(
        workdir
        if workdir is not None
        else tempfile.mkdtemp(prefix="chaos_sebulba_")
    )
    log_dir = workdir / "run"
    env = EnvParams(num_agents=num_agents, max_steps=20)
    schedule = build_schedule(seed, faults, point_names=SEBULBA_POINTS)
    plane = get_fault_plane()
    plane.reset()
    report: Dict[str, Any] = {
        "deterministic": {
            "chaos_seed": int(seed),
            "chaos_faults_armed": len(schedule),
            "schedule": schedule.record(),
        },
    }
    violations: List[Violation] = []

    # One leg: the pipelined driver runs its whole budget (counted at
    # the actor) under the armed transfer weather. Dropped batches slow
    # the learner, never the budget; held-back publishes raise measured
    # staleness, and the staleness gate must keep every batch that
    # REACHES an update inside the bound.
    per_iter = num_formations * num_agents * 5
    driver = SebulbaDriver(
        env,
        ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=32),
        config=TrainConfig(
            num_formations=num_formations,
            total_timesteps=train_iterations * per_iter,
            save_freq=5,
            fused_chunk=fused_chunk,
            name="chaos_sebulba_storm",
            log_dir=str(log_dir),
            seed=0,
            architecture="sebulba",
            transfer_queue_depth=transfer_queue_depth,
            max_param_staleness=max_param_staleness,
        ),
    )
    plane.arm(schedule)
    plane.enabled = True
    try:
        driver.train()  # must SURVIVE every transport failure
    finally:
        # Never leave the process-global plane live past the campaign.
        plane.enabled = False

    # ---- invariants ----------------------------------------------------
    fired = plane.fired_record()
    unfired = plane.pending()
    queue = driver.transfer_queue
    bus = driver.param_bus
    violations += check_no_duplicate_consume(queue.consumed_seqs)
    violations += check_params_version_monotone(driver.consumed_versions)
    violations += check_bounded_staleness(
        driver.consumed_staleness, max_param_staleness
    )
    violations += check_budget_one(
        {
            "sebulba_actor_rollout": driver.actor_guard.count,
            "sebulba_learner_chunk": driver.learner_guard.count,
        }
    )
    violations += check_checkpoint_dir(log_dir)
    violations += check_final_params_finite(jax_device_get_params(driver))
    dup_fired = [
        f
        for f in fired
        if f["point"] == "sebulba.dequeue" and f["kind"] == "raise"
    ]
    if dup_fired and queue.duplicates_absorbed == 0:
        violations.append(
            Violation(
                "no_duplicate_consume",
                f"{len(dup_fired)} dequeue redelivery fault(s) fired but "
                "the queue never absorbed a duplicate — the seq guard "
                "was not exercised (the redelivery path is dead code "
                "under this campaign)",
            )
        )
    if unfired:
        violations.append(
            Violation(
                "campaign_coverage",
                f"{unfired} armed fault(s) never fired — the campaign "
                "ended before exercising its whole schedule (raise "
                "train_iterations or lower the hit windows)",
            )
        )
    report["chaos_violations"] = report_violations(violations, plane)
    report["chaos_invariant_violations"] = len(violations)
    report["chaos_faults_fired"] = len(fired)
    report["chaos_faults_unfired"] = unfired
    report["sebulba_batches_enqueued"] = int(queue.enqueued_total)
    report["sebulba_batches_dropped"] = int(queue.dropped_total)
    report["sebulba_duplicates_absorbed"] = int(queue.duplicates_absorbed)
    report["sebulba_publishes_dropped"] = int(bus.publishes_dropped)
    report["sebulba_stale_dropped"] = int(driver.stale_dropped)
    report["sebulba_batches_consumed"] = len(queue.consumed_seqs)
    report["transfer_queue_occupancy_p95"] = round(
        driver.occupancy_p95(), 2
    )
    report["param_staleness_p95_updates"] = round(
        driver.staleness_p95(), 2
    )
    report["sebulba_actor_compiles"] = int(driver.actor_guard.count)
    report["sebulba_learner_compiles"] = int(driver.learner_guard.count)
    report["final_timesteps"] = int(driver.num_timesteps)
    report["campaign_seconds"] = round(time.perf_counter() - t_start, 2)
    del budget_s  # the pipelined run is bounded by its timestep budget
    return report


def _widen_cpu_devices(n: int) -> None:
    """Best-effort CPU device-pool widening (mirrors serve_policy.py's
    _ensure_cpu_devices): the elastic campaign wants >= 2 devices so
    re-splits exercise the sharded slice path, but runs honestly on
    whatever pool it gets."""
    import os

    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    if len(jax.local_devices()) >= n or jax.default_backend() != "cpu":
        return
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except (AttributeError, RuntimeError):
        try:
            import jax.extend.backend as jeb

            jeb.clear_backends()
        except Exception:  # noqa: BLE001 — widening is best-effort
            pass


def run_elastic_campaign(
    seed: int = 0,
    faults: int = 9,
    budget_s: float = 240.0,
    obs_dim: int = 8,
    rounds: int = 6,
    requests_per_round: int = 60,
    probe_interval_s: float = 0.03,
) -> Dict[str, Any]:
    """The storm pointed at the elastic re-split seams
    (serving/elastic, docs/serving.md "Elastic capacity"): a live fleet
    serves alternating traffic mixes while a ``CapacityController``
    re-splits it round after round, with the seeded schedule raising
    and delaying at the prewarm, barrier-commit, and drain-retire legs.
    Invariants: every accepted request resolves (aborted rounds keep
    the old split serving; retire faults stop replicas undrained and
    their queued work must fail over), served steps stay monotonic
    through every commit, budget-1 compile receipts on the final
    replica set, at least 2 re-splits actually committed, and every
    armed fault fired. One JSON line out."""
    import tempfile

    import numpy as np

    from marl_distributedformation_tpu.chaos import (
        Violation,
        check_budget_one,
        check_no_request_lost,
        check_step_monotonic,
        get_fault_plane,
        report_violations,
    )
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.serving import TraceRecorder
    from marl_distributedformation_tpu.serving.elastic import (
        CapacityController,
    )
    from marl_distributedformation_tpu.serving.fleet import (
        FleetReloadCoordinator,
        FleetRouter,
        warmup_fleet,
    )

    t_start = time.perf_counter()
    deadline = t_start + budget_s
    _widen_cpu_devices(2)
    import jax
    import jax.numpy as jnp

    from marl_distributedformation_tpu.models import MLPActorCritic

    model = MLPActorCritic(act_dim=2, hidden=(8, 8))
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, obs_dim))
    )
    policy = LoadedPolicy(
        dict(variables), model_kwargs={"hidden": (8, 8)}
    )

    schedule = build_schedule(seed, faults, point_names=ELASTIC_POINTS)
    plane = get_fault_plane()
    plane.reset()
    report: Dict[str, Any] = {
        "deterministic": {
            "chaos_seed": int(seed),
            "chaos_faults_armed": len(schedule),
            "schedule": schedule.record(),
        },
    }
    violations: List[Violation] = []

    recorder = TraceRecorder()
    router = FleetRouter(
        policy,
        num_replicas=2,
        buckets=(1, 8),
        window_ms=0.0,
        trace_recorder=recorder,
    )
    workdir = tempfile.mkdtemp(prefix="chaos_elastic_")
    coordinator = FleetReloadCoordinator(workdir, router)
    controller = CapacityController(
        router,
        coordinator,
        row_shape=(obs_dim,),
        p95_target_ms=50.0,
        min_requests=24,
        drain_timeout_s=5.0,
    )
    # Three mixes cycling, so a round's plan always differs from the
    # last COMMITTED one even when the round in between aborted (same
    # mix two rounds apart would plans_equivalent-skip and starve the
    # armed commit/retire cells).
    mixes = (
        ((1, 0.6), (4, 0.4)),
        ((64, 0.5), (128, 0.5)),
        ((8, 0.5), (16, 0.5)),
    )
    rng = np.random.default_rng(seed)
    outcomes: List[dict] = []
    steps: List[Tuple[float, int]] = []

    def _drive_round(mix) -> None:
        """One round of offered traffic; every accepted future must
        resolve (collected for the no-lost-request invariant)."""
        sizes = [s for s, _ in mix]
        probs = [p for _, p in mix]
        futures = []
        for _ in range(requests_per_round):
            n = int(rng.choice(sizes, p=probs))
            obs = rng.standard_normal((n, obs_dim)).astype(np.float32)
            try:
                futures.append(router.submit(obs, timeout_s=5.0))
            except Exception as e:  # noqa: BLE001 — typed reject
                outcomes.append(
                    {"ok": False, "hung": False, "error": type(e).__name__}
                )
            time.sleep(0.002)
        for f in futures:
            try:
                result = f.result(timeout=15.0)
            except FutureTimeout:
                outcomes.append(
                    {
                        "ok": False,
                        "hung": True,
                        "error": "unresolved future",
                    }
                )
                continue
            except Exception as e:  # noqa: BLE001 — typed failure
                outcomes.append(
                    {"ok": False, "hung": False, "error": type(e).__name__}
                )
                continue
            outcomes.append({"ok": True, "hung": False, "error": None})
            steps.append((time.perf_counter(), int(result.model_step)))

    from concurrent.futures import TimeoutError as FutureTimeout

    prober = None
    rounds_run = 0
    try:
        router.start()
        warmup_fleet(router, (obs_dim,))
        plane.arm(schedule)
        plane.enabled = True
        prober = _Prober(
            router, obs_dim, interval_s=probe_interval_s
        ).start()
        # Scheduled rounds, then flush rounds until every armed fault
        # fired (an aborted prewarm consumes no commit/retire cells, so
        # the campaign keeps re-splitting until the schedule drains).
        while rounds_run < rounds or (
            plane.pending(ELASTIC_POINTS) > 0
            and rounds_run < rounds + 6
            and time.perf_counter() < deadline - 10
        ):
            mix = mixes[rounds_run % len(mixes)]
            recorder.clear()  # each round decides from ITS mix alone
            _drive_round(mix)
            controller.step()
            rounds_run += 1
    finally:
        # Never leave the process-global plane live past the campaign.
        plane.enabled = False
        if prober is not None:
            prober.stop()
        router.stop()

    # ---- invariants ----------------------------------------------------
    fired = plane.fired_record()
    unfired = plane.pending()
    violations += check_no_request_lost(outcomes + prober.outcomes)
    violations += check_step_monotonic(
        sorted(steps + prober.steps, key=lambda s: s[0])
    )
    compiles = {
        f"replica{idx}_rung{bucket}": count
        for idx, counts in router.compile_counts().items()
        for bucket, count in counts.items()
    }
    violations += check_budget_one(compiles)
    snap = controller.snapshot()
    if snap["elastic_resplits_committed"] < 2:
        violations.append(
            Violation(
                "campaign_coverage",
                f"only {snap['elastic_resplits_committed']:.0f} "
                "re-split(s) committed — the campaign never exercised "
                "the commit seam under weather (raise rounds or lower "
                "the fault count)",
            )
        )
    if unfired:
        violations.append(
            Violation(
                "campaign_coverage",
                f"{unfired} armed fault(s) never fired — the campaign "
                "ended before exercising its whole schedule (raise "
                "rounds or lower the hit windows)",
            )
        )
    report["chaos_violations"] = report_violations(violations, plane)
    report["chaos_invariant_violations"] = len(violations)
    report["chaos_faults_fired"] = len(fired)
    report["chaos_faults_unfired"] = unfired
    report["elastic_rounds"] = rounds_run
    report["elastic_resplits_committed"] = int(
        snap["elastic_resplits_committed"]
    )
    report["elastic_resplits_aborted"] = int(
        snap["elastic_resplits_aborted"]
    )
    report["elastic_resplits_skipped"] = int(
        snap["elastic_resplits_skipped"]
    )
    report["elastic_prewarm_compiles"] = int(
        snap["elastic_prewarm_compiles_total"]
    )
    report["elastic_last_pause_ms"] = snap["elastic_last_pause_ms"]
    report["requests_resolved"] = len(outcomes) + len(
        prober.outcomes
    )
    report["requests_ok"] = sum(
        1 for o in outcomes + prober.outcomes if o["ok"]
    )
    report["final_replicas"] = len(router.replicas)
    report["campaign_seconds"] = round(time.perf_counter() - t_start, 2)
    return report


def run_mesh_campaign(
    seed: int = 0,
    faults: int = 20,
    hosts: int = 2,
    workdir: Optional[str] = None,
    budget_s: float = 300.0,
    num_agents: int = 3,
    num_formations: int = 4,
    train_iterations: int = 16,
    eval_formations: int = 8,
    wedge_s: float = 2.0,
    gate_timeout_s: float = 1.5,
    probe_interval_s: float = 0.05,
) -> Dict[str, Any]:
    """The storm pointed at a loopback multi-process mesh (ROADMAP item
    1's transfer test): the SAME invariant checkers, now with the fleet
    spread over ``hosts`` real subprocesses, the control-plane faults
    armed in this process, and a real ``kill -9`` of one host
    mid-storm instead of a ``SimulatedCrash``. One JSON line out, same
    shape as :func:`run_campaign` plus the ``mesh_*`` fields."""
    import shutil
    import signal
    import tempfile

    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.chaos import (
        DISRUPTIVE_KINDS,
        LaneWatchdog,
        Violation,
        check_audit_log,
        check_budget_one,
        check_checkpoint_dir,
        check_no_request_lost,
        check_step_monotonic,
        get_fault_plane,
        report_violations,
    )
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.pipeline import (
        AlwaysLearningPipeline,
        GateConfig,
    )
    from marl_distributedformation_tpu.serving.mesh import spawn_local_mesh
    from marl_distributedformation_tpu.train import TrainConfig, Trainer
    from marl_distributedformation_tpu.utils.checkpoint import (
        checkpoint_path,
        checkpoint_step,
        latest_checkpoint,
        restore_latest_partial,
    )

    t_start = time.perf_counter()
    deadline = t_start + budget_s
    workdir = Path(
        workdir
        if workdir is not None
        else tempfile.mkdtemp(prefix="chaos_mesh_")
    )
    log_dir = workdir / "run"
    env = EnvParams(num_agents=num_agents, max_steps=20)
    schedule = build_schedule(
        seed,
        faults,
        wedge_s=wedge_s,
        point_names=TRAIN_POINTS + MESH_SERVE_POINTS,
    )
    plane = get_fault_plane()
    plane.reset()
    report: Dict[str, Any] = {
        "deterministic": {
            "chaos_seed": int(seed),
            "chaos_faults_armed": len(schedule),
            "schedule": schedule.record(),
        },
        "mesh_hosts": int(hosts),
    }
    violations: List[Violation] = []

    # ---- phase 1: train under checkpoint-path faults -------------------
    per_iter = num_formations * num_agents * 5
    trainer = Trainer(
        env,
        ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=32),
        config=TrainConfig(
            num_formations=num_formations,
            total_timesteps=train_iterations * per_iter,
            save_freq=5,
            fused_chunk=2,
            name="chaos_mesh_storm",
            log_dir=str(log_dir),
            seed=0,
        ),
    )
    plane.arm(_split(schedule, TRAIN_POINTS))
    plane.enabled = True
    trainer.train()  # must SURVIVE the injected write failures
    plane.enabled = False

    # ---- phase 2: crash-consistent resume ------------------------------
    found = restore_latest_partial(log_dir, trainer._checkpoint_target())
    report["resume_ok"] = bool(found)

    # ---- phase 3: bootstrap the pipeline, then the mesh ----------------
    gate_cfg = GateConfig(
        scenarios=("wind",),
        severities=(1.0,),
        eval_formations=eval_formations,
        clean_tolerance=10.0,
        rung_tolerance=10.0,
    )
    pipeline = AlwaysLearningPipeline(
        log_dir, env, gate_config=gate_cfg, poll_interval_s=0.05
    )
    if not pipeline.wait_first_promotion(
        timeout_s=max(30.0, deadline - time.perf_counter())
    ):
        report["error"] = "no candidate passed the bootstrap gate"
        report["chaos_invariant_violations"] = -1
        return report
    mesh = spawn_local_mesh(
        pipeline.promoted_dir,
        hosts=hosts,
        buckets=(1, 8),
        num_agents=num_agents,
        heartbeat_s=0.2,
        lease_s=0.8,
        dead_after_s=0.8,
        probe_interval_s=0.5,
        ready_timeout_s=max(30.0, deadline - time.perf_counter()),
    )
    prober = None
    killed_host = None
    t_kill = None
    # The pipeline lane is the only in-process lane to supervise — the
    # hosts are separate processes whose death IS the scenario (the
    # coordinator's lease taxonomy owns declaring it).
    watchdog = LaneWatchdog(
        wedge_timeout_s=1.0,
        backoff_base_s=0.1,
        backoff_cap_s=2.0,
        poll_interval_s=0.1,
    )
    try:
        pipeline.attach_fleet(mesh.router, mesh.coordinator)
        pipeline.gate.config = dataclasses.replace(
            gate_cfg, gate_timeout_s=gate_timeout_s
        )
        watchdog.watch_pipeline(pipeline)
        watchdog.start()
        prober = _Prober(
            mesh.router, env.obs_dim, interval_s=probe_interval_s
        ).start()
        plane.arm(_split(schedule, MESH_SERVE_POINTS))
        plane.enabled = True
        pipeline.run(interval_s=0.05)
        # Pace like the single-host storm: keep the candidate stream
        # fed while commit-path cells are pending, and mid-storm drop
        # the hammer — a REAL SIGKILL of one host subprocess.
        candidate_points = ("gate.eval", "mesh.rpc")
        synth_src = found[0] if found is not None else None
        newest = latest_checkpoint(log_dir)
        synth_step = checkpoint_step(newest) if newest is not None else 0
        synth_last, synth_count = time.perf_counter(), 0
        kill_at = time.perf_counter() + 3.0
        # Pace until every serve-leg fault fired AND at least one
        # coordinator-driven global swap LANDED (swap_count counts
        # commits that served; commit_round counts attempts including
        # aborts — an all-abort campaign must keep waiting) — or the
        # budget ends.
        while (
            plane.pending(MESH_SERVE_POINTS) > 0
            or mesh.coordinator.swap_count == 0
        ) and time.perf_counter() < deadline:
            time.sleep(0.1)
            if killed_host is None and time.perf_counter() >= kill_at:
                t_kill = time.perf_counter()
                killed_host = mesh.kill_host(0, sig=signal.SIGKILL)
            if (
                synth_src is not None
                and plane.pending(candidate_points) > 0
                and time.perf_counter() - synth_last > 1.0
                and synth_count < 24
            ):
                synth_step += per_iter
                dst = checkpoint_path(log_dir, synth_step)
                tmp = dst.with_name(f".{dst.name}.tmp")
                shutil.copyfile(synth_src, tmp)
                tmp.replace(dst)
                pipeline.stream.nudge()
                synth_last = time.perf_counter()
                synth_count += 1
        if killed_host is None:
            # Every fault fired before the timer — the kill is still
            # owed (it IS the campaign's headline disruption).
            t_kill = time.perf_counter()
            killed_host = mesh.kill_host(0, sig=signal.SIGKILL)
        time.sleep(max(2.0, wedge_s))
        plane.enabled = False
        pipeline.stop()
        watchdog.stop()
        prober.stop()
    finally:
        plane.enabled = False
        if prober is not None:
            prober.stop()
        watchdog.stop()
        pipeline.stop()
        receipts = mesh.router.host_compile_counts()
        mesh_snapshot = mesh.router.snapshot()
        mesh_swaps_landed = mesh.coordinator.swap_count
        host_states = {
            h["host_id"]: h["state"] for h in mesh.coordinator.hosts()
        }
        mesh.stop()

    # ---- phase 4: invariants (the PR-12 suite, unchanged) --------------
    fired = plane.fired_record()
    disruptions = [
        f["t"]
        for f in plane.fired
        if f["kind"] in DISRUPTIVE_KINDS and f["point"] in MESH_SERVE_POINTS
    ]
    if t_kill is not None:
        disruptions.append(t_kill)  # the kill -9 IS a disruption
    mttr = prober.mttr_samples(disruptions)
    violations += check_step_monotonic(
        prober.steps,
        rollback_to_steps=[r["to_step"] for r in pipeline.rollbacks],
    )
    violations += check_no_request_lost(prober.outcomes)
    compiles = {
        "gate_matrix": (
            pipeline.gate.program.compile_count
            if pipeline.gate.program is not None
            else 0
        ),
    }
    for host_id, per_rung in receipts.items():
        for rung, count in per_rung.items():
            compiles[f"{host_id}_{rung}"] = int(count)
    violations += check_budget_one(compiles)
    violations += check_audit_log(log_dir / "promotions.jsonl")
    violations += check_checkpoint_dir(log_dir)
    violations += check_checkpoint_dir(pipeline.promoted_dir)
    if disruptions and not mttr:
        violations.append(
            Violation(
                "recovery",
                f"{len(disruptions)} disruption(s) (incl. the host "
                "kill) but no probe ever succeeded afterwards — the "
                "mesh never recovered",
            )
        )
    if killed_host is not None and host_states.get(killed_host) != "dead":
        violations.append(
            Violation(
                "gossip",
                f"killed host {killed_host} never declared dead "
                f"(state: {host_states.get(killed_host)!r}) — the "
                "lease/suspect/dead taxonomy missed a real SIGKILL",
            )
        )
    if mesh_swaps_landed == 0:
        violations.append(
            Violation(
                "global_commit",
                "no coordinator-driven global swap LANDED during the "
                "campaign (aborted rounds don't count) — the "
                "monotonicity witness never crossed a cross-host "
                "commit, so the acceptance criterion was not exercised",
            )
        )
    report["chaos_violations"] = report_violations(violations, plane)
    report["chaos_invariant_violations"] = len(violations)
    report["chaos_faults_fired"] = len(fired)
    report["chaos_faults_unfired"] = plane.pending()
    if mttr:
        report["chaos_mttr_s"] = round(max(mttr), 3)
        report["chaos_mttr_p50_s"] = round(sorted(mttr)[len(mttr) // 2], 3)
    report["chaos_disruptions"] = len(disruptions)
    report["probes_total"] = len(prober.outcomes)
    report["probes_ok"] = sum(1 for o in prober.outcomes if o["ok"])
    report["promotions"] = len(pipeline.promotions)
    report["rejections"] = len(pipeline.rejections)
    report["pipeline_restarts"] = watchdog.restarts_total()
    report["mesh_host_killed"] = killed_host
    report["mesh_host_states"] = host_states
    report["mesh_commit_rounds"] = int(
        mesh_snapshot.get("mesh_commit_rounds", 0)
    )
    report["mesh_global_swaps"] = int(mesh_swaps_landed)
    report["mesh_failed_over_total"] = int(
        mesh_snapshot.get("mesh_failed_over_total", 0)
    )
    report["mesh_final_step"] = int(mesh_snapshot.get("mesh_step", -1))
    report["campaign_seconds"] = round(time.perf_counter() - t_start, 2)
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--faults", type=int, default=25)
    ap.add_argument("--budget-s", type=float, default=300.0)
    ap.add_argument("--workdir", default=None)
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="point the storm at a loopback multi-process mesh "
        "(serving/mesh): control-plane faults in this process plus a "
        "real kill -9 of one host subprocess; the PR-12 invariant "
        "suite runs unchanged",
    )
    ap.add_argument(
        "--hosts", type=int, default=2,
        help="with --mesh: host subprocesses to spawn",
    )
    ap.add_argument(
        "--train",
        action="store_true",
        help="point the storm at the TRAIN lane (train/recovery.py): "
        "NaN carry bombs, finite grad bombs, and checkpoint-time "
        "snapshot corruption through a live fused run with the health "
        "word + recovery ladder armed; invariants: crash-consistent "
        "dir, no non-finite checkpoint visible, finite finish, bounded "
        "MTTR, budget-1 receipts",
    )
    ap.add_argument(
        "--sebulba",
        action="store_true",
        help="point the storm at the sebulba transfer seams "
        "(train/sebulba): batch drops at enqueue, redeliveries at "
        "dequeue, held-back params publishes at the bus, through a "
        "live pipelined actor/learner run; invariants: no trajectory "
        "consumed twice, params versions monotone, bounded staleness "
        "on every consumed batch, budget-1 receipts per slice",
    )
    ap.add_argument(
        "--elastic",
        action="store_true",
        help="point the storm at the elastic re-split seams "
        "(serving/elastic): raises and delays at prewarm, at the "
        "barrier commit, and at drain-retire while a CapacityController "
        "re-splits a live fleet under alternating traffic mixes; "
        "invariants: no accepted request lost, served steps monotone "
        "through every commit, budget-1 compile receipts, >= 2 "
        "committed re-splits, full schedule coverage",
    )
    ap.add_argument(
        "--print-schedule",
        action="store_true",
        help="emit the armed fault schedule (deterministic from the "
        "seed) and exit without running anything",
    )
    args = ap.parse_args(argv)
    exclusive = [
        name
        for name, on in (
            ("--mesh", args.mesh),
            ("--train", args.train),
            ("--sebulba", args.sebulba),
            ("--elastic", args.elastic),
        )
        if on
    ]
    if len(exclusive) > 1:
        ap.error(
            f"{' and '.join(exclusive)} are separate campaigns; pick one"
        )
    if args.elastic:
        elastic_faults = min(args.faults, 9)
        if elastic_faults < args.faults:
            print(
                f"[storm] --elastic caps --faults at 9 (requested "
                f"{args.faults}): the three re-split seams' armable "
                "cells are bounded by the hit windows",
                file=sys.stderr,
            )
        if args.print_schedule:
            schedule = build_schedule(
                args.seed, elastic_faults, point_names=ELASTIC_POINTS
            )
            print(json.dumps({
                "chaos_seed": args.seed,
                "chaos_faults_armed": len(schedule),
                "schedule": schedule.record(),
            }))
            return 0
        report = run_elastic_campaign(
            seed=args.seed,
            faults=elastic_faults,
            budget_s=args.budget_s,
        )
        print(json.dumps(report))
        return 0 if report.get("chaos_invariant_violations") == 0 else 1
    if args.sebulba:
        sebulba_faults = min(args.faults, 12)
        if sebulba_faults < args.faults:
            print(
                f"[storm] --sebulba caps --faults at 12 (requested "
                f"{args.faults}): the three transfer seams' armable "
                "cells are bounded by the hit windows",
                file=sys.stderr,
            )
        if args.print_schedule:
            schedule = build_schedule(
                args.seed, sebulba_faults, point_names=SEBULBA_POINTS
            )
            print(json.dumps({
                "chaos_seed": args.seed,
                "chaos_faults_armed": len(schedule),
                "schedule": schedule.record(),
            }))
            return 0
        report = run_sebulba_campaign(
            seed=args.seed,
            faults=sebulba_faults,
            workdir=args.workdir,
            budget_s=args.budget_s,
        )
        print(json.dumps(report))
        return 0 if report.get("chaos_invariant_violations") == 0 else 1
    if args.train:
        train_faults = min(args.faults, 14)
        if train_faults < args.faults:
            print(
                f"[storm] --train caps --faults at 14 (requested "
                f"{args.faults}): the train lane's armable cells are "
                "bounded by the hit windows",
                file=sys.stderr,
            )
        if args.print_schedule:
            schedule = build_schedule(
                args.seed,
                train_faults,
                point_names=TRAIN_LANE_POINTS + TRAIN_POINTS,
            )
            print(json.dumps({
                "chaos_seed": args.seed,
                "chaos_faults_armed": len(schedule),
                "schedule": schedule.record(),
            }))
            return 0
        report = run_train_campaign(
            seed=args.seed,
            faults=train_faults,
            workdir=args.workdir,
            budget_s=args.budget_s,
        )
        print(json.dumps(report))
        return 0 if report.get("chaos_invariant_violations") == 0 else 1
    mesh_faults = min(args.faults, 20) if args.mesh else args.faults
    if args.mesh and mesh_faults < args.faults:
        print(
            f"[storm] --mesh caps --faults at 20 (requested "
            f"{args.faults}): the mesh serve leg has fewer armable "
            "cells and paces until every one fires",
            file=sys.stderr,
        )
    if args.print_schedule:
        schedule = build_schedule(
            args.seed,
            mesh_faults,
            point_names=(
                TRAIN_POINTS + MESH_SERVE_POINTS if args.mesh else None
            ),
        )
        print(json.dumps({
            "chaos_seed": args.seed,
            "chaos_faults_armed": len(schedule),
            "schedule": schedule.record(),
        }))
        return 0
    if args.mesh:
        report = run_mesh_campaign(
            seed=args.seed,
            faults=mesh_faults,
            hosts=args.hosts,
            workdir=args.workdir,
            budget_s=args.budget_s,
        )
    else:
        report = run_campaign(
            seed=args.seed,
            faults=args.faults,
            workdir=args.workdir,
            budget_s=args.budget_s,
        )
    print(json.dumps(report))
    return 0 if report.get("chaos_invariant_violations") == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
