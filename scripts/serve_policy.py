#!/usr/bin/env python
"""Serve a trained policy from its checkpoint directory.

Usage:
    # one-shot smoke benchmark against the newest checkpoint (1 JSON line)
    python scripts/serve_policy.py logs/run1 --smoke

    # long-running server: hot-reloads new checkpoints as training writes
    # them, emits serving metrics to {log_dir}/serving/metrics.jsonl
    python scripts/serve_policy.py logs/run1 --watch

    # no checkpoint yet? serve a freshly initialized policy
    python scripts/serve_policy.py --init-policy MLPActorCritic --obs-dim 8 --smoke

    # multi-replica fleet: one engine per local device, coordinated
    # hot reload, HTTP frontend on --port (0 = ephemeral, printed)
    python scripts/serve_policy.py logs/run1 --fleet --port 8100
    python scripts/serve_policy.py logs/run1 --fleet --replicas 2 --smoke

    # 2-replica fleet smoke on a forced multi-device CPU (what bench.py
    # records as serving_requests_per_sec_fleet)
    XLA_FLAGS=--xla_force_host_platform_device_count=2 JAX_PLATFORMS=cpu \\
        python scripts/serve_policy.py --init-policy MLPActorCritic \\
        --obs-dim 8 --fleet --replicas 2 --smoke

    # multi-tenant: named model lanes over ONE fleet, each lane hot-
    # reloading from its own promoted/ dir; the smoke drives every lane
    # and reports per-tenant throughput + step monotonicity
    python scripts/serve_policy.py --fleet \\
        --tenants formation-a=logs/a/promoted,formation-b=logs/b/promoted \\
        --smoke

The server is the in-process stack from
``marl_distributedformation_tpu.serving`` (bucketed compiled engine,
micro-batching scheduler, hot-reload registry — docs/serving.md); this
CLI wires it to a checkpoint directory and drives it with a synthetic
mixed-size load (``--smoke``) or leaves it serving + watching
(``--watch``, the mode a real frontend would embed). ``--fleet``
replaces the single engine with ``serving.fleet`` (router + coordinated
reload + optional HTTP frontend, docs/serving.md "Fleet");
``--tenants`` replaces the single model with named lanes over that one
fleet (``serving.tenancy``, docs/serving.md "Multi-tenant lanes").
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

if os.environ.get("JAX_PLATFORMS"):
    # Some containers (this repo's test image included) import jax at
    # interpreter start via sitecustomize, which swallows JAX_PLATFORMS
    # from the environment — re-assert the requested platform the way
    # tests/conftest.py does, so `JAX_PLATFORMS=cpu serve_policy.py`
    # means what it says instead of silently serving over a tunneled TPU.
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def _infer_row_shape(policy) -> tuple:
    """Feature shape of one request row. Per-formation policies
    (CTDE/GNN) take whole ``(num_agents, obs_dim)`` formations as rows
    and their tower widths are post-embedding — inference from the
    kernel is wrong there, so both dims must be passed explicitly. For
    flat per-agent policies the first tower layer's kernel records the
    obs width (the same inference compat.policy.infer_hidden does for
    tower widths)."""
    if getattr(policy.model, "per_formation", False):
        raise SystemExit(
            f"policy {type(policy.model).__name__} serves whole "
            "formations: pass --obs-dim AND --agents to size a request "
            "row (row shape = (agents, obs_dim))"
        )
    inner = policy.params.get("params", {})
    kernel = inner.get("pi_0", {}).get("kernel")
    if kernel is None:
        raise SystemExit(
            "cannot infer --obs-dim from this checkpoint "
            f"(policy {type(policy.model).__name__}); pass --obs-dim"
        )
    import numpy as np

    return (int(np.shape(kernel)[0]),)


def _ensure_cpu_devices(n: int) -> None:
    """Widen the CPU device pool to ``n`` for a --fleet run that asks
    for more replicas than devices. Mirrors tests/conftest.py: the
    backend may already be initialized (this image's sitecustomize
    imports jax at interpreter start), in which case the config update
    needs a backend reset first. On real accelerators this is a no-op —
    you get the devices the hardware has."""
    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        # Land the flag before the first backend init; if the backend
        # already exists (sitecustomize), the reset below re-reads it.
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    if len(jax.local_devices()) >= n or jax.default_backend() != "cpu":
        return
    try:
        jax.config.update("jax_num_cpu_devices", n)  # newer jax spelling
    except (AttributeError, RuntimeError):
        try:
            import jax.extend.backend as jeb

            jeb.clear_backends()  # re-init reads the XLA_FLAGS above
        except Exception:  # noqa: BLE001 — widening is best-effort
            pass
    if len(jax.local_devices()) < n:
        print(
            f"[serve] warning: wanted {n} CPU devices, have "
            f"{len(jax.local_devices())}; replicas will share devices",
            file=sys.stderr,
        )


def _build_init_policy(args):
    """A freshly initialized policy for --init-policy runs (shared by
    the single-engine and --fleet paths — one construction recipe, so
    the two can never drift)."""
    if args.obs_dim is None:
        raise SystemExit("--init-policy requires --obs-dim")
    import jax
    import jax.numpy as jnp

    from marl_distributedformation_tpu.compat.policy import (
        POLICY_REGISTRY,
        LoadedPolicy,
    )

    if args.init_policy not in POLICY_REGISTRY:
        raise SystemExit(
            f"unknown policy {args.init_policy!r}; known: "
            f"{sorted(POLICY_REGISTRY)}"
        )
    kwargs = {}
    if getattr(args, "hidden", None):
        hidden = tuple(int(w) for w in args.hidden.split(","))
        kwargs["hidden"] = hidden
    model = POLICY_REGISTRY[args.init_policy](act_dim=2, **kwargs)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, args.obs_dim))
    )
    return LoadedPolicy(
        dict(variables), policy=args.init_policy, model_kwargs=kwargs
    )


def _run_slo_bench(args) -> int:
    """bench.py phase 9: the SLO-driven serving bench, one JSON line.

    Three fleets on the same forced multi-device CPU (or real mesh),
    driven by the SAME open-loop request trace (serving/loadgen.py):

    1. replicated-only baseline (the PR-4 fleet shape);
    2. + f32 sharded big-rung slice (serving/sharded.py);
    3. + bf16 sharded slice — the "sharding and bf16 on" config, which
       also runs the bisection for ``req_per_sec_at_p95_slo``.

    Three design rules keep the comparison honest on a small shared
    box (each was a measured failure mode of the naive version):

    - **Thread-matched topologies.** A sharded config spends one unit
      of its worker budget on the mesh slice (``replicas - 1``
      single-device replicas + the slice), so every fleet runs the
      same number of scheduler threads — the naive "replicas + slice"
      shape oversubscribes the cores and books the scheduling penalty
      to sharding.
    - **Dedicated big-rung lane.** The slice serves ONLY the big rung
      (``min_rows = big``): big requests never queue behind the small
      stream, small requests never contend the mesh. This is the
      earned-ladder shape the autotuner picks, and the serving-layer
      claim the p95 split measures. The per-dispatch side rides the
      sharded engine's AOT executables (serving/sharded.py ``_run``),
      which on the dp=2 CPU mesh are ~13% faster than the replicated
      pjit dispatch — the compute split itself only materializes on
      real multi-chip hardware.
    - **Interleaved best-of-N.** Each config is replayed ``--slo-passes``
      times in rotated order against long-lived pre-warmed fleets, and
      the reported p95 is each config's best pass — back-to-back
      single passes book container load drift to whichever config hits
      the bad window (the PR-6 bench discipline).

    The autotuner runs on the same trace, so the report carries the
    earned ladder beside the measured one.
    """
    import numpy as np

    from marl_distributedformation_tpu.serving import (
        ShardedSpec,
        max_rate_at_slo,
        run_load,
        synthetic_trace,
    )
    from marl_distributedformation_tpu.serving.autotune import (
        autotune_ladder,
    )
    from marl_distributedformation_tpu.serving.fleet import (
        FleetRouter,
        warmup_fleet,
    )

    replicas = args.replicas or 2
    mesh_devices = args.mesh_devices or replicas
    _ensure_cpu_devices(max(replicas, mesh_devices))
    policy = _build_init_policy(args) if args.init_policy else None
    if policy is None:
        from marl_distributedformation_tpu.compat.policy import (
            LoadedPolicy,
        )
        from marl_distributedformation_tpu.utils.checkpoint import (
            latest_checkpoint,
        )

        path = latest_checkpoint(Path(args.log_dir))
        if path is None:
            raise SystemExit(f"no checkpoint under {args.log_dir}")
        policy = LoadedPolicy.from_checkpoint(path)
    row_shape = (
        (args.agents, args.obs_dim)
        if args.obs_dim and args.agents
        else (args.obs_dim,)
        if args.obs_dim
        else _infer_row_shape(policy)
    )
    buckets = tuple(int(b) for b in args.buckets.split(","))
    big = args.big_rung
    if big not in buckets:
        raise SystemExit(
            f"--big-rung {big} must be one of the ladder rungs {buckets}"
        )
    # The slice serves the big rung only — the earned-ladder lane shape
    # (see docstring). Big rungs are ~20% of requests so the mixed
    # stream queues the replicated lanes; rate sized so the small model
    # keeps up on CPU.
    sharded_buckets = (big,)
    size_mix = ((1, 0.4), (8, 0.2), (64, 0.2), (big, 0.2))
    trace = synthetic_trace(
        args.duration, args.load_rps, seed=7, size_mix=size_mix
    )

    def _fleet(sharded):
        # Thread-matched: the slice replaces one replicated replica, so
        # every config runs `replicas` scheduler workers total.
        n = replicas if sharded is None else max(1, replicas - 1)
        return FleetRouter(
            policy,
            num_replicas=n,
            buckets=buckets,
            window_ms=args.window_ms,
            max_queue=args.queue,
            sharded=sharded,
        )

    def _spec(dtype=None):
        # window_ms=0: the dedicated lane's requests fill the rung on
        # arrival, so there is nothing to coalesce (the autotuner emits
        # exactly this as LadderPlan.sharded_window_ms for this trace).
        return ShardedSpec(
            axis_sizes={"dp": mesh_devices},
            buckets=sharded_buckets,
            min_rows=big,
            dtype=dtype,
            window_ms=0.0,
        )

    report = {
        "slo_p95_target_ms": float(args.slo_p95_ms),
        "replicas": replicas,
        "mesh_devices": mesh_devices,
        "buckets": ",".join(str(b) for b in buckets),
        "big_rung": big,
        "passes": args.slo_passes,
    }
    max_compiles = 0

    def _best(label, key, value):
        """Fold one pass's p95 into the config's best (ignoring empty
        passes — a pass with no completions at a size reports 0.0)."""
        if value <= 0:
            return
        prev = report.get(key)
        report[key] = value if prev is None or prev <= 0 else min(
            prev, value
        )

    configs = [
        ("replicated", None),
        ("sharded", _spec()),
        ("bf16", _spec("bfloat16")),
    ]
    settle = synthetic_trace(
        min(1.0, args.duration), args.load_rps, seed=11, size_mix=size_mix
    )
    with contextlib.ExitStack() as stack:
        routers = {}
        for label, spec in configs:
            router = stack.enter_context(_fleet(spec))
            warmup_fleet(router, row_shape)
            routers[label] = router
        # One unrecorded settle replay per fleet: the first open-loop
        # minutes of a fresh process run 2-4x over the steady-state
        # floor (allocator/thread-pool/frequency ramp), and booking that
        # decay to whichever config is measured first was the dominant
        # noise term in earlier versions of this bench.
        for label, _ in configs:
            run_load(routers[label], settle, row_shape, seed=11)
        # Fixed passes, then adaptive extension: while any config's best
        # p95 still improved >10% in the last round, the process hasn't
        # found its quiet-window floor yet (a noisy container minute at
        # the start must not decide the comparison) — keep going, up to
        # 4 extra rounds.
        rounds = 0
        while rounds < max(1, args.slo_passes) + 4:
            i = rounds
            before = {
                label: report.get(f"{label}_{big}_p95_ms", 0.0)
                for label, _ in configs
            }
            for label, _ in configs[i % 3:] + configs[: i % 3]:
                rep = run_load(routers[label], trace, row_shape, seed=7)
                _best(
                    label,
                    f"{label}_{big}_p95_ms",
                    rep.per_size_p95_ms.get(big, 0.0),
                )
                _best(label, f"{label}_p95_ms", rep.p95_ms)
            rounds += 1
            if rounds >= max(1, args.slo_passes):
                settled = all(
                    before[label] > 0
                    and report[f"{label}_{big}_p95_ms"]
                    > 0.9 * before[label]
                    for label, _ in configs
                )
                if settled:
                    break
        report["passes"] = rounds
        for key in list(report):
            if key.endswith("_p95_ms") and not isinstance(
                report[key], float
            ):
                report[key] = float(report[key])
        report.setdefault(f"replicated_{big}_p95_ms", 0.0)
        report.setdefault(f"sharded_{big}_p95_ms", 0.0)
        report.setdefault(f"bf16_{big}_p95_ms", 0.0)
        f32_p95 = report[f"sharded_{big}_p95_ms"]
        bf16_p95 = report[f"bf16_{big}_p95_ms"]
        report["bf16_speedup_pct"] = (
            100.0 * (f32_p95 / bf16_p95 - 1.0) if bf16_p95 > 0 else 0.0
        )

        # The capacity number: max sustained open-loop rate holding the
        # p95 target, on the full config (sharded slice + bf16 rungs
        # ON) — the same long-lived fleet the comparison measured.
        best, probes = max_rate_at_slo(
            routers["bf16"],
            row_shape,
            p95_target_ms=args.slo_p95_ms,
            lo_rps=args.load_rps / 2,
            hi_rps=args.load_rps * 8,
            probe_duration_s=min(1.0, args.duration),
            iterations=args.slo_iterations,
            seed=7,
            size_mix=size_mix,
            batch_fraction=0.1,
            probe_retries=2,
        )
        preempted = sum(
            r.scheduler.metrics.preempted_total
            for r in routers["bf16"].replicas
        )
        for router in routers.values():
            for counts in router.compile_counts().values():
                max_compiles = max(max_compiles, *counts.values())
    report["req_per_sec_at_p95_slo"] = best
    report["slo_probes"] = len(probes)
    report["max_compiles_per_rung"] = max_compiles
    report["batch_preempted_total"] = preempted

    plan = autotune_ladder(
        trace,
        p95_target_ms=args.slo_p95_ms,
        mesh_divisor=mesh_devices,
        sharded_min_rows=min(sharded_buckets),
    )
    report["autotuned"] = plan.to_dict()
    print(json.dumps(report), flush=True)
    if report[f"sharded_{big}_p95_ms"] <= 0:
        print(
            "[serve] slo bench measured no big-rung completions — failing",
            file=sys.stderr,
        )
        return 1
    return 0


def _run_elastic_bench(args) -> int:
    """The --elastic-bench comparison (bench.py phase "elastic"): a
    shifting-mix day — interactive-heavy first half, big-rung storm
    second half — against two fleets on the same forced multi-device
    CPU mesh:

    - **static**: split + ladder autotuned on the FIRST half and then
      frozen — the fleet a pre-traffic tuner ships. The storm's
      64–256-row requests chunk through its small top rung.
    - **elastic**: boots identically, but a ``CapacityController``
      watches the live ``TraceRecorder`` and re-splits at the fleet
      batch barrier when the mix shifts (prewarm-then-commit; the
      serving interruption is ``elastic_resplit_pause_ms``, the
      barrier pause alone).

    Both fleets are measured on the storm half with the same rate
    bisection (``max_rate_at_slo``); budget-1 compile receipts and a
    ledger census diff (no program registered during the measured
    storm — every compile attributed to prewarm) ride the report.
    One JSON line to stdout.
    """
    import numpy as np  # noqa: F401 — row dtype parity with _run_slo_bench

    from marl_distributedformation_tpu.obs.ledger import get_ledger
    from marl_distributedformation_tpu.serving import (
        CapacityController,
        TraceRecorder,
        max_rate_at_slo,
        run_load,
        synthetic_trace,
    )
    from marl_distributedformation_tpu.serving.autotune import (
        autotune_ladder,
    )
    from marl_distributedformation_tpu.serving.fleet import (
        FleetReloadCoordinator,
        FleetRouter,
        warmup_fleet,
    )

    replicas = args.replicas or 2
    _ensure_cpu_devices(replicas)
    if not args.init_policy:
        raise SystemExit("--elastic-bench wants --init-policy + --obs-dim")
    policy = _build_init_policy(args)
    row_shape = (args.obs_dim,)
    duration = args.duration
    interactive_mix = ((1, 0.5), (2, 0.2), (4, 0.2), (8, 0.1))
    storm_mix = ((64, 0.35), (128, 0.3), (256, 0.35))
    storm_rps = max(4.0, args.load_rps / 6.0)
    interactive = synthetic_trace(
        duration, args.load_rps, seed=7, size_mix=interactive_mix
    )
    storm = synthetic_trace(
        duration, storm_rps, seed=9, size_mix=storm_mix
    )

    # The split a pre-traffic tuner ships: autotuned on the first half,
    # then frozen. The storm never informs it.
    first_half_plan = autotune_ladder(
        interactive, p95_target_ms=args.slo_p95_ms
    )
    boot_buckets = first_half_plan.buckets
    report = {
        "replicas": replicas,
        "slo_p95_target_ms": float(args.slo_p95_ms),
        "boot_buckets": ",".join(str(b) for b in boot_buckets),
        "interactive_rps": float(args.load_rps),
        "storm_rps": float(storm_rps),
    }

    def _measure_storm(router, seed):
        rep = run_load(router, storm, row_shape, seed=seed)
        best, probes = max_rate_at_slo(
            router,
            row_shape,
            p95_target_ms=args.slo_p95_ms,
            lo_rps=storm_rps / 2,
            hi_rps=storm_rps * 8,
            probe_duration_s=min(1.0, duration),
            iterations=args.slo_iterations,
            seed=seed,
            size_mix=storm_mix,
            probe_retries=2,
        )
        return rep.p95_ms, best

    with contextlib.ExitStack() as stack:
        static = stack.enter_context(
            FleetRouter(
                policy,
                num_replicas=replicas,
                buckets=boot_buckets,
                window_ms=first_half_plan.window_ms,
                max_queue=args.queue,
            )
        )
        recorder = TraceRecorder()
        elastic = stack.enter_context(
            FleetRouter(
                policy,
                num_replicas=replicas,
                buckets=boot_buckets,
                window_ms=first_half_plan.window_ms,
                max_queue=args.queue,
                trace_recorder=recorder,
            )
        )
        warmup_fleet(static, row_shape)
        warmup_fleet(elastic, row_shape)
        with tempfile.TemporaryDirectory() as empty_dir:
            coordinator = FleetReloadCoordinator(empty_dir, elastic)
            controller = CapacityController(
                elastic,
                coordinator,
                row_shape=row_shape,
                p95_target_ms=args.slo_p95_ms,
                min_requests=32,
            )
            # First half: both fleets serve the interactive mix (also
            # the fresh-process settle replay, PR-6 bench discipline).
            run_load(static, interactive, row_shape, seed=11)
            rep_i = run_load(elastic, interactive, row_shape, seed=11)
            report["elastic_interactive_p95_ms"] = rep_i.p95_ms
            controller.step()  # may retune windows; interactive-earned
            # The mix shifts: storm traffic reaches the elastic fleet,
            # the controller re-splits, prewarm-then-commit. The static
            # fleet serves the same storm on its frozen split.
            run_load(elastic, storm, row_shape, seed=13)
            resplit = controller.step()
            if resplit is None or not resplit.get("committed"):
                print(
                    f"[serve] elastic bench: storm re-split did not "
                    f"commit ({resplit}) — failing",
                    file=sys.stderr,
                )
                return 1
            # Measured storm: census diff proves no compile rides it.
            programs_before = len(get_ledger().entries())
            static_p95, static_rate = _measure_storm(static, seed=13)
            elastic_p95, elastic_rate = _measure_storm(elastic, seed=13)
            report["elastic_storm_new_programs"] = (
                len(get_ledger().entries()) - programs_before
            )
            snap = controller.snapshot()
            report["static_storm_p95_ms"] = static_p95
            report["elastic_storm_p95_ms"] = elastic_p95
            report["req_per_sec_at_p95_slo_static"] = static_rate
            report["req_per_sec_at_p95_slo_elastic"] = elastic_rate
            report["elastic_resplit_pause_ms"] = snap[
                "elastic_last_pause_ms"
            ]
            report["elastic_resplits_committed"] = snap[
                "elastic_resplits_committed"
            ]
            report["elastic_prewarm_compiles"] = snap[
                "elastic_prewarm_compiles_total"
            ]
            report["elastic_buckets"] = ",".join(
                str(b) for b in resplit["decision"]["replicated_buckets"]
                + resplit["decision"]["sharded_buckets"]
            )
            max_compiles = 0
            for router in (static, elastic):
                for counts in router.compile_counts().values():
                    if counts:
                        max_compiles = max(
                            max_compiles, *counts.values()
                        )
            report["max_compiles_per_rung"] = max_compiles
    print(json.dumps(report), flush=True)
    if report["req_per_sec_at_p95_slo_elastic"] <= 0:
        print(
            "[serve] elastic bench: elastic fleet sustained no rate at "
            "the p95 target — failing",
            file=sys.stderr,
        )
        return 1
    return 0


def _run_fleet(args) -> int:
    """The --fleet serving path: router + coordinated reload +
    optional HTTP frontend (serving/fleet/, docs/serving.md "Fleet")."""
    if args.replicas:
        _ensure_cpu_devices(args.replicas)

    from marl_distributedformation_tpu.serving.fleet import (
        FleetFrontend,
        FleetRouter,
        fleet_from_checkpoint_dir,
        run_fleet_smoke,
    )

    buckets = tuple(int(b) for b in args.buckets.split(","))
    sharded = None
    if args.sharded:
        from marl_distributedformation_tpu.serving import ShardedSpec

        sharded = ShardedSpec(
            axis_sizes=(
                {"dp": args.mesh_devices} if args.mesh_devices else None
            ),
            dtype="bfloat16" if args.bf16 else None,
        )
    recorder = None
    if args.record_trace:
        from marl_distributedformation_tpu.serving import TraceRecorder

        recorder = TraceRecorder()
    logger = None
    coordinator = None
    if args.init_policy:
        policy = _build_init_policy(args)
        router = FleetRouter(
            policy,
            num_replicas=args.replicas,
            buckets=buckets,
            window_ms=args.window_ms,
            max_queue=args.queue,
            sharded=sharded,
            trace_recorder=recorder,
        )
    elif args.log_dir:
        from marl_distributedformation_tpu.utils.logging import MetricsLogger

        logger = MetricsLogger(
            Path(args.log_dir) / "serving", run_name="fleet"
        )
        router, coordinator = fleet_from_checkpoint_dir(
            args.log_dir,
            num_replicas=args.replicas,
            buckets=buckets,
            window_ms=args.window_ms,
            max_queue=args.queue,
            poll_interval_s=args.poll_s,
            logger=logger,
            sharded=sharded,
            trace_recorder=recorder,
        )
        policy = router.policy
        print(
            f"[serve] fleet serving {type(policy.model).__name__} from "
            f"{args.log_dir} at step {coordinator.fleet_step}",
            file=sys.stderr,
        )
    else:
        raise SystemExit("need a log_dir or --init-policy (see --help)")

    if args.obs_dim:
        row_shape = (
            (args.agents, args.obs_dim) if args.agents else (args.obs_dim,)
        )
    else:
        row_shape = _infer_row_shape(policy)
    devices = {str(r.device) for r in router.replicas}
    print(
        f"[serve] fleet: {len(router.replicas)} replicas over "
        f"{len(devices)} devices, buckets {args.buckets}",
        file=sys.stderr,
    )

    frontend = None
    try:
        router.start()
        if coordinator is not None:
            coordinator.start()
        if args.port is not None:
            frontend = FleetFrontend(router, port=args.port).start()
            print(
                f"[serve] fleet frontend listening on {frontend.url}",
                file=sys.stderr,
            )
        if args.smoke or (args.port is None and not args.watch):
            report = run_fleet_smoke(
                router,
                row_shape=row_shape,
                duration_s=args.duration,
                num_clients=args.clients,
                deterministic=not args.stochastic,
                coordinator=coordinator,
            )
            report["buckets"] = ",".join(str(b) for b in buckets)
            report["replicas"] = float(len(router.replicas))
            print(json.dumps(report), flush=True)
            if report["client_requests_ok"] == 0:
                print(
                    "[serve] fleet smoke served 0 requests — failing",
                    file=sys.stderr,
                )
                return 1
        else:
            print(
                "[serve] fleet serving; Ctrl-C to stop", file=sys.stderr
            )
            while True:
                time.sleep(10.0)
                snap = router.snapshot()
                print(
                    f"[serve] step={snap['model_step']:.0f} "
                    f"healthy={snap['fleet_healthy_replicas']:.0f}/"
                    f"{len(router.replicas)} "
                    f"routed={snap['fleet_routed_total']:.0f} "
                    f"p95={snap['latency_p95_ms']:.1f}ms",
                    file=sys.stderr,
                )
    except KeyboardInterrupt:
        print("[serve] interrupted; shutting down", file=sys.stderr)
    finally:
        if frontend is not None:
            frontend.stop()
        if coordinator is not None:
            coordinator.stop()
        router.stop()
        if logger is not None:
            logger.close()
        if recorder is not None:
            # Replayable loadgen JSONL (serving.loadgen.load_trace):
            # feed it back through run_load or autotune_ladder.
            if recorder.save(args.record_trace):
                print(
                    f"[serve] recorded {recorder.recorded_total} "
                    f"arrivals -> {args.record_trace}",
                    file=sys.stderr,
                )
            else:
                print(
                    "[serve] --record-trace saw <2 arrivals; nothing "
                    "to save",
                    file=sys.stderr,
                )
    return 0


def _parse_tenants(chunks) -> list:
    """``NAME=DIR`` pairs from repeated/comma-joined --tenants values."""
    lanes = []
    seen = set()
    for chunk in chunks:
        for item in chunk.split(","):
            item = item.strip()
            if not item:
                continue
            name, sep, directory = item.partition("=")
            if not sep or not name or not directory:
                raise SystemExit(
                    f"--tenants wants NAME=DIR pairs, got {item!r}"
                )
            if name in seen:
                raise SystemExit(f"--tenants declares {name!r} twice")
            seen.add(name)
            lanes.append((name, directory))
    if not lanes:
        raise SystemExit("--tenants got no NAME=DIR pairs")
    return lanes


def _run_tenants(args) -> int:
    """The --tenants serving path: named model lanes over ONE fleet
    (serving/tenancy/, docs/serving.md "Multi-tenant lanes"). Each
    lane's architecture is read from its own newest checkpoint, so
    same-arch lanes land in one router group (shared compiled rungs)
    and distinct archs get their own — the smoke's
    ``shared_rung_compiles`` census is the receipt."""
    if args.replicas:
        _ensure_cpu_devices(args.replicas)

    from marl_distributedformation_tpu.compat.policy import (
        infer_hidden,
        load_checkpoint_raw,
    )
    from marl_distributedformation_tpu.serving.tenancy import (
        TenantDirectory,
        TenantSpec,
        run_tenant_smoke,
        tenant_fleet_from_directory,
    )
    from marl_distributedformation_tpu.utils.checkpoint import (
        latest_checkpoint,
    )

    pairs = _parse_tenants(args.tenants)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    directory = TenantDirectory()
    for name, lane_dir in pairs:
        path = latest_checkpoint(Path(lane_dir))
        if path is None:
            raise SystemExit(
                f"--tenants {name}={lane_dir}: no rl_model_*_steps"
                ".msgpack checkpoint there to serve"
            )
        raw = load_checkpoint_raw(path)
        policy_cls = raw.get("policy", "MLPActorCritic")
        hidden = infer_hidden(raw["params"]["params"], policy_cls)
        try:
            directory.add(
                TenantSpec(
                    model_id=name,
                    policy=policy_cls,
                    hidden=tuple(hidden) if hidden else (64, 64),
                    promoted_dir=str(lane_dir),
                    num_agents=args.agents,
                )
            )
        except ValueError as e:
            raise SystemExit(f"--tenants {name}: {e}") from e

    fleet = tenant_fleet_from_directory(
        directory,
        poll_interval_s=args.poll_s,
        num_replicas=args.replicas,
        buckets=buckets,
        window_ms=args.window_ms,
        max_queue=args.queue,
        watch=True,
    )
    groups = directory.arch_groups()
    print(
        f"[serve] tenant fleet: {len(directory)} lanes in "
        f"{len(groups)} arch group(s) — "
        + "; ".join(
            f"{arch}: {', '.join(s.model_id for s in specs)}"
            for arch, specs in groups.items()
        ),
        file=sys.stderr,
    )
    frontend = None
    try:
        fleet.start()
        if args.port is not None:
            # FleetFrontend duck-types over the TenantFleet: submits
            # carry model_id, /v1/metrics reports per-lane gauges.
            from marl_distributedformation_tpu.serving.fleet import (
                FleetFrontend,
            )

            frontend = FleetFrontend(fleet, port=args.port).start()
            print(
                f"[serve] tenant frontend listening on {frontend.url}",
                file=sys.stderr,
            )
        if args.smoke or (args.port is None and not args.watch):
            report = run_tenant_smoke(
                fleet,
                duration_s=args.duration,
                clients_per_lane=max(1, args.clients // len(pairs)),
                deterministic=not args.stochastic,
            )
            report["buckets"] = ",".join(str(b) for b in buckets)
            print(json.dumps(report), flush=True)
            starved = [
                name
                for name, _ in pairs
                if report[f"model_{name}__requests_ok"] == 0
            ]
            wiggled = [
                name
                for name, _ in pairs
                if report[f"model_{name}__step_monotonic_violations"] > 0
            ]
            if starved or wiggled:
                print(
                    f"[serve] tenant smoke failing — lanes served 0: "
                    f"{starved}; lanes non-monotonic: {wiggled}",
                    file=sys.stderr,
                )
                return 1
        else:
            print(
                "[serve] tenant fleet serving; Ctrl-C to stop",
                file=sys.stderr,
            )
            while True:
                time.sleep(10.0)
                steps = fleet.lane_steps()
                print(
                    "[serve] "
                    + " ".join(
                        f"{mid}@{step}" for mid, step in sorted(steps.items())
                    )
                    + f" healthy={fleet.healthy_replicas}/"
                    f"{len(fleet.replicas)}",
                    file=sys.stderr,
                )
    except KeyboardInterrupt:
        print("[serve] interrupted; shutting down", file=sys.stderr)
    finally:
        if frontend is not None:
            frontend.stop()
        fleet.stop()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "log_dir",
        nargs="?",
        help="checkpoint directory (logs/{name}) to serve and watch",
    )
    parser.add_argument(
        "--init-policy",
        help="serve a freshly initialized policy of this class instead of "
        "a checkpoint (requires --obs-dim)",
    )
    parser.add_argument("--obs-dim", type=int, help="request row width")
    parser.add_argument(
        "--hidden",
        help="with --init-policy: comma-separated tower widths "
        "(default the model's own, 64,64) — the SLO bench widens the "
        "net so big-rung compute is non-trivial",
    )
    parser.add_argument(
        "--agents",
        type=int,
        help="agents per formation — required for per-formation policies "
        "(CTDE/GNN), whose request rows are (agents, obs_dim)",
    )
    parser.add_argument(
        "--buckets",
        default="1,8,64,512",
        help="comma-separated batch-shape ladder (default 1,8,64,512)",
    )
    parser.add_argument(
        "--window-ms", type=float, default=2.0, help="coalescing window"
    )
    parser.add_argument(
        "--queue", type=int, default=256, help="request queue bound"
    )
    parser.add_argument(
        "--poll-s", type=float, default=2.0, help="checkpoint poll cadence"
    )
    parser.add_argument(
        "--smoke",
        action="store_true",
        help="run the mixed-size smoke benchmark and print one JSON line",
    )
    parser.add_argument(
        "--duration", type=float, default=3.0, help="smoke duration (s)"
    )
    parser.add_argument(
        "--clients", type=int, default=4, help="smoke client threads"
    )
    parser.add_argument(
        "--stochastic",
        action="store_true",
        help="sample actions instead of the deterministic mode",
    )
    parser.add_argument(
        "--scenario",
        help="perturb smoke request observations with this registered "
        "scenario's sensor-noise magnitudes (scenarios/registry.py)",
    )
    parser.add_argument(
        "--scenario-severity",
        type=float,
        default=1.0,
        help="severity scale for --scenario (default 1.0)",
    )
    parser.add_argument(
        "--watch",
        action="store_true",
        help="keep serving + hot-reloading until interrupted",
    )
    parser.add_argument(
        "--fleet",
        action="store_true",
        help="serve a multi-replica fleet (serving.fleet): one "
        "engine+scheduler per local device behind a load-aware router "
        "with coordinated hot reload",
    )
    parser.add_argument(
        "--replicas",
        type=int,
        help="fleet replica count (default: one per local device); on a "
        "CPU backend the device pool is widened to match if needed",
    )
    parser.add_argument(
        "--tenants",
        action="append",
        metavar="NAME=DIR",
        help="with --fleet: serve named model lanes over ONE fleet, "
        "each NAME hot-reloading from its own promoted checkpoint DIR "
        "(repeat the flag or comma-join pairs); the smoke drives every "
        "lane and reports per-tenant req/s + step monotonicity",
    )
    parser.add_argument(
        "--port",
        type=int,
        help="with --fleet: expose the stdlib HTTP frontend on this "
        "port (0 = ephemeral; the bound port is printed to stderr)",
    )
    parser.add_argument(
        "--sharded",
        action="store_true",
        help="with --fleet: add the mesh-backed big-rung replica "
        "(serving.sharded — partition-rule params over a dp slice of "
        "the local devices; big requests route there)",
    )
    parser.add_argument(
        "--bf16",
        action="store_true",
        help="with --sharded: serve the sharded rungs in bfloat16 "
        "(opt-in; divergence bounded by tests/bf16_budget.py)",
    )
    parser.add_argument(
        "--mesh-devices",
        type=int,
        help="dp width of the sharded mesh slice (default: the fleet "
        "replica count)",
    )
    parser.add_argument(
        "--record-trace",
        metavar="PATH",
        help="with --fleet: record every offered request arrival "
        "(rows + SLO class + inter-arrival gap, captured before "
        "admission control) and dump replayable loadgen JSONL here on "
        "shutdown — the same format synthetic_trace saves, so the "
        "recorded day replays through run_load / autotune_ladder",
    )
    parser.add_argument(
        "--elastic-bench",
        action="store_true",
        help="run the elastic-vs-static capacity bench (bench.py phase "
        "'elastic'): a shifting-mix trace against a frozen "
        "first-half-tuned fleet and a CapacityController-managed one, "
        "both measured on the storm half; one JSON line",
    )
    parser.add_argument(
        "--slo-bench",
        action="store_true",
        help="run the SLO-driven serving bench (bench.py phase 9): "
        "replicated vs sharded vs bf16 under the same open-loop load "
        "trace, then bisect for req/s at the p95 target; one JSON line",
    )
    parser.add_argument(
        "--slo-p95-ms",
        type=float,
        default=50.0,
        help="p95 latency target for --slo-bench (default 50 ms)",
    )
    parser.add_argument(
        "--slo-iterations",
        type=int,
        default=5,
        help="rate-bisection steps for --slo-bench (default 5)",
    )
    parser.add_argument(
        "--slo-passes",
        type=int,
        default=4,
        help="interleaved replay passes per config for --slo-bench; "
        "each config reports its best pass (default 4, extended "
        "adaptively while any config's floor still improves)",
    )
    parser.add_argument(
        "--load-rps",
        type=float,
        default=300.0,
        help="base offered rate for the --slo-bench comparison trace",
    )
    parser.add_argument(
        "--big-rung",
        type=int,
        default=512,
        help="the rung the sharded-vs-replicated p95 comparison tracks",
    )
    parser.add_argument(
        "--obs-trace",
        choices=("on", "off"),
        default="on",
        help="obs/ tracing spine: batch spans + trace-ID propagation "
        "(default on; bench phase 8 runs the smoke both ways to measure "
        "the overhead)",
    )
    args = parser.parse_args(argv)

    from marl_distributedformation_tpu import obs

    obs.configure(enabled=args.obs_trace == "on")

    if args.slo_bench:
        return _run_slo_bench(args)
    if args.elastic_bench:
        return _run_elastic_bench(args)

    if (args.port is not None or args.replicas is not None) and not args.fleet:
        raise SystemExit("--port/--replicas require --fleet")
    if args.record_trace and not args.fleet:
        raise SystemExit("--record-trace requires --fleet")
    if args.record_trace and args.tenants:
        raise SystemExit(
            "--record-trace records one fleet's offered stream; it "
            "does not combine with --tenants yet"
        )
    if (args.sharded or args.bf16) and not args.fleet:
        raise SystemExit("--sharded/--bf16 require --fleet")
    if args.bf16 and not args.sharded:
        raise SystemExit("--bf16 requires --sharded")
    if args.tenants:
        if not args.fleet:
            raise SystemExit("--tenants requires --fleet")
        if args.log_dir or args.init_policy:
            raise SystemExit(
                "--tenants names each lane's checkpoint dir itself; "
                "drop the positional log_dir / --init-policy"
            )
        if args.sharded or args.scenario:
            raise SystemExit(
                "--tenants does not combine with --sharded/--scenario "
                "yet (lanes + sharded big-rung is an open item)"
            )
        return _run_tenants(args)

    if args.scenario:
        # Resolve against the registry BEFORE the expensive part
        # (checkpoint load + engine warmup): a typo'd name exits cleanly
        # naming the valid entries, like every other entry point.
        from marl_distributedformation_tpu.scenarios import get_scenario

        try:
            get_scenario(args.scenario)
        except ValueError as e:
            raise SystemExit(str(e)) from e

    if args.fleet:
        if args.scenario:
            raise SystemExit(
                "--scenario perturbs the single-engine smoke only; "
                "run it without --fleet"
            )
        return _run_fleet(args)

    from marl_distributedformation_tpu.serving import (
        BucketedPolicyEngine,
        MicroBatchScheduler,
        ModelRegistry,
        run_smoke_benchmark,
    )

    registry = None
    if args.init_policy:
        policy = _build_init_policy(args)
    elif args.log_dir:
        registry = ModelRegistry(
            args.log_dir, poll_interval_s=args.poll_s
        )
        policy = registry.policy
        print(
            f"[serve] serving {type(policy.model).__name__} from "
            f"{args.log_dir} at step {registry.active_step}",
            file=sys.stderr,
        )
    else:
        raise SystemExit("need a log_dir or --init-policy (see --help)")

    if args.obs_dim:
        row_shape = (
            (args.agents, args.obs_dim) if args.agents else (args.obs_dim,)
        )
    else:
        row_shape = _infer_row_shape(policy)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    engine = BucketedPolicyEngine(policy, buckets=buckets)

    logger = None
    if args.log_dir:
        from marl_distributedformation_tpu.utils.logging import MetricsLogger

        logger = MetricsLogger(
            Path(args.log_dir) / "serving", run_name="serving"
        )

    scheduler = MicroBatchScheduler(
        engine,
        registry=registry,
        max_queue=args.queue,
        window_ms=args.window_ms,
        logger=logger,
    )
    if registry is not None:
        registry.start()
    try:
        with scheduler:
            if args.smoke or not args.watch:
                report = run_smoke_benchmark(
                    scheduler,
                    row_shape=row_shape,
                    duration_s=args.duration,
                    num_clients=args.clients,
                    deterministic=not args.stochastic,
                    registry=registry,
                    scenario=args.scenario,
                    scenario_severity=args.scenario_severity,
                )
                report["buckets"] = ",".join(str(b) for b in buckets)
                print(json.dumps(report), flush=True)
                if report["client_requests_ok"] == 0:
                    # A smoke run that served nothing is a failure, not
                    # a report (e.g. a row shape the model rejects).
                    print(
                        "[serve] smoke served 0 requests — failing",
                        file=sys.stderr,
                    )
                    return 1
            else:
                print(
                    "[serve] watching for checkpoints; Ctrl-C to stop",
                    file=sys.stderr,
                )
                while True:
                    time.sleep(10.0)
                    snap = scheduler.metrics.snapshot()
                    print(
                        f"[serve] step={registry.active_step if registry else 0} "
                        f"requests={snap['requests']:.0f} "
                        f"occupancy={snap['batch_occupancy_pct']:.1f}% "
                        f"p95={snap['latency_p95_ms']:.1f}ms",
                        file=sys.stderr,
                    )
    except KeyboardInterrupt:
        print("[serve] interrupted; shutting down", file=sys.stderr)
    finally:
        if registry is not None:
            registry.stop()
        if logger is not None:
            logger.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
