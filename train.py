#!/usr/bin/env python
"""Training entry point — the reference's ``python vectorized_env.py name=x``
workflow (reference vectorized_env.py:112-137, README.md:18) on the
TPU-native backend.

Usage:
    python train.py name=myrun num_formation=4096 num_agents_per_formation=5

Any key in cfg/config.yaml can be overridden with ``key=value`` (hydra CLI
contract; hydra itself is optional — see utils/config.py).
"""

from __future__ import annotations

import sys

from marl_distributedformation_tpu.algo import PPOConfig
from marl_distributedformation_tpu.train import TrainConfig, Trainer
from marl_distributedformation_tpu.utils import (
    env_params_from_config,
    load_config,
    repo_root,
    scenario_schedule_from_config,
    setup_platform,
)


def ppo_from_config(cfg) -> PPOConfig:
    return PPOConfig(
        n_steps=cfg.n_steps,
        learning_rate=cfg.learning_rate,
        ent_coef=cfg.ent_coef,
        gamma=cfg.gamma,
        gae_lambda=cfg.gae_lambda,
        clip_range=cfg.clip_range,
        clip_range_vf=cfg.get("clip_range_vf"),
        n_epochs=cfg.n_epochs,
        batch_size=cfg.batch_size,
        vf_coef=cfg.vf_coef,
        max_grad_norm=cfg.max_grad_norm,
        normalize_advantage=cfg.normalize_advantage,
        log_std_init=cfg.log_std_init,
        ent_coef_final=cfg.get("ent_coef_final"),
        log_std_final=cfg.get("log_std_final"),
        log_std_decay_start=float(cfg.get("log_std_decay_start") or 0.0),
    )


def train_config_from_config(cfg) -> TrainConfig:
    run_name = str(cfg.name)  # hydra parses numeric-looking names as ints
    return TrainConfig(
        num_formations=cfg.num_formation,
        total_timesteps=cfg.total_timesteps,
        seed=cfg.seed,
        save_freq=cfg.save_freq,
        name=run_name,
        log_dir=str(repo_root() / "logs" / run_name),
        use_wandb=cfg.use_wandb,
        use_tensorboard=bool(cfg.get("use_tensorboard", False)),
        resume=cfg.get("resume", False),
        log_interval=cfg.log_interval,
        profile=bool(cfg.get("profile", False)),
        # Dispatches to trace under profile=true — whole fused chunks in
        # Anakin mode (chunk-granular capture, docs/profiling.md).
        profile_iterations=int(cfg.get("profile_iterations", 3)),
        iters_per_dispatch=int(cfg.get("iters_per_dispatch", 1)),
        # Anakin mode (docs/training.md): K iterations per lax.scan
        # dispatch, stacked metrics drained double-buffered, checkpoints
        # on a background writer. fused_chunk=32 is a good TPU default.
        # Composes with num_seeds>1 population sweeps AND curriculum
        # populations (chunks clip at stage boundaries).
        fused_chunk=int(cfg.get("fused_chunk", 0)),
        # Runtime tracing guards (analysis/guards.py): guard_retraces=1
        # enforces the compiles-exactly-once contract on the train step.
        guard_retraces=int(cfg.get("guard_retraces", 0)),
        guard_transfers=bool(cfg.get("guard_transfers", False)),
        guard_nans=bool(cfg.get("guard_nans", False)),
        # Self-healing train lane (train/recovery.py, docs/recovery.md):
        # in-program health word + skip guard, the host-side escalation
        # ladder, and the checkpoint retention ring.
        health=bool(cfg.get("health", False)),
        health_grad_norm_max=float(cfg.get("health_grad_norm_max", 1.0e6)),
        health_param_drift_max=float(
            cfg.get("health_param_drift_max", 10.0)
        ),
        recovery=bool(cfg.get("recovery", False)),
        recovery_breach_iters=int(cfg.get("recovery_breach_iters", 3)),
        recovery_max_rollbacks=int(cfg.get("recovery_max_rollbacks", 3)),
        recovery_lr_backoff=float(cfg.get("recovery_lr_backoff", 1.0)),
        recovery_severity_backoff=float(
            cfg.get("recovery_severity_backoff", 1.0)
        ),
        keep_last_n=int(cfg.get("keep_last_n", 0)),
        # Sebulba lane (train/sebulba/, docs/sebulba.md): split
        # acting/learning with hardened host-side transfer queues.
        architecture=str(cfg.get("architecture", "anakin")),
        actor_devices=int(cfg.get("actor_devices", 1)),
        transfer_queue_depth=int(cfg.get("transfer_queue_depth", 2)),
        max_param_staleness=int(cfg.get("max_param_staleness", 2)),
    )


def _hidden_sizes(cfg):
    """Optional ``hidden_sizes=[w1, w2, ...]`` — the SB3
    ``policy_kwargs={'net_arch': ...}`` analog (the reference uses the
    'MlpPolicy' default [64, 64]; this knob replaces that part of SB3's
    constructor surface). None/null keeps each model's default."""
    sizes = cfg.get("hidden_sizes")
    if not sizes:
        return None
    return tuple(int(w) for w in sizes)


def build_model(cfg, env_params, policy: str):
    """The ONE policy-module construction site (both the plain and the
    curriculum trainer paths build through here): maps the ``policy``
    name + config knobs (``hidden_sizes``, ``log_std_init``, knn
    geometry) to a model instance, or None for the default-shape MLP
    (trainer shells construct that themselves)."""
    hidden = _hidden_sizes(cfg)
    extra = {"hidden": hidden} if hidden else {}
    if policy == "ctde":
        from marl_distributedformation_tpu.models import CTDEActorCritic

        return CTDEActorCritic(
            act_dim=env_params.act_dim, log_std_init=cfg.log_std_init,
            **extra,
        )
    if policy == "gnn":
        if env_params.obs_mode != "knn":
            raise SystemExit(
                "policy=gnn needs the k-NN observation graph: set "
                "obs_mode=knn (and knn_k) in the config"
            )
        from marl_distributedformation_tpu.models import GNNActorCritic

        return GNNActorCritic(
            k=env_params.knn_k,
            act_dim=env_params.act_dim,
            goal_in_obs=env_params.goal_in_obs,
            log_std_init=cfg.log_std_init,
            **extra,
        )
    if policy == "mlp":
        if not hidden:
            return None
        from marl_distributedformation_tpu.models import MLPActorCritic

        return MLPActorCritic(
            act_dim=env_params.act_dim,
            hidden=hidden,
            log_std_init=cfg.log_std_init,
        )
    raise SystemExit(
        f"policy={policy!r} is not implemented; available: mlp, ctde, gnn"
    )


def shard_fn_from_config(cfg):
    if not cfg.get("mesh"):
        return None
    from marl_distributedformation_tpu.parallel import (
        make_hybrid_mesh,
        make_shard_fn,
    )

    # Hybrid construction keeps the gradient psum on ICI within a slice
    # with only slice-partials over DCN; single-slice it is a plain mesh.
    return make_shard_fn(mesh=make_hybrid_mesh(dict(cfg.mesh)))


def build_trainer(cfg) -> Trainer:
    if cfg.backend != "jax":
        raise SystemExit(
            f"backend={cfg.backend!r} is not available in this repo; the "
            "TPU-native backend is 'jax' (the reference torch/SB3 stack "
            "lives in the original repository)."
        )
    env_params = env_params_from_config(cfg)
    ppo = ppo_from_config(cfg)
    train_cfg = train_config_from_config(cfg)
    shard_fn = shard_fn_from_config(cfg)
    num_seeds = int(cfg.get("num_seeds", 1))
    learning_rates = cfg.get("learning_rates")
    if learning_rates and num_seeds <= 1:
        # Validated before any dispatch so no path can silently drop it.
        raise SystemExit(
            "learning_rates is a population knob: set num_seeds to the "
            "number of rates (one member per rate)"
        )
    # Fail-fast at config time: unknown scenario names raise here naming
    # the registry entries (never a silent clean-env run).
    scenario_schedule = scenario_schedule_from_config(cfg)
    if train_cfg.architecture == "sebulba" and cfg.get("curriculum"):
        raise SystemExit(
            "architecture=sebulba does not compose with curriculum "
            "training yet (the hetero stage machinery is Anakin-shaped); "
            "drop one of the two"
        )
    if cfg.get("curriculum"):
        if num_seeds > 1 and learning_rates:
            raise SystemExit(
                "learning_rates does not compose with curriculum "
                "populations (candidate-seed selection trains at one "
                "rate); drop one of the two"
            )
        if scenario_schedule is not None:
            raise SystemExit(
                "scenarios do not compose with curriculum training yet "
                "(the hetero step is not scenario-wrapped); drop one of "
                "the two"
            )
        return build_hetero_trainer(
            cfg, env_params, ppo, train_cfg, shard_fn, num_seeds
        )
    policy = cfg.get("policy", "mlp")
    model = build_model(cfg, env_params, policy)
    if train_cfg.architecture == "sebulba":
        if num_seeds > 1:
            raise SystemExit(
                "architecture=sebulba does not compose with num_seeds>1 "
                "population sweeps yet (the sweep's vmapped iteration is "
                "Anakin-shaped); drop one of the two"
            )
        from marl_distributedformation_tpu.train import SebulbaDriver

        # Mesh / curriculum / recovery incompatibilities fail fast inside
        # the driver with actionable messages.
        return SebulbaDriver(
            env_params,
            ppo=ppo,
            config=train_cfg,
            model=model,
            shard_fn=shard_fn,
            scenario_schedule=scenario_schedule,
        )
    if train_cfg.architecture != "anakin":
        raise SystemExit(
            f"architecture={train_cfg.architecture!r} is unknown; "
            "available: anakin (fused same-device), sebulba (split "
            "acting/learning — docs/sebulba.md)"
        )
    if num_seeds > 1:
        if scenario_schedule is not None:
            raise SystemExit(
                "scenarios do not compose with num_seeds>1 population "
                "sweeps yet (the vmapped sweep iteration is not "
                "scenario-wrapped); drop one of the two"
            )
        from marl_distributedformation_tpu.train import SweepTrainer

        return SweepTrainer(
            env_params,
            ppo=ppo,
            config=train_cfg,
            num_seeds=num_seeds,
            model=model,
            mesh=getattr(shard_fn, "mesh", None),
            learning_rates=learning_rates,
        )
    return Trainer(
        env_params,
        ppo=ppo,
        config=train_cfg,
        model=model,
        shard_fn=shard_fn,
        scenario_schedule=scenario_schedule,
    )


def build_hetero_trainer(cfg, env_params, ppo, train_cfg, shard_fn,
                         num_seeds: int = 1):
    """Curriculum path (BASELINE.json config 5): mixed-size padded formations
    with an obstacle field, staged over ``cfg.curriculum``. With
    ``num_seeds > 1``, K candidate seeds of the full curriculum train in
    one vmapped program (train/hetero_sweep.py) — the det-gate candidate
    selection workflow (docs/acceptance/hetero5/)."""
    from marl_distributedformation_tpu.envs import spec_for_params
    from marl_distributedformation_tpu.train import (
        HeteroTrainer,
        curriculum_from_cfg,
    )

    env_name = spec_for_params(env_params).name
    if env_name != "formation":
        raise SystemExit(
            f"curriculum training is formation-only (the hetero padded-"
            f"formation machinery wraps env/hetero.py, not the registered-"
            f"env dispatch); env={env_name!r} does not compose — drop "
            "curriculum or set env=formation"
        )
    policy = cfg.get("policy", "mlp")
    if policy not in ("mlp", "ctde"):
        raise SystemExit(
            f"curriculum training supports policy=mlp (shared per-agent "
            f"MLP) and policy=ctde (masked centralized critic); "
            f"policy={policy!r} is not supported — the GNN needs knn obs, "
            "and heterogeneous formations are ring-observed"
        )
    if env_params.obs_mode != "ring":
        raise SystemExit(
            "curriculum training uses the ring observation model (padded "
            f"formations mask the ring per transition); obs_mode="
            f"{env_params.obs_mode!r} is not supported — set obs_mode=ring"
        )
    model = build_model(cfg, env_params, policy)
    curriculum = curriculum_from_cfg(cfg.curriculum)
    if num_seeds > 1:
        from marl_distributedformation_tpu.train import HeteroSweepTrainer

        return HeteroSweepTrainer(
            curriculum=curriculum,
            env_params=env_params,
            ppo=ppo,
            config=train_cfg,
            num_seeds=num_seeds,
            model=model,
            mesh=getattr(shard_fn, "mesh", None),
        )
    return HeteroTrainer(
        curriculum=curriculum,
        env_params=env_params,
        ppo=ppo,
        config=train_cfg,
        model=model,
        shard_fn=shard_fn,
    )


def _snapshot_config(cfg, log_dir) -> None:
    """Save the resolved run config to ``logs/{name}/config.json`` — the
    analog of hydra's per-run ``.hydra/config.yaml`` snapshot (the
    reference gets one implicitly via ``@hydra.main``; see
    docs/migration.md 'Run directory'). Only process 0 writes. A
    ``resume=true`` invocation never writes the canonical file —
    ``config.json`` always describes the config the run was originally
    trained with; resumes snapshot to ``config_resume.json`` (latest
    resume wins)."""
    import json
    from pathlib import Path

    from marl_distributedformation_tpu.parallel import is_coordinator

    if not is_coordinator():
        return
    path = Path(log_dir)
    path.mkdir(parents=True, exist_ok=True)
    name = "config_resume.json" if cfg.get("resume") else "config.json"
    snap = dict(cfg)
    # The requested config says what the user asked for; these say what
    # actually ran — an acceptance record claiming "TPU" must be able to
    # prove it from the run directory (e.g. after a silent CPU fallback).
    try:
        import jax

        dev = jax.devices()[0]
        snap["resolved_platform"] = dev.platform
        snap["resolved_device"] = dev.device_kind
    except Exception:  # noqa: BLE001 — a snapshot never kills a run
        pass
    with open(path / name, "w") as f:
        json.dump(snap, f, indent=2, default=str)


def main(argv=None) -> None:
    cfg = load_config(sys.argv[1:] if argv is None else argv)
    setup_platform(cfg.get("platform"))
    from marl_distributedformation_tpu.parallel import init_distributed

    if init_distributed():  # no-op single-process; env-var driven multi-host
        import jax

        print(
            f"[train] multi-host: process {jax.process_index()}/"
            f"{jax.process_count()}, {len(jax.local_devices())} local "
            f"of {len(jax.devices())} global devices"
        )
    trainer = build_trainer(cfg)
    _snapshot_config(cfg, trainer.log_dir)
    # Live-metrics plane (obs/metrics.py, docs/observability.md): the
    # trainer records env-steps/s, chunk drain latency, checkpoint-writer
    # health, and compile counters into the process registry;
    # telemetry_port serves them as Prometheus text on GET /metrics so a
    # bare training run is scrapeable without a serving fleet.
    from marl_distributedformation_tpu.obs import (
        TelemetryServer,
        configure_ledger,
        configure_metrics,
        get_ledger,
    )

    configure_metrics(
        enabled=bool(cfg.get("telemetry", True)),
        reservoir=int(cfg.get("telemetry_reservoir", 512)),
    )
    # Program ledger (obs/ledger.py): every compile this run performs
    # registers its executable's cost/memory facts; the census lands
    # beside the checkpoints at exit for program_report.py / the
    # chip-window census gate.
    configure_ledger(
        enabled=bool(cfg.get("ledger", True)),
        reservoir=int(cfg.get("ledger_reservoir", 256)),
    )
    telemetry = None
    if cfg.get("telemetry_port") is not None:
        telemetry = TelemetryServer(port=int(cfg.telemetry_port)).start()
        print(f"[train] telemetry: {telemetry.url}")
    print(
        f"[train] {cfg.name}: M={cfg.num_formation} formations x "
        f"N={cfg.num_agents_per_formation} agents, "
        f"{trainer.total_timesteps} agent-transitions, "
        f"logs -> {trainer.log_dir}"
    )
    try:
        final = trainer.train()
    finally:
        if telemetry is not None:
            telemetry.stop()
        ledger = get_ledger()
        if ledger.enabled and ledger.entries():
            from pathlib import Path as _Path

            try:
                path = ledger.write_census(
                    _Path(trainer.log_dir) / "program_ledger.json"
                )
                print(f"[train] program ledger census -> {path}")
            except OSError as e:
                print(f"[train] census write failed: {e!r}")
    print(f"[train] done at {trainer.num_timesteps} steps: {final}")


if __name__ == "__main__":
    main()
