#!/usr/bin/env python
"""Headline benchmark: parallel-formation env throughput on one chip.

Measures env-steps/sec (formation steps per second) for M=4096 parallel
5-agent formations driven by a uniform random policy inside one jitted
``lax.scan`` — the BASELINE.json north-star configuration ("4096 parallel
5-agent formations ... on 1 TPU core"). The reference achieves 1,066
formation-steps/s at its default M=1000x5 on CPU (BASELINE.md, measured:
sequential Python loop over torch simulators, vectorized_env.py:71-81);
``vs_baseline`` is the speedup over that number.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "env-steps/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.formation import reset_batch, step_batch

REFERENCE_FORMATION_STEPS_PER_SEC = 1066.0  # BASELINE.md, M=1000 x N=5, CPU

M = 4096  # parallel formations (north-star config)
N = 5  # agents per formation (default cfg)
CHUNK = 4096  # env steps per scan (amortizes tunnel RTT; see BENCH notes)
REPEATS = 4  # timed scans


def make_runner(params: EnvParams):
    @jax.jit
    def run_chunk(state, key):
        def body(carry, _):
            state, key = carry
            key, k_act = jax.random.split(key)
            # Uniform random policy in [-1, 1], scaled like the adapter
            # (vectorized_env.py:69-70) — matches how BASELINE.md measured
            # the reference (env stepping only, no policy inference).
            actions = jax.random.uniform(
                k_act, (M, params.num_agents, 2), minval=-1.0, maxval=1.0
            )
            state, tr = step_batch(
                state, params.max_speed * actions, params
            )
            return (state, key), tr.reward.mean()
        (state, key), rewards = jax.lax.scan(
            body, (state, key), None, length=CHUNK
        )
        return state, key, rewards.mean()

    return run_chunk


def main() -> None:
    params = EnvParams(num_agents=N)
    key = jax.random.PRNGKey(0)
    state = reset_batch(key, params, M)
    run_chunk = make_runner(params)

    # Warmup: compile + one execution.
    state, key, r = run_chunk(state, jax.random.PRNGKey(1))
    float(r)

    t0 = time.perf_counter()
    for _ in range(REPEATS):
        state, key, r = run_chunk(state, key)
    float(r)  # hard host sync — block_until_ready under-reports on the
    # experimental axon platform (returns before queued chunks finish)
    elapsed = time.perf_counter() - t0

    env_steps = M * CHUNK * REPEATS
    rate = env_steps / elapsed
    print(
        f"[bench] device={jax.devices()[0].device_kind} M={M} N={N} "
        f"steps={env_steps} elapsed={elapsed:.3f}s "
        f"agent_steps_per_sec={rate * N:.0f}",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": f"env_steps_per_sec_{M}x{N}_single_chip",
                "value": round(rate, 1),
                "unit": "env-steps/s",
                "vs_baseline": round(
                    rate / REFERENCE_FORMATION_STEPS_PER_SEC, 2
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
