#!/usr/bin/env python
"""Headline benchmark: formation-env + PPO-train throughput on one chip.

Measures, inside one process and one JSON line:

- ``env_steps_per_sec`` (headline ``value``): M parallel N-agent formations
  driven by a uniform random policy inside one jitted ``lax.scan`` — the
  BASELINE.json north-star configuration (M=4096 x N=5 on one TPU core).
  The reference achieves 1,066 formation-steps/s at its default M=1000x5 on
  CPU (BASELINE.md, measured: sequential Python loop over torch simulators,
  reference vectorized_env.py:71-81); ``vs_baseline`` is the speedup over
  that number.
- ``train_env_steps_per_sec``: the FULL PPO training iteration
  (rollout + GAE + minibatch-epoch update — the ``Trainer._iteration`` XLA
  program), in formation-steps/s. This is the workload the framework exists
  for, not just env stepping.
- ``knn_env_steps_per_sec``: the large-swarm variant (N=100 agents, k-NN
  observation graph, BASELINE.json config 4).
- ``knn_big_env_steps_per_sec``: the N=1024 swarm past the fused kernel's
  VMEM cliff (chunked-streaming kernel on TPU, XLA elsewhere; the
  ``knn_big_impl`` field records which ran).
- ``scenario_env_steps_per_sec``: env stepping through the 3-layer
  "storm" disturbance stack (scenarios/) — the scenario engine's wrapper
  overhead vs the clean headline (``scenario_overhead_pct``).
- ``env_steps_per_sec_formation`` / ``env_steps_per_sec_pursuit_evasion``:
  the registered-env ladder (envs/) — every env in the registry timed
  through the same random-policy chunk via params-type dispatch, plus
  ``obstacle_overhead_pct``: the obstacle_field occlusion layer
  (layout-driven neighbor masking) vs the clean step on the same
  4-obstacle params.
- ``train_env_steps_per_sec_fused_scan``: the Anakin fused-scan trainer
  (``TrainConfig.fused_chunk``): K full PPO iterations per ``lax.scan``
  dispatch, best rate over the chunk ladder {1, 8, 32}, with the
  compile-once RetraceGuard receipts and ``dispatch_overhead_pct`` (the
  host loop's per-iteration dispatch/drain cost vs the fused program).
- ``sweep_env_steps_per_sec_fused_scan``: the Anakin POPULATION sweep
  (``SweepTrainer`` + ``fused_chunk``): K independent PPO runs advanced
  by one fused-scan program, rate counted across all members, vs the
  host-loop sweep at matched K/M (``sweep_env_steps_per_sec_host_loop``,
  ``sweep_dispatch_overhead_pct``) with per-rung compile-once receipts.
- ``serving_requests_per_sec_fleet`` / ``serving_fleet_p95_ms``: the
  serving-side number — a 2-replica fleet (serving/fleet/) driven by the
  mixed-size smoke storm on a forced 2-device CPU, measured in a
  subprocess (the multi-device CPU flag must land before backend init).
- ``promotion_latency_s_p50``/``p95`` + ``gate_eval_steps_per_sec``: the
  always-learning pipeline (pipeline/, scripts/always_learning.py) run
  end to end — trainer streaming checkpoints through the promotion gate
  into a 2-replica fleet; latency is train-step -> served ``model_step``
  wall time, with the gate's one-compile receipt
  (``pipeline_gate_compiles``) alongside.
- ``serving_req_per_sec_at_p95_slo``: the capacity number — max
  sustained OPEN-loop request rate holding a p95 latency target
  (serving/loadgen.py bisection) on the full sharded+bf16 fleet, with
  ``serving_sharded_512_p95_ms`` vs ``serving_replicated_512_p95_ms``
  (same trace, with/without the mesh-backed big-rung slice) and
  ``serving_bf16_speedup_pct`` beside it.
- ``telemetry_overhead_pct``: the live-metrics plane's cost — the
  phase-5 fused training loop re-timed through the real instrumented
  drain seam with the MetricsRegistry enabled vs disabled (interleaved
  passes, same methodology as ``tracing_overhead_pct``), with
  ``sentinel_checks_per_sec`` (RegressionSentinel poll cost vs the
  newest committed BENCH record) beside it.
- ``adversarial_candidates_per_sec``: the falsifier-search throughput
  (scenarios/adversary.py — one vmapped compiled eval per generation,
  ``adversarial_search_compiles`` == 1 across all generations and both
  trained policies) and ``worst_case_return_gap_pct``: the
  auto-curriculum payoff — curriculum-trained vs clean-trained return
  at the discovered worst cases, equal training steps.
- ``chaos_mttr_s`` / ``chaos_invariant_violations`` /
  ``fault_plane_overhead_pct``: the chaos plane (chaos/,
  scripts/chaos_storm.py) — one seeded fault campaign through the
  whole trainer -> gate -> fleet loop; MTTR is worst kill -> first
  served recovery, violations MUST be 0, and the disabled plane's
  per-request cost is ~0 (one attribute read per injection point).
- ``ledger_overhead_pct`` / ``ledger_program_count`` /
  ``ledger_compile_seconds_total``: the program ledger (obs/ledger.py)
  — the fused loop re-timed with per-dispatch ledger recording on vs
  off (interleaved, same methodology as phases 8/11), plus the census
  headlines off the whole bench run's process-global ledger: how many
  compiled executables registered and their attributed backend-compile
  wall. The census itself is what a chip window commits beside this
  record (``check_bench_record.py --census``).
- ``mesh_req_per_sec`` / ``mesh_global_swap_latency_s_p50``/``_p95`` /
  ``mesh_failover_lost_requests``: the cross-host tier
  (serving/mesh/, docs/mesh.md) — a loopback 2-host mesh (real host
  SUBPROCESSES behind the MetaRouter) hammered by client threads
  while the coordinator drives global barrier swaps and one host is
  killed with a real SIGKILL mid-load. Lost requests MUST be 0, step
  monotonicity must hold across hosts (``mesh_step_violations`` == 0),
  and every surviving host's compile receipts stay at 1
  (``mesh_host_compile_receipts_max``).
- ``health_overhead_pct`` / ``recovery_mttr_s`` /
  ``train_divergence_events``: the self-healing train lane
  (train/recovery.py, docs/recovery.md) — the fused loop re-timed with
  the in-program health word + skip guard ON vs OFF (interleaved,
  phase-11 methodology; the bar is <= 5%), plus a seeded NaN carry
  bomb through a live fused run with the recovery ladder armed:
  detection-at-drain -> rollback wall clock from recovery.jsonl, and
  the ladder's sustained-breach count (>= 1 or the detector is
  broken).
- ``graftlint_wall_s``: one full ``scripts/graftlint.py --check`` pass
  over the package (pure-AST, subprocess — the exact CI invocation).
  The call-graph engine rebuilds its whole-repo graph from a cold
  process, so this wall is the worst-case lint cost a pre-commit hook
  pays; check_bench_record.py holds it under a ceiling so the
  whole-package analyses (lock-ordering cycles, guarded-write DFS)
  cannot quietly go super-linear as the repo grows.
- ``sebulba_env_steps_per_sec`` / ``sebulba_learner_steps_per_sec`` /
  ``transfer_queue_occupancy_p95`` / ``param_staleness_p95_updates`` /
  ``gate_eval_p50_under_load_s``: the sebulba lane (train/sebulba/,
  docs/sebulba.md) — one pipelined actor/learner run with the bounded
  TransferQueue between the slices, per-slice budget-1 compile
  receipts (``sebulba_actor_compiles`` / ``sebulba_learner_compiles``
  MUST be 1), and the promotion gate evaluating live checkpoints from
  its OWN slice while the learner is saturated (steady-state eval
  wall, post-compile).

Phases skipped via
  ``BENCH_SKIP_*`` env vars record the explicit ``"skipped"`` sentinel
  in their rate fields plus a ``phases_skipped`` list, so "not run"
  never reads as "regressed to absent".

Hardened against the flaky axon tunnel (round-1 failure mode: the first
device op hung for minutes and the round recorded nothing):

- the backend is probed in a SUBPROCESS with a hard timeout, retried once
  with backoff; if the probe never answers, the bench falls back to the CPU
  backend (recorded via ``"platform"``/``"fallback"`` fields) so a parseable
  number is always emitted;
- every phase checks a global deadline (``BENCH_BUDGET_S``, default 600s)
  and per-phase failures degrade to a note instead of killing the run;
- any unexpected error still prints the one JSON line, with an ``"error"``
  field.

Env-var knobs: BENCH_M, BENCH_N, BENCH_CHUNK, BENCH_TRAIN_M, BENCH_KNN_M,
BENCH_KNN_BIG_M, BENCH_KNN_BIG_N, BENCH_BUDGET_S, BENCH_PROBE_TIMEOUT_S,
BENCH_FUSED_CHUNKS (default "1,8,32"; empty disables the fused phase),
BENCH_SWEEP_CHUNKS (default "1,8"; empty disables the fused-sweep
rungs), BENCH_SWEEP_SEEDS, BENCH_SWEEP_M, BENCH_SWEEP_REPEATS
(interleaved best-of passes per rung, default 5), BENCH_SKIP_SWEEP=1,
BENCH_FORCE_CPU=1, BENCH_SKIP_TRAIN=1, BENCH_SKIP_KNN=1,
BENCH_SKIP_KNN_BIG=1, BENCH_SKIP_SCENARIO=1, BENCH_SKIP_ENVS=1,
BENCH_ENVS_M, BENCH_SKIP_SERVING=1,
BENCH_SERVING_DURATION_S, BENCH_SKIP_PIPELINE=1, BENCH_PIPELINE_M,
BENCH_PIPELINE_GATE_M, BENCH_PIPELINE_BUDGET_S, BENCH_SLO_DURATION_S,
BENCH_SLO_P95_MS, BENCH_SKIP_ADVERSARIAL=1, BENCH_ADV_M,
BENCH_ADV_ITERS, BENCH_ADV_EVAL_M, BENCH_TELEMETRY_CHUNK,
BENCH_TELEMETRY_PASSES, BENCH_SENTINEL_CHECKS, BENCH_SKIP_CHAOS=1,
BENCH_CHAOS_SEED, BENCH_CHAOS_FAULTS, BENCH_LEDGER_CHUNK,
BENCH_LEDGER_PASSES (the ledger phase shares BENCH_SKIP_TRAIN),
BENCH_SKIP_MESH=1, BENCH_MESH_HOSTS, BENCH_MESH_DURATION_S,
BENCH_MESH_SWAPS, BENCH_SKIP_LINT=1, BENCH_LINT_TIMEOUT_S,
BENCH_SKIP_SEBULBA=1, BENCH_SEBULBA_M, BENCH_SEBULBA_ITERS,
BENCH_SEBULBA_CHUNK.

Prints exactly one JSON line with at least:
    {"metric": ..., "value": N, "unit": "env-steps/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REFERENCE_FORMATION_STEPS_PER_SEC = 1066.0  # BASELINE.md, M=1000 x N=5, CPU

# Honest denominator for the TRAIN metric: the reference's *full* SB3
# training loop, not just env stepping — estimated by measuring its three
# components with the same torch-CPU stack (env loop 1.07 vec-steps/s from
# BASELINE.md + measured MlpPolicy inference + measured minibatch
# fwd/bwd/Adam x 7810 per iteration at SB3 defaults). Method + raw numbers:
# scripts/estimate_reference_train.py, docs/reference_train_estimate.md.
REFERENCE_TRAIN_FORMATION_STEPS_PER_SEC = 255.2


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


# Explicit not-run marker for env-var-skipped phases. Before this, a
# BENCH_SKIP_SERVING=1 run simply lacked the serving fields —
# indistinguishable from a run where the phase silently regressed to
# absent. The sentinel value lands IN the rate fields (consumers must
# treat it as "not a number, not missing") and the skipped phase names
# accumulate in ``phases_skipped``.
SKIPPED = "skipped"


def _mark_skipped(result: dict, phase: str, fields) -> None:
    for f in fields:
        result[f] = SKIPPED
    result.setdefault("phases_skipped", []).append(phase)


def _num(rec: dict, key: str, default: float = 0.0) -> float:
    """A record field as a float, treating the ``"skipped"`` sentinel
    (and any other non-number) as absent."""
    try:
        return float(rec.get(key, default))
    except (TypeError, ValueError):
        return default


M = _env_int("BENCH_M", 4096)  # parallel formations (north-star config)
N = _env_int("BENCH_N", 5)  # agents per formation (default cfg)
CHUNK = _env_int("BENCH_CHUNK", 1024)  # env steps per jitted scan
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", 600))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 30))
MIN_TIMED_S = 3.0  # keep timing until a phase has at least this much signal


def probe_backend(timeout_s: float = PROBE_TIMEOUT_S):
    """Ask a subprocess what backend JAX resolves to, under a hard timeout.

    Round 1 showed ``jax.devices()`` can hang for minutes when the tunneled
    TPU is unreachable; probing out-of-process keeps this process healthy and
    lets it fall back to CPU. Returns the platform string or None.

    ONE attempt at 30s (VERDICT r4 next-#6): an up tunnel answers a device
    query in ~5-10s, so the old 2x75s retry ladder only delayed the CPU
    fallback by minutes in the short-window tunnel regime. Chip windows are
    caught by the watchdog (scripts/chip_watchdog.sh), not by bench retries;
    set BENCH_PROBE_TIMEOUT_S to lengthen when a slow link is expected.
    """
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
        for line in reversed(out.stdout.splitlines()):
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1].strip()
    except subprocess.TimeoutExpired:
        print(
            f"[bench] backend probe timed out after {timeout_s:.0f}s",
            file=sys.stderr,
        )
    return None


def make_runner(params, m: int, chunk: int):
    """Jitted random-policy env-stepping chunk: ``chunk`` vec-steps of ``m``
    formations per call (amortizes dispatch/tunnel RTT)."""
    import jax

    from marl_distributedformation_tpu.envs import spec_for_params

    # Registered-env dispatch (envs/): formation params resolve to the
    # legacy step_batch verbatim, PursuitParams to the pursuit step — the
    # same runner times every registered env.
    step_batch = spec_for_params(params).step_batch

    @jax.jit
    def run_chunk(state, key):
        def body(carry, _):
            state, key = carry
            key, k_act = jax.random.split(key)
            # Uniform random policy in [-1, 1], scaled like the adapter
            # (reference vectorized_env.py:69-70) — matches how BASELINE.md
            # measured the reference (env stepping only, no policy inference).
            actions = jax.random.uniform(
                k_act, (m, params.num_agents, 2), minval=-1.0, maxval=1.0
            )
            state, tr = step_batch(state, params.max_speed * actions, params)
            return (state, key), tr.reward.mean()

        (state, key), rewards = jax.lax.scan(
            body, (state, key), None, length=chunk
        )
        return state, key, rewards.mean()

    return run_chunk


def make_scenario_runner(params, m: int, chunk: int, sp):
    """Scenario-stacked twin of ``make_runner``: the same random-policy
    chunk through ``scenarios.scenario_step_batch`` with the disturbance
    params as a traced argument (measures the wrapper's true overhead —
    every layer's math is in the program, magnitudes are data)."""
    import jax

    from marl_distributedformation_tpu.scenarios import scenario_step_batch

    @jax.jit
    def run_chunk(state, key, sp):
        def body(carry, _):
            state, key = carry
            key, k_act = jax.random.split(key)
            actions = jax.random.uniform(
                k_act, (m, params.num_agents, 2), minval=-1.0, maxval=1.0
            )
            state, tr = scenario_step_batch(
                state, params.max_speed * actions, sp, params
            )
            return (state, key), tr.reward.mean()

        (state, key), rewards = jax.lax.scan(
            body, (state, key), None, length=chunk
        )
        return state, key, rewards.mean()

    def run(state, key):
        return run_chunk(state, key, sp)

    return run


def _time_env_phase(
    params, m: int, chunk: int, deadline: float, scenario=None
) -> float:
    """Adaptive timing: warm up (compile + 1 exec), then run timed chunks
    until MIN_TIMED_S of signal or the deadline. Returns formation-steps/s.
    ``scenario`` (ScenarioParams) times the disturbance-stacked step."""
    import jax

    from marl_distributedformation_tpu.envs import spec_for_params

    state = spec_for_params(params).reset_batch(jax.random.PRNGKey(0), params, m)
    if scenario is None:
        run_chunk = make_runner(params, m, chunk)
    else:
        run_chunk = make_scenario_runner(params, m, chunk, scenario)

    state, key, r = run_chunk(state, jax.random.PRNGKey(1))
    float(r)  # hard host sync: block_until_ready under-reports on axon

    repeats = 0
    t0 = time.perf_counter()
    while True:
        state, key, r = run_chunk(state, key)
        float(r)
        repeats += 1
        elapsed = time.perf_counter() - t0
        if elapsed >= MIN_TIMED_S or time.time() > deadline or repeats >= 64:
            break
    return m * chunk * repeats / elapsed


def _time_train_phase(
    n_agents: int, m: int, deadline: float, ppo=None, iters_per_dispatch=1
):
    """Time the full jitted PPO iteration (rollout + GAE + update) —
    ``Trainer._iteration``. ``iters_per_dispatch > 1`` times the scan-fused
    multi-iteration program (TrainConfig.iters_per_dispatch). Returns
    (train_env_steps_per_sec, iters_per_sec, n_steps)."""
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    ppo = ppo or PPOConfig()
    trainer = Trainer(
        EnvParams(num_agents=n_agents),
        ppo=ppo,
        config=TrainConfig(
            num_formations=m, checkpoint=False, use_wandb=False,
            name="bench", iters_per_dispatch=iters_per_dispatch,
        ),
    )
    # Warm up TWICE: the first execution's donated outputs adopt the
    # compiled program's shardings, which can retrace the second call —
    # timing after one warmup would include that compile.
    for _ in range(2):
        metrics = trainer.run_iteration()
        float(metrics["loss"])

    # Sync once per BURST of iterations, not per iteration: a host sync
    # pays a full tunnel RTT, which at tuned-config speeds (~84 ms/iter)
    # would be a material fraction of every iteration. XLA executions on
    # one device are serialized, so syncing the last iteration's metrics
    # times the whole burst; the burst is small enough that the dispatch
    # queue stays bounded.
    burst = 8
    iters = 0
    t0 = time.perf_counter()
    while True:
        for _ in range(burst):
            metrics = trainer.run_iteration()
            iters += 1
            if time.time() > deadline:  # pure wall-clock, no host sync —
                break  # keep deadline responsiveness per-iteration
        float(metrics["loss"])  # host sync for the whole burst
        elapsed = time.perf_counter() - t0
        if elapsed >= MIN_TIMED_S or time.time() > deadline or iters >= 256:
            break
    iters *= iters_per_dispatch  # each dispatch ran this many iterations
    rate = ppo.n_steps * m * iters / elapsed
    return rate, iters / elapsed, ppo.n_steps


def _time_fused_phase(n_agents: int, m: int, deadline: float, ppo, chunk: int):
    """Time the Anakin fused-scan program (``TrainConfig.fused_chunk``):
    ``chunk`` full PPO iterations per ``lax.scan`` dispatch, per-iteration
    metrics stacked on-device. Returns
    ``(train_env_steps_per_sec, iters_per_sec, compile_count)`` —
    ``compile_count`` is the RetraceGuard receipt (the fused program must
    compile exactly once per config)."""
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import TrainConfig, Trainer

    trainer = Trainer(
        EnvParams(num_agents=n_agents),
        ppo=ppo,
        config=TrainConfig(
            num_formations=m, checkpoint=False, use_wandb=False,
            name="bench_fused", fused_chunk=chunk,
        ),
    )
    # Warm up twice, same rationale as _time_train_phase (donated outputs
    # adopting the program's shardings can retrace the second call). A
    # large chunk's warmup is a whole compile + 2*K iterations, so check
    # the deadline between dispatches — a blown budget degrades to a
    # short timing window instead of starving the watchdog.
    for _ in range(2):
        stacked = trainer.run_chunk()
        float(stacked["loss"][-1])
        if time.time() > deadline:
            break

    # Keep >= 2 dispatches in flight between host syncs so the queue
    # pipelines like the real Anakin loop (drain overlapped with the
    # next chunk) — a sync after every dispatch would serialize the
    # mode whose point is not serializing.
    burst = max(8 // chunk, 2)
    dispatches = 0
    t0 = time.perf_counter()
    while True:
        for _ in range(burst):
            stacked = trainer.run_chunk()
            dispatches += 1
            if time.time() > deadline:
                break
        float(stacked["loss"][-1])  # host sync for the whole burst
        elapsed = time.perf_counter() - t0
        if (
            elapsed >= MIN_TIMED_S
            or time.time() > deadline
            or dispatches * chunk >= 256
        ):
            break
    iters = dispatches * chunk
    rate = trainer.ppo.n_steps * m * iters / elapsed
    return rate, iters / elapsed, trainer.retrace_guard.count


def _make_sweep_timer(
    n_agents: int, m: int, num_seeds: int, ppo, fused_chunk: int = 0
):
    """Build + warm a K-member population sweep (``SweepTrainer``) and
    return ``(run_timed, trainer)``: ``run_timed(deadline)`` times the
    already-compiled program for one pass and returns
    ``(population_env_steps_per_sec, iters_per_sec)``. One dispatch
    advances every member one iteration (host loop, ``fused_chunk=0``)
    or ``fused_chunk`` iterations (Anakin fused-scan population mode);
    rates count formation-steps across ALL members. Splitting
    construction from timing lets the sweep phase interleave repeated
    passes over every rung — on a contended host one long pass per
    config confounds the fused-vs-host comparison with load drift, and
    this comparison is the phase's whole point."""
    import jax

    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.train import SweepTrainer, TrainConfig

    trainer = SweepTrainer(
        EnvParams(num_agents=n_agents),
        ppo=ppo,
        config=TrainConfig(
            num_formations=m, checkpoint=False, use_wandb=False,
            name="bench_sweep", fused_chunk=fused_chunk,
        ),
        num_seeds=num_seeds,
    )
    step = trainer.run_chunk if fused_chunk else trainer.run_iteration
    iters_per_dispatch = fused_chunk or 1
    # Warm up twice (donated outputs adopting the program's shardings can
    # retrace the second call — the _time_train_phase rationale).
    for _ in range(2):
        jax.block_until_ready(step())

    def run_timed(deadline: float):
        # Sync once per burst of >= 2 dispatches so the fused mode
        # pipelines like the real driver (drain overlapped with the
        # next chunk).
        burst = max(8 // iters_per_dispatch, 2)
        dispatches = 0
        t0 = time.perf_counter()
        while True:
            for _ in range(burst):
                metrics = step()
                dispatches += 1
                if time.time() > deadline:
                    break
            jax.block_until_ready(metrics)  # host sync for the burst
            elapsed = time.perf_counter() - t0
            if (
                elapsed >= MIN_TIMED_S
                or time.time() > deadline
                or dispatches * iters_per_dispatch >= 256
            ):
                break
        iters = dispatches * iters_per_dispatch
        rate = trainer.ppo.n_steps * m * num_seeds * iters / elapsed
        return rate, iters / elapsed

    return run_timed, trainer


def _latest_chip_bench_claim() -> str:
    """Compose the fallback JSON's pointer at the newest committed chip
    bench record (``docs/acceptance/tpu_bench_r*.md``) at runtime.

    The records are written by ``scripts/mirror_bench.py`` (or round 3's
    hand-mirrored ``tpu_bench_r3.md``); both carry the raw bench JSON
    line(s) and a measurement date. Parsing the newest file keeps the
    replayed claim from going stale when a later round lands a new
    record — the round-3 version of this field froze one round's numbers
    in source. Any parse problem degrades to a generic pointer rather
    than failing the bench."""
    import re
    from pathlib import Path

    root = Path(__file__).resolve().parent

    def _round_no(p) -> int:
        # Numeric, not lexicographic: "r10" must beat "r9".
        m = re.search(r"tpu_bench_r(\d+)", p.name)
        return int(m.group(1)) if m else -1

    records = sorted(
        root.glob("docs/acceptance/tpu_bench_r*.md"),
        key=_round_no,
        reverse=True,
    )
    for path in records:
        try:
            text = path.read_text()
            # Candidate JSON payloads: fenced ```json blocks (the
            # mirror_bench.py format indents over many lines) and bare
            # single-line objects (the round-3 hand-mirrored format).
            payloads = re.findall(r"```json\n(.*?)```", text, re.DOTALL)
            payloads += [
                ln.strip()
                for ln in text.splitlines()
                if ln.strip().startswith("{")
            ]
            def _train_claim(r: dict):
                # Best training rate a record carries, across field
                # generations, preferring the population-sweep fused
                # rate (aggregate formation-steps/s over all K members
                # — the repo's biggest training number, recorded since
                # r6) over the single-run ladder (fused_scan r6,
                # tuned_fused r3-r5, tuned always). Returns
                # (rate, label) or (0.0, None).
                # _num: a "skipped" sentinel in a rate field (phase
                # disabled by env var) reads as absent, not a crash.
                sweep = _num(r, "sweep_env_steps_per_sec_fused_scan")
                if sweep:
                    k = r.get("sweep_num_seeds")
                    label = (
                        f"fused {k}-member population sweep"
                        if k
                        else "fused population sweep"
                    )
                    return sweep, label
                single = (
                    _num(r, "train_env_steps_per_sec_fused_scan")
                    or _num(r, "train_env_steps_per_sec_tuned_fused")
                    or _num(r, "train_env_steps_per_sec_tuned")
                )
                return single, "tuned full-PPO train"

            def _tuned(r: dict) -> float:
                return _train_claim(r)[0]

            recs = []
            for payload in payloads:
                try:
                    cand = json.loads(payload)
                except json.JSONDecodeError:
                    continue
                if cand.get("metric") and not cand.get("fallback"):
                    recs.append(cand)
            if not recs:
                continue
            # A record file may carry several runs (round 3 mirrors both
            # the full run and a burst-synced re-measure) — claim the
            # best training rate, falling back to the best env rate.
            rec = max(recs, key=lambda r: (_tuned(r), _num(r, "value")))
            date = None
            m = re.search(r"measured: (\S+)", text)
            if m:
                date = m.group(1)
            else:
                m = re.search(r"(\d{4}-\d{2}-\d{2})", text)
                date = m.group(1) if m else "date unrecorded"
            env_rate = float(rec.get("value", 0.0))
            tuned, tuned_label = _train_claim(rec)
            tuned_txt = (
                f", {tuned_label} {tuned / 1e3:,.0f}k formation-steps/s"
                if tuned
                else ""
            )
            rel = path.relative_to(root)
            return (
                f"recorded {date}: env {env_rate / 1e6:,.1f}M "
                f"formation-steps/s{tuned_txt} on "
                f"{rec.get('device', 'unknown device')} ({rel}; tunnel "
                "down at bench time)"
            )
        except Exception:  # noqa: BLE001 — a replay field never kills bench
            continue
    return (
        "recorded: no committed chip bench record found under "
        "docs/acceptance/ (tunnel down at bench time)"
    )


def _make_emitter(result: dict):
    """Single-shot JSON emitter shared by the main path and the watchdog, so
    exactly one JSON line prints no matter which one gets there."""
    emitted = threading.Event()
    lock = threading.Lock()

    def emit():
        with lock:
            if not emitted.is_set():
                print(json.dumps(result), flush=True)
                emitted.set()

    emit.done = emitted
    return emit


def main() -> None:
    deadline = time.time() + BUDGET_S
    result = {
        "metric": f"env_steps_per_sec_{M}x{N}_single_chip",
        "value": 0.0,
        "unit": "env-steps/s",
        "vs_baseline": 0.0,
    }
    notes = []
    emit = _make_emitter(result)

    def watchdog():
        # Device ops in THIS process have no timeout (the probe only covers
        # a subprocess): if the tunnel drops between probe and use, a compile
        # or execute can hang forever — a hang is not an Exception, so the
        # try/except below never fires. Guarantee the JSON line anyway, then
        # hard-exit (daemon threads can't interrupt a stuck runtime call).
        time.sleep(max(deadline - time.time(), 0.0) + 60.0)
        if emit.done.is_set():
            return  # bench finished normally; never kill a host process
        result.setdefault(
            "error", "watchdog: budget exceeded (device op hang?)"
        )
        if notes:
            result.setdefault("notes", "; ".join(notes))
        emit()
        os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
        platform = None if force_cpu else probe_backend()
        fallback = platform is None and not force_cpu

        import jax

        if platform is None:
            jax.config.update("jax_platforms", "cpu")
            if fallback:
                notes.append("device backend unreachable; fell back to CPU")
        # Report what the process ACTUALLY runs on (config.update cannot
        # switch an already-initialized backend, e.g. under pytest).
        platform = jax.default_backend()
        on_accel = platform != "cpu"  # tunneled TPU reports "axon", not "tpu"
        result["platform"] = platform
        result["fallback"] = fallback
        result["device"] = jax.devices()[0].device_kind
        if fallback:
            # Machine-readable pointer at the last REAL chip record, with
            # its measurement date — a fallback JSON should carry the
            # hardware story explicitly instead of leaving only CPU
            # numbers beside a "fallback" flag (VERDICT r3 weak #1). The
            # "recorded" prefix marks it a replay, same contract as the
            # parity fields. Parsed from the newest committed
            # docs/acceptance/tpu_bench_r*.md at runtime so the pointer
            # can never go stale when a later round mirrors a new record.
            result["recorded_chip_bench"] = _latest_chip_bench_claim()

        from marl_distributedformation_tpu.env import EnvParams

        # Phase 1 — headline: random-policy env stepping, north-star shape.
        rate = _time_env_phase(
            EnvParams(num_agents=N), M, CHUNK, deadline
        )
        result["value"] = round(rate, 1)
        result["vs_baseline"] = round(
            rate / REFERENCE_FORMATION_STEPS_PER_SEC, 2
        )
        result["agent_steps_per_sec"] = round(rate * N, 1)
        print(
            f"[bench] env: {rate:,.0f} formation-steps/s on {platform}",
            file=sys.stderr,
        )

        # Phase 1b — headroom: same env at 4x the formations. The
        # north-star M=4096 batch is small enough that a per-scan-step
        # latency floor (RNG chain, tiny fused kernels) can dominate; if
        # stepping is latency-bound rather than compute-bound, the
        # bigger batch raises throughput nearly for free and this field
        # records how far the single-chip ceiling actually sits above
        # the headline. Accelerator-only (on one vCPU it just splits the
        # same FLOPs) and skippable via BENCH_SKIP_ENV_MAX=1.
        if (
            on_accel
            and os.environ.get("BENCH_SKIP_ENV_MAX") != "1"
            and time.time() < deadline - 30
        ):
            try:
                m_max = _env_int("BENCH_ENV_MAX_M", 4 * M)
                rate_max = _time_env_phase(
                    EnvParams(num_agents=N), m_max, CHUNK, deadline
                )
                result["env_max_steps_per_sec"] = round(rate_max, 1)
                result["env_max_m"] = m_max
                print(
                    f"[bench] env-max (M={m_max}): {rate_max:,.0f} "
                    "formation-steps/s",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"env-max phase failed: {e!r}"[:200])

        # Phase 1c — scenario engine overhead: the same env stepping
        # through the 3-layer "storm" disturbance stack (wind + actuator
        # noise + sensor noise, scenarios/) at severity 1. The wrapper
        # keeps every layer's math in the compiled program with
        # magnitudes as traced data, so this rate vs the headline is the
        # full price of scenario-readiness — recorded so the perf
        # trajectory catches a regression in the stack.
        if (
            os.environ.get("BENCH_SKIP_SCENARIO") != "1"
            and time.time() < deadline - 30
        ):
            try:
                import jax.numpy as jnp

                from marl_distributedformation_tpu.scenarios import (
                    broadcast_params,
                    get_scenario,
                )

                storm = broadcast_params(
                    get_scenario("storm").build(jnp.float32(1.0)), M
                )
                rate_scen = _time_env_phase(
                    EnvParams(num_agents=N), M, CHUNK, deadline,
                    scenario=storm,
                )
                result["scenario_env_steps_per_sec"] = round(rate_scen, 1)
                result["scenario_stack"] = "storm@1.0"
                if rate:
                    result["scenario_overhead_pct"] = round(
                        max(0.0, (1.0 - rate_scen / rate) * 100.0), 1
                    )
                print(
                    f"[bench] scenario (storm, 3 layers): "
                    f"{rate_scen:,.0f} formation-steps/s "
                    f"({result.get('scenario_overhead_pct', 0.0):.1f}% "
                    "overhead vs clean)",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"scenario phase failed: {e!r}"[:200])

        # Phase 1d — registered-env ladder (envs/, docs/environments.md):
        # the SAME random-policy chunk through every registered
        # environment at matched M/N/chunk, via the registry's params-type
        # dispatch (spec_for_params) — the formation rate here re-times
        # the headline path through the registry (a materially lower
        # number than phase 1 would mean the indirection itself costs,
        # which it must not: the dispatch resolves at trace time), and
        # the pursuit rate is the second env's first perf number. Plus
        # obstacle_overhead_pct: the obstacle_field occlusion layer
        # (layout-driven neighbor masking, scenarios/layers.py) vs the
        # clean step on the SAME num_obstacles>0 params.
        if os.environ.get("BENCH_SKIP_ENVS") == "1":
            _mark_skipped(
                result,
                "envs",
                (
                    "env_steps_per_sec_formation",
                    "env_steps_per_sec_pursuit_evasion",
                    "obstacle_overhead_pct",
                ),
            )
        elif time.time() < deadline - 30:
            try:
                from marl_distributedformation_tpu.envs import (
                    get_env,
                    registered_envs,
                )
                from marl_distributedformation_tpu.scenarios import (
                    broadcast_params,
                    scenario_params_for,
                )

                envs_m = _env_int("BENCH_ENVS_M", M if on_accel else 256)
                envs_chunk = max(CHUNK // 8, 16)
                for env_name in registered_envs():
                    spec = get_env(env_name)
                    env_rate = _time_env_phase(
                        spec.default_params(num_agents=N),
                        envs_m, envs_chunk, deadline,
                    )
                    result[f"env_steps_per_sec_{env_name}"] = round(
                        env_rate, 1
                    )
                    print(
                        f"[bench] envs ({env_name}): {env_rate:,.0f} "
                        "formation-steps/s",
                        file=sys.stderr,
                    )
                result["envs_m"] = envs_m
                # Obstacle-layer overhead: clean vs obstacle_field@1.0
                # (80 px occlusion masking the layout-declared neighbor
                # blocks) on the same 4-obstacle formation params.
                obst_params = EnvParams(num_agents=N, num_obstacles=4)
                clean_rate = _time_env_phase(
                    obst_params, envs_m, envs_chunk, deadline
                )
                occl = broadcast_params(
                    scenario_params_for("obstacle_field", 1.0), envs_m
                )
                occl_rate = _time_env_phase(
                    obst_params, envs_m, envs_chunk, deadline, scenario=occl
                )
                if clean_rate:
                    result["obstacle_overhead_pct"] = round(
                        max(0.0, (1.0 - occl_rate / clean_rate) * 100.0), 1
                    )
                result["obstacle_stack"] = "obstacle_field@1.0 (K=4)"
                print(
                    f"[bench] obstacle_field occlusion: {occl_rate:,.0f} "
                    f"vs clean {clean_rate:,.0f} formation-steps/s "
                    f"({result.get('obstacle_overhead_pct', 0.0):.1f}% "
                    "overhead)",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"envs phase failed: {e!r}"[:200])
        else:
            notes.append("envs phase skipped: deadline")

        # Phase 2 — full PPO training iteration, at BOTH hyperparameter
        # points: the reference-parity config (SB3 batch_size=64 — tiny
        # sequential minibatches, the reference's own structure) and the
        # TPU-tuned preset (the REAL utils/config.py PRESETS["tpu"] batch —
        # same data, same epochs). vs_baseline for both uses the
        # measured full-SB3-loop estimate, not env-stepping-only (see
        # REFERENCE_TRAIN_FORMATION_STEPS_PER_SEC).
        if os.environ.get("BENCH_SKIP_TRAIN") != "1":
            if time.time() < deadline - 30:
                try:
                    from marl_distributedformation_tpu.algo import PPOConfig
                    from marl_distributedformation_tpu.utils.config import (
                        PRESETS,
                    )

                    tuned_batch = PRESETS["tpu"]["batch_size"]
                    train_m = _env_int(
                        "BENCH_TRAIN_M", M if on_accel else 256
                    )
                    t_rate, t_iters, n_steps = _time_train_phase(
                        N, train_m, deadline
                    )
                    result["train_env_steps_per_sec"] = round(t_rate, 1)
                    result["train_iters_per_sec"] = round(t_iters, 2)
                    result["train_m"] = train_m
                    result["train_n_steps"] = n_steps
                    result["train_vs_baseline"] = round(
                        t_rate / REFERENCE_TRAIN_FORMATION_STEPS_PER_SEC, 2
                    )
                    result["train_baseline_denominator"] = (
                        "full SB3 loop estimate "
                        f"{REFERENCE_TRAIN_FORMATION_STEPS_PER_SEC} "
                        "formation-steps/s (docs/reference_train_estimate.md)"
                    )
                    print(
                        f"[bench] train: {t_rate:,.0f} formation-steps/s "
                        f"({t_iters:.2f} iters/s at M={train_m})",
                        file=sys.stderr,
                    )
                    tuned_rate, tuned_iters, _ = _time_train_phase(
                        N, train_m, deadline,
                        ppo=PPOConfig(batch_size=tuned_batch),
                    )
                    result["train_env_steps_per_sec_tuned"] = round(
                        tuned_rate, 1
                    )
                    result["train_iters_per_sec_tuned"] = round(
                        tuned_iters, 2
                    )
                    result["train_tuned_batch_size"] = tuned_batch
                    result["train_tuned_vs_baseline"] = round(
                        tuned_rate / REFERENCE_TRAIN_FORMATION_STEPS_PER_SEC,
                        2,
                    )
                    print(
                        f"[bench] train (preset=tpu, batch={tuned_batch}): "
                        f"{tuned_rate:,.0f} formation-steps/s "
                        f"({tuned_iters:.2f} iters/s)",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"train phase failed: {e!r}"[:200])
            else:
                notes.append("train phase skipped: deadline")

        def run_knn_phase(prefix: str, n: int, default_m: int, chunk: int):
            """Time one knn env-stepping variant; record rate + which
            neighbor-search impl auto-dispatch resolves at this shape.
            Failures degrade to a note, like every other phase."""
            try:
                key = prefix.replace("-", "_")
                m = _env_int(f"BENCH_{key.upper()}_M", default_m)
                params = EnvParams(num_agents=n, obs_mode="knn", knn_k=4)
                rate = _time_env_phase(params, m, chunk, deadline)

                import jax.numpy as jnp

                from marl_distributedformation_tpu.ops.knn import (
                    _resolve_auto_impl,
                )

                result[f"{key}_env_steps_per_sec"] = round(rate, 1)
                result[f"{key}_m"] = m
                result[f"{key}_n"] = n
                result[f"{key}_impl"] = _resolve_auto_impl(
                    jnp.zeros((m, n, 2))
                )
                print(
                    f"[bench] {prefix} (N={n}): {rate:,.0f} "
                    f"formation-steps/s ({result[f'{key}_impl']})",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"{prefix} phase failed: {e!r}"[:200])

        # Phase 3 — large-swarm knn variant (BASELINE.json config 4).
        # Provenance (VERDICT.md r2 weak #4 / r3 weak #5): the committed
        # hardware-parity status of the pallas/xla kernel pairs
        # (docs/acceptance/tpu_parity.txt, written by
        # tests/tpu_compiled_parity.py on the chip). These REPLAY a
        # committed artifact, not a same-run measurement — each line is
        # dated so a CPU-fallback JSON can't be misread as live TPU
        # parity, and each phase below attaches only the artifact legs
        # for the kernel it actually benchmarks (fused vs pallas_big).
        # Any recorded PARITY_FAIL leg wins over OK legs so a failure
        # can never be masked by line position.
        def parity_claim(legs, stamp, pick=0):
            failed = [s for s in legs if "PARITY_OK" not in s]
            return (stamp + (failed[0] if failed else legs[pick]))[:200]

        parity_file = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "docs", "acceptance", "tpu_parity.txt",
        )
        try:
            status, recorded = [], None
            with open(parity_file) as pf:
                for ln in pf:
                    if ln.startswith("# date:"):
                        recorded = ln.split(":", 1)[1].strip()
                    elif ln.startswith("PARITY"):
                        status.append(ln.strip())
            stamp = f"recorded {recorded or 'undated'}: "
            # The artifact's big-kernel leg is "pallas_big ..." on success
            # but "PARITY_FAIL(big): ..." on failure
            # (tests/tpu_compiled_parity.py:155-163) — match both so a
            # big-kernel failure routes to the knn-big phase, not fused.
            big_legs = [
                s for s in status
                if "pallas_big" in s or s.startswith("PARITY_FAIL(big)")
            ]
            fused_legs = [s for s in status if s not in big_legs]
        except OSError:
            stamp, fused_legs, big_legs = None, [], []

        if os.environ.get("BENCH_SKIP_KNN") != "1":
            if time.time() < deadline - 30:
                run_knn_phase(
                    "knn", 100, 4096 if on_accel else 256,
                    max(CHUNK // 8, 16),
                )
                result["knn_device_parity"] = (
                    parity_claim(fused_legs, stamp) if fused_legs
                    else "no committed artifact" if stamp is None
                    else "no fused-kernel leg in artifact"
                )
            else:
                notes.append("knn phase skipped: deadline")

        # Phase 4 — swarm past the fused kernel's VMEM cliff (N=1024):
        # the chunked-streaming kernel (ops/knn_pallas.py
        # knn_batch_pallas_big) on TPU, XLA elsewhere.
        if os.environ.get("BENCH_SKIP_KNN_BIG") != "1":
            if time.time() < deadline - 30:
                run_knn_phase(
                    "knn-big",
                    _env_int("BENCH_KNN_BIG_N", 1024),
                    512 if on_accel else 32,
                    max(CHUNK // 32, 8),
                )
                result["knn_big_device_parity"] = (
                    parity_claim(big_legs, stamp, pick=-1) if big_legs
                    else "no committed artifact" if stamp is None
                    else "no big-kernel leg in artifact"
                )
            else:
                notes.append("knn-big phase skipped: deadline")

        # Phase 5 — Anakin fused-scan training (TrainConfig.fused_chunk,
        # docs/training.md): the WHOLE rollout+update loop inside one
        # lax.scan program, K iterations per dispatch, per-iteration
        # metrics stacked on-device and drained once per chunk. Replaces
        # the retired iters_per_dispatch burst phase — at the tuned
        # config the burst never paid for itself (BENCH_r05:
        # iters_per_dispatch=2 measured 11,147 vs 11,476 plain on CPU;
        # see docs/training.md "Why the burst path lost"). Records the
        # best rate over the chunk ladder, the per-chunk rates, the
        # compile-once RetraceGuard receipts, and the dispatch overhead
        # the host loop pays relative to the fused program. Runs LAST
        # among train phases: its scan compiles are the most expensive
        # and must never starve the long-standing knn fields.
        if os.environ.get("BENCH_SKIP_TRAIN") != "1":
            try:
                chunks = [
                    int(c)
                    for c in os.environ.get(
                        "BENCH_FUSED_CHUNKS", "1,8,32"
                    ).split(",")
                    if c.strip() and int(c) > 0
                ]
            except ValueError as e:
                # A malformed knob degrades like any phase failure — the
                # JSON line (and every already-measured field) still
                # prints.
                notes.append(f"bad BENCH_FUSED_CHUNKS: {e!r}"[:200])
                chunks = []
            if chunks and time.time() < deadline - 30:
                try:
                    from marl_distributedformation_tpu.algo import PPOConfig
                    from marl_distributedformation_tpu.utils.config import (
                        PRESETS,
                    )

                    train_m = _env_int(
                        "BENCH_TRAIN_M", M if on_accel else 256
                    )
                    tuned_ppo = PPOConfig(
                        batch_size=PRESETS["tpu"]["batch_size"]
                    )
                    rates, receipts = {}, {}
                    for k_chunk in chunks:
                        if time.time() > deadline - 15:
                            notes.append(
                                f"fused-scan chunk {k_chunk} skipped: "
                                "deadline"
                            )
                            break
                        f_rate, f_iters, compiles = _time_fused_phase(
                            N, train_m, deadline, tuned_ppo, k_chunk
                        )
                        rates[k_chunk] = f_rate
                        receipts[str(k_chunk)] = compiles
                        print(
                            f"[bench] train (fused-scan, chunk={k_chunk}):"
                            f" {f_rate:,.0f} formation-steps/s "
                            f"({f_iters:.2f} iters/s, {compiles} "
                            "compile)",
                            file=sys.stderr,
                        )
                    if rates:
                        best = max(rates, key=rates.get)
                        result["train_env_steps_per_sec_fused_scan"] = (
                            round(rates[best], 1)
                        )
                        result["train_fused_scan_chunk"] = best
                        result["train_fused_scan_rates"] = {
                            str(kk): round(v, 1) for kk, v in rates.items()
                        }
                        # Compile-once receipt: every fused program must
                        # have compiled exactly once (tier-1 pins this;
                        # the bench records the evidence).
                        result["train_fused_scan_compiles"] = receipts
                        tuned_prev = result.get(
                            "train_env_steps_per_sec_tuned"
                        )
                        if tuned_prev:
                            # Share of the fused rate the host loop gives
                            # back to dispatch/drain overhead at the same
                            # totals (>= 0: the fused program IS the same
                            # math minus per-iteration host round trips).
                            result["dispatch_overhead_pct"] = round(
                                max(
                                    0.0,
                                    (1.0 - tuned_prev / rates[best])
                                    * 100.0,
                                ),
                                1,
                            )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"fused-scan phase failed: {e!r}"[:200])
            elif chunks:
                notes.append("fused-scan phase skipped: deadline")

        # Phase 5b — population-sweep training (train/sweep.py): K
        # independent PPO runs advanced by ONE program. The host-loop
        # sweep pays one dispatch+drain round trip per population
        # iteration; the fused-scan sweep (fused_chunk, round 6) pays it
        # once per chunk — this phase measures both at MATCHED K and
        # population size and records what the fusion buys
        # (sweep_dispatch_overhead_pct). Rates count formation-steps
        # across ALL members; compile receipts come from the sweep's
        # RetraceGuard (one compile per rung, ever).
        if os.environ.get("BENCH_SKIP_SWEEP") != "1":
            try:
                sweep_chunks = [
                    int(c)
                    for c in os.environ.get(
                        "BENCH_SWEEP_CHUNKS", "1,8"
                    ).split(",")
                    if c.strip() and int(c) > 0
                ]
            except ValueError as e:
                notes.append(f"bad BENCH_SWEEP_CHUNKS: {e!r}"[:200])
                sweep_chunks = []
            if sweep_chunks and time.time() < deadline - 30:
                try:
                    from marl_distributedformation_tpu.algo import PPOConfig
                    from marl_distributedformation_tpu.utils.config import (
                        PRESETS,
                    )

                    num_seeds = _env_int("BENCH_SWEEP_SEEDS", 4)
                    sweep_m = _env_int(
                        "BENCH_SWEEP_M", (M // 4) if on_accel else 16
                    )
                    repeats = _env_int("BENCH_SWEEP_REPEATS", 5)
                    tuned_ppo = PPOConfig(
                        batch_size=PRESETS["tpu"]["batch_size"]
                    )
                    # Build + compile every rung FIRST, then interleave
                    # `repeats` timing passes across all of them and keep
                    # each rung's best: back-to-back per-config passes
                    # would book host-load drift (heavy on this shared
                    # container) to whichever config ran in the bad
                    # window, which is the exact comparison
                    # sweep_dispatch_overhead_pct exists to make.
                    timers = {0: _make_sweep_timer(
                        N, sweep_m, num_seeds, tuned_ppo
                    )}
                    for k_chunk in sweep_chunks:
                        if time.time() > deadline - 20:
                            notes.append(
                                f"fused-sweep chunk {k_chunk} skipped: "
                                "deadline"
                            )
                            break
                        timers[k_chunk] = _make_sweep_timer(
                            N, sweep_m, num_seeds, tuned_ppo,
                            fused_chunk=k_chunk,
                        )
                    rates = {kk: 0.0 for kk in timers}
                    for _ in range(max(1, repeats)):
                        if time.time() > deadline - 10:
                            break
                        for kk, (run_timed, _t) in timers.items():
                            rate, _ips = run_timed(deadline)
                            rates[kk] = max(rates[kk], rate)
                    host_rate = rates.pop(0)
                    # Warmup/compile can eat the whole budget before any
                    # timed pass runs — degrade to a note instead of
                    # recording 0.0 rates (and dividing by one below).
                    rates = {kk: r for kk, r in rates.items() if r > 0}
                    if host_rate <= 0 or not rates:
                        raise RuntimeError(
                            "deadline expired before a timed pass ran"
                        )
                    receipts = {
                        str(kk): timers[kk][1].retrace_guard.count
                        for kk in rates
                    }
                    result["sweep_env_steps_per_sec_host_loop"] = round(
                        host_rate, 1
                    )
                    result["sweep_num_seeds"] = num_seeds
                    result["sweep_m"] = sweep_m
                    result["sweep_timing"] = (
                        f"best of {repeats} interleaved passes per rung"
                    )
                    print(
                        f"[bench] sweep (host loop, K={num_seeds}, "
                        f"M={sweep_m}): {host_rate:,.0f} "
                        "formation-steps/s "
                        f"({timers[0][1].retrace_guard.count} compile)",
                        file=sys.stderr,
                    )
                    for kk, rate in rates.items():
                        print(
                            f"[bench] sweep (fused-scan, chunk={kk}): "
                            f"{rate:,.0f} formation-steps/s "
                            f"({receipts[str(kk)]} compile)",
                            file=sys.stderr,
                        )
                    if rates:
                        best = max(rates, key=rates.get)
                        result["sweep_env_steps_per_sec_fused_scan"] = (
                            round(rates[best], 1)
                        )
                        result["sweep_fused_scan_chunk"] = best
                        result["sweep_fused_scan_rates"] = {
                            str(kk): round(v, 1) for kk, v in rates.items()
                        }
                        result["sweep_fused_scan_compiles"] = receipts
                        # Share of the fused-population rate the host
                        # loop gives back to per-iteration dispatch +
                        # drain at the same K and M (>= 0: same math,
                        # fewer host round trips).
                        result["sweep_dispatch_overhead_pct"] = round(
                            max(
                                0.0,
                                (1.0 - host_rate / rates[best]) * 100.0,
                            ),
                            1,
                        )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"sweep phase failed: {e!r}"[:200])
            elif sweep_chunks:
                notes.append("sweep phase skipped: deadline")
        # Phase 6 — serving fleet throughput: a 2-replica fleet
        # (serving/fleet/) under the mixed-size smoke storm. Runs in a
        # SUBPROCESS with a forced 2-device CPU backend — the
        # multi-device flag must land before backend init, which this
        # process's backend has long passed — and always on CPU: this
        # is a host-path (routing + coalescing + dispatch) number, the
        # layer the fleet adds; model FLOPs are noise at this size.
        # First serving-side perf number in the trajectory.
        if os.environ.get("BENCH_SKIP_SERVING") == "1":
            _mark_skipped(
                result,
                "serving",
                ("serving_requests_per_sec_fleet", "serving_fleet_p95_ms"),
            )
        else:
            if time.time() < deadline - 60:
                try:
                    serving_s = float(
                        os.environ.get("BENCH_SERVING_DURATION_S", 2.0)
                    )
                    cmd = [
                        sys.executable,
                        os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "serve_policy.py",
                        ),
                        "--init-policy", "MLPActorCritic",
                        "--obs-dim", "8",
                        "--fleet", "--replicas", "2",
                        "--smoke",
                        "--duration", str(serving_s),
                    ]
                    env = dict(os.environ)
                    env["JAX_PLATFORMS"] = "cpu"
                    env["XLA_FLAGS"] = (
                        env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                    ).strip()
                    out = subprocess.run(
                        cmd, capture_output=True, text=True,
                        timeout=max(deadline - time.time(), 60),
                        env=env,
                    )
                    if out.returncode != 0:
                        raise RuntimeError(
                            f"fleet smoke exited {out.returncode}: "
                            + out.stderr[-200:]
                        )
                    rep = json.loads(out.stdout.strip().splitlines()[-1])
                    result["serving_requests_per_sec_fleet"] = round(
                        rep["requests_per_sec_fleet"], 1
                    )
                    result["serving_fleet_p95_ms"] = round(
                        rep["latency_p95_ms"], 2
                    )
                    result["serving_fleet_replicas"] = 2
                    result["serving_fleet_max_compiles_per_rung"] = rep[
                        "max_compiles_per_rung"
                    ]
                    print(
                        "[bench] serving fleet (2 replicas, CPU): "
                        f"{rep['requests_per_sec_fleet']:,.0f} req/s, "
                        f"p95 {rep['latency_p95_ms']:.1f} ms",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"serving phase failed: {e!r}"[:200])
            else:
                notes.append("serving phase skipped: deadline")
        # Phase 7 — the always-learning pipeline (pipeline/,
        # docs/pipeline.md): trainer -> promotion gate -> fleet as ONE
        # loop, in a subprocess on a forced 2-device CPU (same rationale
        # as phase 6 — host-path control-plane numbers; the multi-device
        # flag must land before backend init). Records the train-step ->
        # served-model_step wall time (p50/p95 over the run's
        # promotions), the gate's eval throughput, and the compile-once
        # receipts: the gate's whole candidate series must cost ONE eval
        # compile, and serving must stay at <= 1 compile per rung.
        if os.environ.get("BENCH_SKIP_PIPELINE") == "1":
            _mark_skipped(
                result,
                "pipeline",
                (
                    "promotion_latency_s_p50",
                    "promotion_latency_s_p95",
                    "gate_eval_steps_per_sec",
                ),
            )
        else:
            if time.time() < deadline - 90:
                try:
                    pipeline_budget = min(
                        float(
                            os.environ.get("BENCH_PIPELINE_BUDGET_S", 240.0)
                        ),
                        max(deadline - time.time() - 10, 60),
                    )
                    cmd = [
                        sys.executable,
                        os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "always_learning.py",
                        ),
                        "name=bench_pipeline",
                        f"num_formation={_env_int('BENCH_PIPELINE_M', 16)}",
                        "total_timesteps=4800",
                        "max_steps=60",
                        "log_interval=100",
                        f"gate_formations="
                        f"{_env_int('BENCH_PIPELINE_GATE_M', 32)}",
                        "pipeline_replicas=2",
                        f"pipeline_budget_s={pipeline_budget}",
                    ]
                    env = dict(os.environ)
                    env["JAX_PLATFORMS"] = "cpu"
                    env["XLA_FLAGS"] = (
                        env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                    ).strip()
                    out = subprocess.run(
                        cmd, capture_output=True, text=True,
                        timeout=max(deadline - time.time(), 90),
                        env=env,
                    )
                    if out.returncode != 0:
                        raise RuntimeError(
                            f"pipeline run exited {out.returncode}: "
                            + out.stderr[-200:]
                        )
                    rep = json.loads(out.stdout.strip().splitlines()[-1])
                    p50 = rep.get("promotion_latency_s_p50")
                    p95 = rep.get("promotion_latency_s_p95")
                    if p50 is None or p95 is None:
                        raise RuntimeError(
                            "pipeline run produced no measured "
                            f"promotions: {rep}"
                        )
                    result["promotion_latency_s_p50"] = round(p50, 3)
                    result["promotion_latency_s_p95"] = round(p95, 3)
                    result["gate_eval_steps_per_sec"] = round(
                        rep["gate_eval_steps_per_sec"], 1
                    )
                    result["pipeline_promotions"] = int(rep["promotions"])
                    result["pipeline_rejections"] = int(rep["rejections"])
                    # Compile-once receipts: ONE gate eval program across
                    # every candidate, <= 1 serving compile per rung.
                    result["pipeline_gate_compiles"] = int(
                        rep["gate_eval_compiles"]
                    )
                    result["pipeline_serving_max_compiles_per_rung"] = int(
                        rep["serving_max_compiles_per_rung"]
                    )
                    # Phase 8's span decomposition (obs/): per-stage
                    # p50s over the run's traced promotions — where the
                    # promotion seconds actually go (stream poll vs gate
                    # eval vs publish vs barrier commit vs first serve).
                    breakdown = rep.get("promotion_span_breakdown")
                    if breakdown:
                        result["promotion_span_breakdown"] = {
                            str(k): round(float(v), 4)
                            for k, v in breakdown.items()
                        }
                    print(
                        "[bench] pipeline (train->gate->fleet, 2-replica "
                        f"CPU): {rep['promotions']} promotions, "
                        f"latency p50 {p50:.2f}s / p95 {p95:.2f}s, gate "
                        f"{rep['gate_eval_steps_per_sec']:,.0f} "
                        f"eval-steps/s ({rep['gate_eval_compiles']} "
                        "compile)",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"pipeline phase failed: {e!r}"[:200])
            else:
                notes.append("pipeline phase skipped: deadline")
        # Phase 8 — tracing overhead (obs/, docs/observability.md): the
        # phase-6 fleet smoke run twice back to back at equal duration,
        # obs tracing ON then OFF; tracing_overhead_pct is the relative
        # req/s cost of leaving the spine enabled on the serving hot
        # path (the ISSUE 8 bar is < 5% — one ring append per coalesced
        # batch, not per request, is why it holds). Same subprocess /
        # forced-2-device rationale as phase 6. The companion
        # promotion_span_breakdown field rides phase 7's pipeline rep.
        if os.environ.get("BENCH_SKIP_SERVING") == "1":
            _mark_skipped(result, "obs", ("tracing_overhead_pct",))
        else:
            if time.time() < deadline - 60:
                try:
                    obs_s = float(
                        os.environ.get("BENCH_OBS_DURATION_S", 2.0)
                    )
                    env = dict(os.environ)
                    env["JAX_PLATFORMS"] = "cpu"
                    env["XLA_FLAGS"] = (
                        env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                    ).strip()
                    # Best-of-N INTERLEAVED passes, the phase-5b
                    # rationale: back-to-back per-mode timing on a
                    # shared container books load drift to whichever
                    # mode hit the bad window; interleaving + best-of
                    # cancels it.
                    passes = _env_int("BENCH_OBS_PASSES", 2)
                    rates = {"on": 0.0, "off": 0.0}
                    for _ in range(max(1, passes)):
                        for mode in ("on", "off"):
                            cmd = [
                                sys.executable,
                                os.path.join(
                                    os.path.dirname(
                                        os.path.abspath(__file__)
                                    ),
                                    "scripts", "serve_policy.py",
                                ),
                                "--init-policy", "MLPActorCritic",
                                "--obs-dim", "8",
                                "--fleet", "--replicas", "2",
                                "--smoke",
                                "--duration", str(obs_s),
                                "--obs-trace", mode,
                            ]
                            out = subprocess.run(
                                cmd, capture_output=True, text=True,
                                timeout=max(deadline - time.time(), 60),
                                env=env,
                            )
                            if out.returncode != 0:
                                raise RuntimeError(
                                    f"obs-{mode} smoke exited "
                                    f"{out.returncode}: "
                                    + out.stderr[-200:]
                                )
                            rep = json.loads(
                                out.stdout.strip().splitlines()[-1]
                            )
                            rates[mode] = max(
                                rates[mode],
                                float(rep["requests_per_sec_fleet"]),
                            )
                    overhead = (
                        100.0 * (rates["off"] - rates["on"]) / rates["off"]
                    )
                    result["tracing_overhead_pct"] = round(overhead, 2)
                    result["tracing_smoke_req_s_on"] = round(rates["on"], 1)
                    result["tracing_smoke_req_s_off"] = round(
                        rates["off"], 1
                    )
                    print(
                        "[bench] tracing overhead (2-replica CPU smoke): "
                        f"{rates['on']:,.0f} req/s traced vs "
                        f"{rates['off']:,.0f} untraced "
                        f"({overhead:+.1f}%)",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"obs phase failed: {e!r}"[:200])
            else:
                notes.append("obs phase skipped: deadline")
        # Phase 9 — SLO-driven sharded serving (serving/sharded.py,
        # loadgen.py, docs/serving.md "Sharded rungs & the earned
        # ladder"): three fleets on a forced 2-device CPU driven by the
        # SAME open-loop trace — replicated baseline, + f32 sharded
        # big-rung slice, + bf16 slice — then a rate bisection for the
        # capacity headline: max sustained req/s holding the p95 target
        # with sharding AND bf16 on, budget-1 compile receipts per rung.
        # On CPU the sharded 512-rung p95 win is the serving-layer one
        # (dedicated slice = no queue contention with small requests);
        # the intra-dispatch compute split needs real multi-chip
        # hardware, and bf16 is recorded honestly (negative on CPU — a
        # chip-side number by construction).
        if os.environ.get("BENCH_SKIP_SERVING") == "1":
            _mark_skipped(
                result,
                "serving_slo",
                (
                    "serving_req_per_sec_at_p95_slo",
                    "serving_sharded_512_p95_ms",
                    "serving_replicated_512_p95_ms",
                    "serving_bf16_speedup_pct",
                ),
            )
        else:
            if time.time() < deadline - 90:
                try:
                    slo_s = float(
                        os.environ.get("BENCH_SLO_DURATION_S", 1.5)
                    )
                    slo_p95 = float(
                        os.environ.get("BENCH_SLO_P95_MS", 50.0)
                    )
                    cmd = [
                        sys.executable,
                        os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "serve_policy.py",
                        ),
                        "--init-policy", "MLPActorCritic",
                        "--obs-dim", "8",
                        "--slo-bench", "--replicas", "2",
                        "--duration", str(slo_s),
                        "--slo-p95-ms", str(slo_p95),
                    ]
                    env = dict(os.environ)
                    env["JAX_PLATFORMS"] = "cpu"
                    env["XLA_FLAGS"] = (
                        env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                    ).strip()
                    out = subprocess.run(
                        cmd, capture_output=True, text=True,
                        timeout=max(deadline - time.time(), 90),
                        env=env,
                    )
                    if out.returncode != 0:
                        raise RuntimeError(
                            f"slo bench exited {out.returncode}: "
                            + out.stderr[-200:]
                        )
                    rep = json.loads(out.stdout.strip().splitlines()[-1])
                    result["serving_req_per_sec_at_p95_slo"] = round(
                        rep["req_per_sec_at_p95_slo"], 1
                    )
                    result["serving_slo_p95_target_ms"] = slo_p95
                    result["serving_sharded_512_p95_ms"] = round(
                        rep["sharded_512_p95_ms"], 2
                    )
                    result["serving_replicated_512_p95_ms"] = round(
                        rep["replicated_512_p95_ms"], 2
                    )
                    result["serving_bf16_speedup_pct"] = round(
                        rep["bf16_speedup_pct"], 1
                    )
                    result["serving_slo_max_compiles_per_rung"] = int(
                        rep["max_compiles_per_rung"]
                    )
                    result["serving_batch_preempted_total"] = int(
                        rep["batch_preempted_total"]
                    )
                    result["serving_autotuned_ladder"] = rep["autotuned"]
                    print(
                        "[bench] serving SLO (2-device CPU, sharded+bf16"
                        f" on): {rep['req_per_sec_at_p95_slo']:,.0f} "
                        f"req/s at p95<={slo_p95:.0f}ms; 512-rung p95 "
                        f"{rep['sharded_512_p95_ms']:.1f}ms sharded vs "
                        f"{rep['replicated_512_p95_ms']:.1f}ms "
                        "replicated",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"serving slo phase failed: {e!r}"[:200])
            else:
                notes.append("serving slo phase skipped: deadline")
        # Phase 9b — elastic capacity (serving/elastic/, docs/serving.md
        # "Elastic capacity"): one shifting-mix day — interactive-heavy
        # first half, big-rung storm second half — against a STATIC
        # fleet whose split+ladder were autotuned on the first half and
        # frozen, and an ELASTIC fleet whose CapacityController replays
        # the live TraceRecorder window through the same DP and
        # re-splits at the fleet batch barrier (prewarm-then-commit).
        # Both measured on the storm half by the same rate bisection;
        # the barrier pause, prewarm compile attribution (census diff:
        # zero programs registered during the measured storm), and
        # budget-1 receipts ride along.
        if os.environ.get("BENCH_SKIP_SERVING") == "1":
            _mark_skipped(
                result,
                "elastic",
                (
                    "serving_req_per_sec_at_p95_slo_elastic",
                    "serving_req_per_sec_at_p95_slo_static",
                    "elastic_resplit_pause_ms",
                    "elastic_prewarm_compiles",
                ),
            )
        else:
            if time.time() < deadline - 90:
                try:
                    ela_s = float(
                        os.environ.get("BENCH_ELASTIC_DURATION_S", 2.0)
                    )
                    ela_p95 = float(
                        os.environ.get("BENCH_ELASTIC_P95_MS", 80.0)
                    )
                    cmd = [
                        sys.executable,
                        os.path.join(
                            os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "serve_policy.py",
                        ),
                        "--init-policy", "MLPActorCritic",
                        "--obs-dim", "8", "--hidden", "64,64",
                        "--elastic-bench", "--replicas", "2",
                        "--duration", str(ela_s),
                        "--load-rps", "120",
                        "--slo-p95-ms", str(ela_p95),
                        "--slo-iterations", "4",
                    ]
                    env = dict(os.environ)
                    env["JAX_PLATFORMS"] = "cpu"
                    env["XLA_FLAGS"] = (
                        env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                    ).strip()
                    out = subprocess.run(
                        cmd, capture_output=True, text=True,
                        timeout=max(deadline - time.time(), 90),
                        env=env,
                    )
                    if out.returncode != 0:
                        raise RuntimeError(
                            f"elastic bench exited {out.returncode}: "
                            + out.stderr[-200:]
                        )
                    rep = json.loads(out.stdout.strip().splitlines()[-1])
                    result["serving_req_per_sec_at_p95_slo_elastic"] = (
                        round(rep["req_per_sec_at_p95_slo_elastic"], 1)
                    )
                    result["serving_req_per_sec_at_p95_slo_static"] = (
                        round(rep["req_per_sec_at_p95_slo_static"], 1)
                    )
                    result["elastic_resplit_pause_ms"] = round(
                        rep["elastic_resplit_pause_ms"], 3
                    )
                    result["elastic_prewarm_compiles"] = int(
                        rep["elastic_prewarm_compiles"]
                    )
                    result["elastic_storm_new_programs"] = int(
                        rep["elastic_storm_new_programs"]
                    )
                    result["elastic_resplits_committed"] = int(
                        rep["elastic_resplits_committed"]
                    )
                    result["elastic_max_compiles_per_rung"] = int(
                        rep["max_compiles_per_rung"]
                    )
                    result["elastic_storm_p95_ms"] = round(
                        rep["elastic_storm_p95_ms"], 2
                    )
                    result["elastic_static_storm_p95_ms"] = round(
                        rep["static_storm_p95_ms"], 2
                    )
                    result["elastic_buckets"] = rep["elastic_buckets"]
                    print(
                        "[bench] elastic capacity (2-device CPU, storm "
                        "half): "
                        f"{rep['req_per_sec_at_p95_slo_elastic']:,.0f} "
                        "req/s elastic vs "
                        f"{rep['req_per_sec_at_p95_slo_static']:,.0f} "
                        f"static at p95<={ela_p95:.0f}ms; re-split "
                        f"pause {rep['elastic_resplit_pause_ms']:.2f}ms,"
                        f" {rep['elastic_prewarm_compiles']:.0f} prewarm"
                        " compiles (0 on the storm path)",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"elastic phase failed: {e!r}"[:200])
            else:
                notes.append("elastic phase skipped: deadline")
        # Phase 10 — adversarial robustness (scenarios/adversary.py,
        # docs/adversarial.md): the falsifier search throughput + its
        # budget-1 compile receipt, and the auto-curriculum payoff at
        # EQUAL training steps — two tiny policies from the same seed,
        # one trained clean throughout, one switched mid-run to the
        # from_falsifiers stage discovered by searching its own
        # half-trained params (the train -> search -> train loop the
        # gate automates). worst_case_return_gap_pct is the
        # curriculum-trained policy's relative improvement over the
        # clean-trained one at the clean policy's discovered worst
        # cases (positive = adversarial training helped); honest noise
        # caveat: at bench-sized budgets this is directional, and it is
        # recorded whatever its sign.
        if os.environ.get("BENCH_SKIP_ADVERSARIAL") == "1":
            _mark_skipped(
                result,
                "adversarial",
                (
                    "adversarial_candidates_per_sec",
                    "adversarial_search_compiles",
                    "worst_case_return_gap_pct",
                ),
            )
        else:
            if time.time() < deadline - 60:
                try:
                    from marl_distributedformation_tpu.algo import PPOConfig
                    from marl_distributedformation_tpu.scenarios import (
                        AdversaryConfig,
                        AdversarySearch,
                        ScenarioSchedule,
                        ScenarioStage,
                        from_falsifiers,
                    )
                    from marl_distributedformation_tpu.train import (
                        TrainConfig,
                        Trainer,
                    )

                    adv_env = EnvParams(num_agents=4, max_steps=60)
                    adv_m = _env_int("BENCH_ADV_M", 16)
                    adv_iters = _env_int("BENCH_ADV_ITERS", 24)
                    adv_ppo = PPOConfig(
                        n_steps=5, n_epochs=2, batch_size=64
                    )
                    per_iter = adv_ppo.n_steps * adv_m * adv_env.num_agents
                    clean_sched = ScenarioSchedule(stages=(ScenarioStage(
                        rollouts=1, scenarios=("clean",),
                        severity=0.0, severity_start=0.0,
                    ),))

                    def adv_trainer(name):
                        return Trainer(
                            adv_env,
                            ppo=adv_ppo,
                            config=TrainConfig(
                                num_formations=adv_m,
                                total_timesteps=adv_iters * per_iter,
                                checkpoint=False,
                                name=name,
                                log_dir=f"/tmp/bench_{name}",
                                seed=0,
                            ),
                            scenario_schedule=clean_sched,
                        )

                    clean_tr = adv_trainer("adv_clean")
                    curr_tr = adv_trainer("adv_curriculum")
                    # Same searcher (ONE compiled population program)
                    # serves the mid-run search, the final search, and
                    # the worst-case comparison cells.
                    search = AdversarySearch(
                        clean_tr.model,
                        adv_env,
                        AdversaryConfig(
                            scenarios=("wind", "sensor_noise",
                                       "actuator_noise"),
                            grid=4,
                            generations=3,
                            num_formations=_env_int("BENCH_ADV_EVAL_M", 16),
                            drop_tolerance=0.1,
                        ),
                    )
                    half = adv_iters // 2
                    for _ in range(half):
                        clean_tr.run_iteration()
                        curr_tr.run_iteration()
                    mid = search.search(
                        curr_tr.train_state.params, origin="half-trained"
                    )
                    if mid["falsifiers"]:
                        curr_tr.update_scenario_schedule(from_falsifiers(
                            mid["falsifiers"], rollouts=adv_iters - half,
                        ))
                    for _ in range(adv_iters - half):
                        clean_tr.run_iteration()
                        curr_tr.run_iteration()
                    # The recorded search: the CLEAN-trained policy's
                    # falsifiers (timed; candidates/sec headline).
                    final = search.search(
                        clean_tr.train_state.params, origin="clean-trained"
                    )
                    cells = [
                        (f["scenario"], f["severity"])
                        for f in final["falsifiers"]
                    ] or [
                        (name, search.config.max_severity)
                        for name in final["scenarios"]
                    ]
                    wc_clean = min(search.evaluate_cells(
                        clean_tr.train_state.params, cells,
                        origin="clean-trained",
                    ))
                    wc_curr = min(search.evaluate_cells(
                        curr_tr.train_state.params, cells,
                        origin="curriculum-trained",
                    ))
                    gap = (
                        100.0 * (wc_curr - wc_clean)
                        / max(abs(wc_clean), 1.0)
                    )
                    result["adversarial_candidates_per_sec"] = round(
                        search.candidates_per_sec(), 1
                    )
                    result["adversarial_search_compiles"] = (
                        search.compile_count
                    )
                    result["adversarial_search_generations"] = (
                        final["generations"]
                    )
                    result["adversarial_falsifiers"] = {
                        f["scenario"]: f["severity"]
                        for f in final["falsifiers"]
                    }
                    result["worst_case_return_gap_pct"] = round(gap, 2)
                    result["worst_case_return_clean_trained"] = round(
                        wc_clean, 2
                    )
                    result["worst_case_return_curriculum_trained"] = round(
                        wc_curr, 2
                    )
                    result["adversarial_train_timesteps"] = (
                        adv_iters * per_iter
                    )
                    print(
                        "[bench] adversarial (search + auto-curriculum, "
                        f"{adv_iters} iters each): "
                        f"{result['adversarial_candidates_per_sec']:,.0f} "
                        f"candidates/s ({search.compile_count} compile), "
                        f"worst-case return {wc_clean:,.0f} clean-trained "
                        f"vs {wc_curr:,.0f} curriculum-trained "
                        f"({gap:+.1f}%)",
                        file=sys.stderr,
                    )
                except Exception as e:  # noqa: BLE001 — degrade, don't die
                    notes.append(f"adversarial phase failed: {e!r}"[:200])
            else:
                notes.append("adversarial phase skipped: deadline")
        # Phase 11 — telemetry overhead (obs/metrics.py,
        # docs/observability.md): the phase-5 fused-scan training loop
        # re-timed as the REAL Anakin driver (dispatch chunk N+1, drain
        # chunk N through Trainer._drain_chunk — the seam where the
        # MetricsRegistry records) with telemetry enabled vs disabled,
        # interleaved best-of-N passes (the phase-8 rationale:
        # back-to-back per-mode timing on a shared container books load
        # drift to whichever mode hit the bad window). The ISSUE 11 bar
        # is <= 5%; a handful of dict ops per chunk is why it holds.
        # Beside it, sentinel_checks_per_sec: how fast the
        # RegressionSentinel compares a live registry snapshot against
        # the newest committed BENCH record (the control-plane poll
        # cost an always_learning run pays per supervision step).
        if os.environ.get("BENCH_SKIP_TRAIN") == "1":
            _mark_skipped(
                result,
                "telemetry",
                ("telemetry_overhead_pct", "sentinel_checks_per_sec"),
            )
        elif time.time() < deadline - 30:
            try:
                from marl_distributedformation_tpu.algo import PPOConfig
                from marl_distributedformation_tpu.obs import (
                    RegressionSentinel,
                    configure_metrics,
                    default_watches,
                )
                from marl_distributedformation_tpu.train import (
                    TrainConfig,
                    Trainer,
                )
                from marl_distributedformation_tpu.utils import MetricsLogger
                from marl_distributedformation_tpu.utils.config import (
                    PRESETS,
                )
                from marl_distributedformation_tpu.utils.profiling import (
                    Throughput,
                )

                t_chunk = _env_int("BENCH_TELEMETRY_CHUNK", 8)
                train_m = _env_int("BENCH_TRAIN_M", M if on_accel else 256)
                trainer = Trainer(
                    EnvParams(num_agents=N),
                    ppo=PPOConfig(batch_size=PRESETS["tpu"]["batch_size"]),
                    config=TrainConfig(
                        num_formations=train_m, checkpoint=False,
                        use_wandb=False, name="bench_telemetry",
                        log_dir="/tmp/bench_telemetry",
                        fused_chunk=t_chunk,
                    ),
                )
                for _ in range(2):  # warm twice (_time_fused_phase)
                    stacked = trainer.run_chunk()
                    float(stacked["loss"][-1])
                    if time.time() > deadline:
                        break
                logger = MetricsLogger(
                    "/tmp/bench_telemetry", run_name="bench_telemetry"
                )
                meter = Throughput()

                def timed_pass() -> float:
                    # The double-buffered Anakin loop (_train_fused
                    # minus checkpoints): drain goes through the REAL
                    # instrumented seam, so the on/off delta is exactly
                    # the registry's recording cost.
                    dispatches, iteration, pending = 0, 0, None
                    t0 = time.perf_counter()
                    while True:
                        steps_before = trainer.num_timesteps
                        stacked = trainer.run_chunk()
                        dispatches += 1
                        if pending is not None:
                            trainer._drain_chunk(logger, meter, *pending)
                        pending = (stacked, iteration, steps_before, None)
                        iteration += t_chunk
                        if (
                            time.perf_counter() - t0 >= MIN_TIMED_S / 2
                            or time.time() > deadline
                            or dispatches * t_chunk >= 128
                        ):
                            break
                    trainer._drain_chunk(logger, meter, *pending)
                    elapsed = time.perf_counter() - t0
                    n_steps = trainer.ppo.n_steps
                    return (
                        n_steps * train_m * dispatches * t_chunk / elapsed
                    )

                passes = _env_int("BENCH_TELEMETRY_PASSES", 2)
                rates = {"on": 0.0, "off": 0.0}
                expired = False
                for _ in range(max(1, passes)):
                    for mode in ("on", "off"):
                        configure_metrics(enabled=(mode == "on"))
                        rates[mode] = max(rates[mode], timed_pass())
                        if time.time() > deadline:
                            expired = True
                            break
                    if expired:  # exit the OUTER loop too — no more
                        break  # full training chunks past the deadline
                configure_metrics(enabled=True)
                logger.close()
                if rates["on"] > 0.0 and rates["off"] > 0.0:
                    overhead = (
                        100.0 * (rates["off"] - rates["on"]) / rates["off"]
                    )
                    result["telemetry_overhead_pct"] = round(overhead, 2)
                    result["telemetry_fused_rate_on"] = round(
                        rates["on"], 1
                    )
                    result["telemetry_fused_rate_off"] = round(
                        rates["off"], 1
                    )
                    print(
                        "[bench] telemetry (fused-scan loop, chunk="
                        f"{t_chunk}): {rates['on']:,.0f} "
                        f"formation-steps/s recorded vs "
                        f"{rates['off']:,.0f} unrecorded "
                        f"({overhead:+.1f}%)",
                        file=sys.stderr,
                    )
                else:
                    # The deadline ate one mode's passes: the comparison
                    # is unmeasurable, not zero — degrade to a note and
                    # keep whatever the sentinel timing below salvages.
                    notes.append(
                        "telemetry overhead unmeasured: deadline before "
                        "both modes ran"
                    )
                # Sentinel poll cost over the live registry (the trainer
                # gauges were just recorded above) vs the newest
                # committed record; trip_after at the untrippable cap so
                # the timing never pays a flight dump.
                sentinel = RegressionSentinel(
                    default_watches(), trip_after=10**9
                )
                checks = _env_int("BENCH_SENTINEL_CHECKS", 500)
                t0 = time.perf_counter()
                for _ in range(checks):
                    sentinel.check()
                result["sentinel_checks_per_sec"] = round(
                    checks / (time.perf_counter() - t0), 1
                )
                print(
                    "[bench] sentinel: "
                    f"{result['sentinel_checks_per_sec']:,.0f} checks/s "
                    f"vs {sentinel.record_source or 'no committed record'}",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"telemetry phase failed: {e!r}"[:200])
        else:
            notes.append("telemetry phase skipped: deadline")

        # --- Phase 12: chaos plane (chaos/, scripts/chaos_storm.py,
        # docs/chaos.md): one seeded fault campaign through trainer ->
        # gate -> fleet. Three headline fields: chaos_mttr_s (worst
        # kill -> first-served-recovery over the campaign's disruptive
        # faults), chaos_invariant_violations (step monotonicity,
        # no-request-lost, budget-1 receipts, audit-log + checkpoint-dir
        # consistency — MUST be 0), and fault_plane_overhead_pct (the
        # disabled plane's per-request cost, ~0: one attribute read per
        # injection point). The campaign replays bit-identically from
        # chaos_seed (scripts/chaos_storm.py --print-schedule).
        chaos_fields = (
            "chaos_mttr_s",
            "chaos_invariant_violations",
            "fault_plane_overhead_pct",
        )
        if os.environ.get("BENCH_SKIP_CHAOS") == "1":
            _mark_skipped(result, "chaos", chaos_fields)
        elif time.time() < deadline - 60:
            try:
                import tempfile

                sys.path.insert(
                    0,
                    os.path.join(os.path.dirname(__file__), "scripts"),
                )
                try:
                    from chaos_storm import run_campaign
                finally:
                    sys.path.pop(0)

                chaos_seed = _env_int("BENCH_CHAOS_SEED", 0)
                chaos_report = run_campaign(
                    seed=chaos_seed,
                    faults=_env_int("BENCH_CHAOS_FAULTS", 25),
                    workdir=tempfile.mkdtemp(prefix="bench_chaos_"),
                    budget_s=max(30.0, deadline - time.time() - 15.0),
                )
                result["chaos_seed"] = chaos_seed
                result["chaos_invariant_violations"] = chaos_report[
                    "chaos_invariant_violations"
                ]
                result["chaos_faults_fired"] = chaos_report[
                    "chaos_faults_fired"
                ]
                if "chaos_mttr_s" in chaos_report:
                    result["chaos_mttr_s"] = chaos_report["chaos_mttr_s"]
                result["fault_plane_overhead_pct"] = chaos_report[
                    "fault_plane_overhead_pct"
                ]
                result["chaos_pipeline_restarts"] = chaos_report[
                    "pipeline_restarts"
                ]
                print(
                    "[bench] chaos: "
                    f"{chaos_report['chaos_faults_fired']} faults fired, "
                    f"{chaos_report['chaos_invariant_violations']} "
                    "invariant violations, MTTR "
                    f"{chaos_report.get('chaos_mttr_s', 'n/a')}s, "
                    "disabled-plane overhead "
                    f"{chaos_report['fault_plane_overhead_pct']}%",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"chaos phase failed: {e!r}"[:200])
        else:
            notes.append("chaos phase skipped: deadline")

        # --- Phase 13: program ledger (obs/ledger.py,
        # docs/observability.md "Program ledger"): the phase-11 fused
        # training loop re-timed with the ledger enabled vs disabled,
        # interleaved best-of-N passes (the phase-8/11 rationale:
        # back-to-back per-mode timing on a shared container books
        # load drift to whichever mode hit the bad window). The bar is
        # < 5%: steady-state ledger cost is a perf_counter pair plus a
        # per-thread shard append per dispatch; registration happens
        # once per COMPILE. Beside it, the census headline fields off
        # the process-global ledger, which by this point has seen every
        # program this bench run compiled: ledger_program_count and
        # ledger_compile_seconds_total (attributed backend-compile
        # wall, the number the chip window commits and the census diff
        # gate re-checks).
        ledger_fields = (
            "ledger_overhead_pct",
            "ledger_program_count",
            "ledger_compile_seconds_total",
        )
        if os.environ.get("BENCH_SKIP_TRAIN") == "1":
            _mark_skipped(result, "ledger", ledger_fields)
        elif time.time() < deadline - 30:
            try:
                from marl_distributedformation_tpu.algo import PPOConfig
                from marl_distributedformation_tpu.obs import (
                    configure_ledger,
                    get_ledger,
                )
                from marl_distributedformation_tpu.train import (
                    TrainConfig,
                    Trainer,
                )
                from marl_distributedformation_tpu.utils import (
                    MetricsLogger,
                )
                from marl_distributedformation_tpu.utils.config import (
                    PRESETS,
                )
                from marl_distributedformation_tpu.utils.profiling import (
                    Throughput,
                )

                l_chunk = _env_int("BENCH_LEDGER_CHUNK", 8)
                train_m = _env_int("BENCH_TRAIN_M", M if on_accel else 256)
                configure_ledger(enabled=True)  # registration pass
                trainer = Trainer(
                    EnvParams(num_agents=N),
                    ppo=PPOConfig(
                        batch_size=PRESETS["tpu"]["batch_size"]
                    ),
                    config=TrainConfig(
                        num_formations=train_m, checkpoint=False,
                        use_wandb=False, name="bench_ledger",
                        log_dir="/tmp/bench_ledger",
                        fused_chunk=l_chunk,
                    ),
                )
                for _ in range(2):  # warm twice (_time_fused_phase)
                    stacked = trainer.run_chunk()
                    float(stacked["loss"][-1])
                    if time.time() > deadline:
                        break
                logger = MetricsLogger(
                    "/tmp/bench_ledger", run_name="bench_ledger"
                )
                meter = Throughput()

                def ledger_pass() -> float:
                    # The double-buffered Anakin loop, same shape as
                    # phase 11: dispatch N+1, drain N through the real
                    # instrumented seam. The on/off delta is exactly
                    # the ledger's dispatch-recording cost.
                    dispatches, iteration, pending = 0, 0, None
                    t0 = time.perf_counter()
                    while True:
                        steps_before = trainer.num_timesteps
                        stacked = trainer.run_chunk()
                        dispatches += 1
                        if pending is not None:
                            trainer._drain_chunk(logger, meter, *pending)
                        pending = (stacked, iteration, steps_before, None)
                        iteration += l_chunk
                        if (
                            time.perf_counter() - t0 >= MIN_TIMED_S / 2
                            or time.time() > deadline
                            or dispatches * l_chunk >= 128
                        ):
                            break
                    trainer._drain_chunk(logger, meter, *pending)
                    elapsed = time.perf_counter() - t0
                    n_steps = trainer.ppo.n_steps
                    return (
                        n_steps * train_m * dispatches * l_chunk / elapsed
                    )

                passes = _env_int("BENCH_LEDGER_PASSES", 2)
                rates = {"on": 0.0, "off": 0.0}
                expired = False
                for _ in range(max(1, passes)):
                    for mode in ("on", "off"):
                        configure_ledger(enabled=(mode == "on"))
                        rates[mode] = max(rates[mode], ledger_pass())
                        if time.time() > deadline:
                            expired = True
                            break
                    if expired:
                        break
                configure_ledger(enabled=True)
                logger.close()
                if rates["on"] > 0.0 and rates["off"] > 0.0:
                    overhead = (
                        100.0 * (rates["off"] - rates["on"]) / rates["off"]
                    )
                    result["ledger_overhead_pct"] = round(overhead, 2)
                    result["ledger_fused_rate_on"] = round(rates["on"], 1)
                    result["ledger_fused_rate_off"] = round(
                        rates["off"], 1
                    )
                else:
                    notes.append(
                        "ledger overhead unmeasured: deadline before "
                        "both modes ran"
                    )
                # Census headlines off the whole bench run's ledger.
                ledger = get_ledger()
                census = ledger.census()
                result["ledger_program_count"] = census["totals"][
                    "programs"
                ]
                result["ledger_compile_seconds_total"] = round(
                    census["totals"]["compile_seconds"], 3
                )
                result["ledger_compile_seconds_max"] = round(
                    ledger.compile_seconds_max(), 3
                )
                by_source = {}
                for prog in census["programs"]:
                    src = prog.get("analysis_source", "unavailable")
                    by_source[src] = by_source.get(src, 0) + 1
                result["ledger_analysis_sources"] = by_source
                wm = census["totals"].get("watermark_bytes")
                if wm is not None:
                    result["device_memory_watermark_bytes"] = wm
                print(
                    "[bench] ledger (fused-scan loop, chunk="
                    f"{l_chunk}): {rates['on']:,.0f} formation-steps/s "
                    f"recorded vs {rates['off']:,.0f} unrecorded "
                    f"({result.get('ledger_overhead_pct', 'n/a')}%); "
                    f"census {result['ledger_program_count']} programs, "
                    f"{result['ledger_compile_seconds_total']:.1f}s "
                    "compile",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"ledger phase failed: {e!r}"[:200])
        else:
            notes.append("ledger phase skipped: deadline")

        # --- Phase 14: the mesh tier (serving/mesh/, docs/mesh.md):
        # a loopback 2-host mesh — real host subprocesses behind the
        # MetaRouter — hammered by client threads while the
        # coordinator drives global barrier swaps and one host eats a
        # real SIGKILL mid-load. Headlines: mesh_req_per_sec,
        # mesh_global_swap_latency_s_p50/p95 (wall of the two-phase
        # prepare+commit across every host, under load),
        # mesh_failover_lost_requests (MUST be 0 — the
        # no-accepted-request-lost invariant across a host death), and
        # the per-host budget-1 receipts.
        mesh_fields = (
            "mesh_req_per_sec",
            "mesh_global_swap_latency_s_p50",
            "mesh_global_swap_latency_s_p95",
            "mesh_failover_lost_requests",
        )
        if os.environ.get("BENCH_SKIP_MESH") == "1":
            _mark_skipped(result, "mesh", mesh_fields)
        elif time.time() < deadline - 90:
            try:
                import tempfile

                from marl_distributedformation_tpu.serving.mesh.smoke import (  # noqa: E501
                    run_mesh_smoke,
                )

                smoke = run_mesh_smoke(
                    tempfile.mkdtemp(prefix="bench_mesh_"),
                    hosts=_env_int("BENCH_MESH_HOSTS", 2),
                    duration_s=float(
                        os.environ.get("BENCH_MESH_DURATION_S", "8")
                    ),
                    swaps=_env_int("BENCH_MESH_SWAPS", 3),
                    ready_timeout_s=max(
                        30.0, deadline - time.time() - 30.0
                    ),
                )
                result["mesh_hosts"] = smoke["mesh_hosts"]
                result["mesh_req_per_sec"] = smoke["mesh_req_per_sec"]
                for key in (
                    "mesh_global_swap_latency_s_p50",
                    "mesh_global_swap_latency_s_p95",
                ):
                    if smoke.get(key) is not None:
                        result[key] = smoke[key]
                result["mesh_failover_lost_requests"] = smoke[
                    "mesh_failover_lost_requests"
                ]
                result["mesh_step_violations"] = smoke[
                    "mesh_step_violations"
                ]
                result["mesh_global_swaps"] = smoke["mesh_global_swaps"]
                result["mesh_host_compile_receipts_max"] = smoke[
                    "mesh_host_compile_receipts_max"
                ]
                print(
                    "[bench] mesh (2-host loopback): "
                    f"{smoke['mesh_req_per_sec']:,.0f} req/s, "
                    f"{smoke['mesh_global_swaps']} global swaps "
                    f"(p50 {smoke.get('mesh_global_swap_latency_s_p50')}"
                    "s), host killed "
                    f"{smoke['mesh_host_killed']!r}, "
                    f"{smoke['mesh_failover_lost_requests']} lost, "
                    f"{smoke['mesh_step_violations']} step violations",
                    file=sys.stderr,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"mesh phase failed: {e!r}"[:200])
        else:
            notes.append("mesh phase skipped: deadline")

        # --- Phase 15: train-lane recovery (train/recovery.py,
        # docs/recovery.md). Three headline fields:
        # health_overhead_pct — the phase-11 interleaved fused loop
        # (dispatch N+1, drain N through the REAL Trainer._drain_chunk)
        # with the in-program health word + skip guard ON vs OFF (two
        # trainers, one compiled program each; best-of-N passes
        # alternate modes so container load drift books to neither);
        # recovery_mttr_s — a seeded NaN carry bomb through a live
        # fused run with the ladder armed, detection-at-drain ->
        # rollback wall from recovery.jsonl; train_divergence_events —
        # the ladder's sustained-breach count for that run (MUST be
        # >= 1: a bomb that never registers is a broken detector, not
        # a fast one).
        recovery_fields = (
            "health_overhead_pct",
            "recovery_mttr_s",
            "train_divergence_events",
        )
        if os.environ.get("BENCH_SKIP_TRAIN") == "1":
            _mark_skipped(result, "recovery", recovery_fields)
        elif time.time() < deadline - 30:
            try:
                from marl_distributedformation_tpu.algo import PPOConfig
                from marl_distributedformation_tpu.chaos import (
                    FaultSchedule,
                    FaultSpec,
                    get_fault_plane,
                )
                from marl_distributedformation_tpu.train import (
                    TrainConfig,
                    Trainer,
                    read_recovery_log,
                )
                from marl_distributedformation_tpu.utils import MetricsLogger
                from marl_distributedformation_tpu.utils.config import (
                    PRESETS,
                )
                from marl_distributedformation_tpu.utils.profiling import (
                    Throughput,
                )

                r_chunk = _env_int("BENCH_RECOVERY_CHUNK", 8)
                train_m = _env_int("BENCH_TRAIN_M", M if on_accel else 256)

                def make_recovery_trainer(name: str, health: bool):
                    return Trainer(
                        EnvParams(num_agents=N),
                        ppo=PPOConfig(
                            batch_size=PRESETS["tpu"]["batch_size"]
                        ),
                        config=TrainConfig(
                            num_formations=train_m, checkpoint=False,
                            use_wandb=False, name=name,
                            log_dir=f"/tmp/{name}",
                            fused_chunk=r_chunk, health=health,
                        ),
                    )

                trainers = {
                    "on": make_recovery_trainer("bench_health_on", True),
                    "off": make_recovery_trainer("bench_health_off", False),
                }
                logger = MetricsLogger(
                    "/tmp/bench_health_on", run_name="bench_health"
                )
                meter = Throughput()
                for tr in trainers.values():  # warm twice (phase 5/11)
                    for _ in range(2):
                        stacked = tr.run_chunk()
                        float(stacked["loss"][-1])
                        if time.time() > deadline:
                            break

                def timed_pass(tr) -> float:
                    dispatches, iteration, pend = 0, 0, None
                    t0 = time.perf_counter()
                    while True:
                        steps_before = tr.num_timesteps
                        stacked = tr.run_chunk()
                        dispatches += 1
                        if pend is not None:
                            tr._drain_chunk(logger, meter, *pend)
                        pend = (stacked, iteration, steps_before, None)
                        iteration += r_chunk
                        if (
                            time.perf_counter() - t0 >= MIN_TIMED_S / 2
                            or time.time() > deadline
                            or dispatches * r_chunk >= 128
                        ):
                            break
                    tr._drain_chunk(logger, meter, *pend)
                    elapsed = time.perf_counter() - t0
                    n_steps = tr.ppo.n_steps
                    return (
                        n_steps * train_m * dispatches * r_chunk / elapsed
                    )

                passes = _env_int("BENCH_RECOVERY_PASSES", 2)
                rates = {"on": 0.0, "off": 0.0}
                expired = False
                for _ in range(max(1, passes)):
                    for mode in ("on", "off"):
                        rates[mode] = max(
                            rates[mode], timed_pass(trainers[mode])
                        )
                        if time.time() > deadline:
                            expired = True
                            break
                    if expired:
                        break
                logger.close()
                if rates["on"] > 0.0 and rates["off"] > 0.0:
                    overhead = (
                        100.0 * (rates["off"] - rates["on"]) / rates["off"]
                    )
                    result["health_overhead_pct"] = round(overhead, 2)
                    result["health_fused_rate_on"] = round(rates["on"], 1)
                    result["health_fused_rate_off"] = round(
                        rates["off"], 1
                    )
                    print(
                        "[bench] health word (fused-scan loop, chunk="
                        f"{r_chunk}): {rates['on']:,.0f} "
                        f"formation-steps/s guarded vs {rates['off']:,.0f}"
                        f" unguarded ({overhead:+.1f}%)",
                        file=sys.stderr,
                    )
                else:
                    notes.append(
                        "health overhead unmeasured: deadline before "
                        "both modes ran"
                    )
                # The recovery drill: one seeded NaN carry bomb through
                # a SMALL fused run with the full ladder + retention
                # ring armed; MTTR is the detection->restored wall the
                # ladder logged. Small shapes — the restore cost under
                # measurement is checkpoint IO + re-placement, not
                # model math.
                if time.time() < deadline - 20:
                    import tempfile
                    from pathlib import Path

                    drill_dir = tempfile.mkdtemp(prefix="bench_recovery_")
                    drill_m, drill_chunk = 8, 2
                    per_iter = 5 * drill_m * N
                    drill = Trainer(
                        EnvParams(num_agents=N),
                        ppo=PPOConfig(
                            n_steps=5, n_epochs=2, batch_size=64
                        ),
                        config=TrainConfig(
                            num_formations=drill_m,
                            total_timesteps=16 * per_iter,
                            save_freq=5, fused_chunk=drill_chunk,
                            name="bench_recovery", log_dir=drill_dir,
                            seed=_env_int("BENCH_CHAOS_SEED", 0),
                            health=True, recovery=True,
                            recovery_breach_iters=2, keep_last_n=4,
                        ),
                    )
                    plane = get_fault_plane()
                    was_enabled = plane.enabled
                    # Fresh counters: phase 12's campaign already drove
                    # a Trainer with the plane ENABLED, so the
                    # train-lane hit counters are far past at_hit=4 —
                    # without a reset the bomb would never fire and the
                    # drill would record a broken detector.
                    plane.reset()
                    plane.arm(FaultSchedule([
                        FaultSpec("train.carry_poison", "raise", at_hit=4)
                    ]))
                    plane.enabled = True
                    try:
                        drill.train()
                    finally:
                        plane.enabled = was_enabled
                        plane.disarm()
                    mttr = [
                        float(e["mttr_s"])
                        for e in read_recovery_log(
                            Path(drill_dir) / "recovery.jsonl"
                        )
                        if e["event"] == "rollback"
                    ]
                    ladder = drill.recovery_ladder
                    if mttr:
                        result["recovery_mttr_s"] = round(max(mttr), 4)
                    result["train_divergence_events"] = (
                        ladder.breaches if ladder is not None else 0
                    )
                    print(
                        "[bench] recovery drill: "
                        f"{ladder.recoveries} rollback(s), MTTR "
                        f"{result.get('recovery_mttr_s', 'n/a')}s, "
                        f"{ladder.skipped_total} skipped update(s), "
                        f"halted={drill.halted}",
                        file=sys.stderr,
                    )
                else:
                    notes.append("recovery drill skipped: deadline")
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"recovery phase failed: {e!r}"[:200])
        else:
            notes.append("recovery phase skipped: deadline")

        # --- Phase 16: graftlint wall (scripts/graftlint.py,
        # analysis/callgraph.py, docs/static_analysis.md). One full
        # --check pass over the package in a fresh subprocess — the
        # exact CI invocation, so the wall includes the cold-process
        # whole-repo call-graph rebuild (the worst case a pre-commit
        # hook pays). check_bench_record.py holds the field under a
        # ceiling: the lock-ordering / guarded-write analyses are
        # package-global DFS walks and must not go super-linear as the
        # repo grows. A non-zero lint exit is a note, not a crash —
        # the bench record must still emit on a dirty tree.
        if os.environ.get("BENCH_SKIP_LINT") == "1":
            _mark_skipped(result, "lint", ("graftlint_wall_s",))
        elif time.time() < deadline - 10:
            import pathlib

            lint_cmd = [
                sys.executable,
                str(
                    pathlib.Path(__file__).resolve().parent
                    / "scripts"
                    / "graftlint.py"
                ),
                "--check",
            ]
            lint_timeout = _env_int("BENCH_LINT_TIMEOUT_S", 300)
            t0 = time.perf_counter()
            try:
                lint = subprocess.run(
                    lint_cmd, capture_output=True, text=True,
                    timeout=lint_timeout,
                )
            except subprocess.TimeoutExpired:
                notes.append(
                    f"graftlint timed out after {lint_timeout}s"
                )
            else:
                result["graftlint_wall_s"] = round(
                    time.perf_counter() - t0, 3
                )
                if lint.returncode != 0:
                    notes.append("graftlint --check found errors")
                print(
                    "[bench] graftlint --check: "
                    f"{result['graftlint_wall_s']}s wall "
                    f"(exit {lint.returncode})",
                    file=sys.stderr,
                )
        else:
            notes.append("lint phase skipped: deadline")

        # --- Phase 17: the sebulba lane (train/sebulba/,
        # docs/sebulba.md). One pipelined actor/learner run at bench
        # scale: the actor thread streams rollouts through the bounded
        # TransferQueue while the learner drains K per fused chunk —
        # headlines sebulba_env_steps_per_sec (actor-side env
        # interaction wall rate), sebulba_learner_steps_per_sec
        # (batches consumed into updates per second), the queue /
        # staleness p95s, and the per-slice budget-1 compile receipts.
        # While the learner is SATURATED, the promotion gate — pinned
        # to its own slice via assign_gate_device — evaluates live
        # checkpoints: gate_eval_p50_under_load_s is the steady-state
        # (post-compile) eval wall beside a busy learner, the number
        # the gate's latency budget is written against.
        sebulba_fields = (
            "sebulba_env_steps_per_sec",
            "sebulba_learner_steps_per_sec",
            "transfer_queue_occupancy_p95",
            "param_staleness_p95_updates",
            "sebulba_actor_compiles",
            "sebulba_learner_compiles",
            "gate_eval_p50_under_load_s",
        )
        if os.environ.get("BENCH_SKIP_SEBULBA") == "1":
            _mark_skipped(result, "sebulba", sebulba_fields)
        elif time.time() < deadline - 30:
            try:
                import tempfile
                import threading as _threading

                from marl_distributedformation_tpu.algo import PPOConfig
                from marl_distributedformation_tpu.pipeline import (
                    GateConfig,
                    PromotionGate,
                )
                from marl_distributedformation_tpu.train import (
                    SebulbaDriver,
                    TrainConfig,
                    assign_gate_device,
                )
                from marl_distributedformation_tpu.utils.checkpoint import (
                    latest_checkpoint,
                )

                seb_m = _env_int("BENCH_SEBULBA_M", 64)
                seb_iters = _env_int("BENCH_SEBULBA_ITERS", 24)
                seb_chunk = _env_int("BENCH_SEBULBA_CHUNK", 2)
                seb_dir = tempfile.mkdtemp(prefix="bench_sebulba_")
                seb_env = EnvParams(num_agents=N)
                per_iter = 5 * seb_m * N
                driver = SebulbaDriver(
                    seb_env,
                    ppo=PPOConfig(n_steps=5, n_epochs=2, batch_size=64),
                    config=TrainConfig(
                        num_formations=seb_m,
                        total_timesteps=seb_iters * per_iter,
                        save_freq=4,
                        fused_chunk=seb_chunk,
                        name="bench_sebulba",
                        log_dir=seb_dir,
                        seed=0,
                        architecture="sebulba",
                    ),
                )
                t0 = time.perf_counter()
                train_box: list = []
                train_thread = _threading.Thread(
                    target=lambda: train_box.append(driver.train()),
                    name="bench-sebulba-train",
                    daemon=True,
                )
                train_thread.start()
                # Gate-beside-learner leg: wait for the run's first
                # checkpoint, then evaluate it from THIS thread on the
                # gate's own slice while the learner chews. One warm
                # eval absorbs the matrix compile (the gate's budget-1
                # bootstrap, not its steady state); the timed evals are
                # the under-load latency the budget is written against.
                gate_device = assign_gate_device(1)
                gate = PromotionGate(
                    seb_env,
                    GateConfig(
                        scenarios=("wind",),
                        severities=(1.0,),
                        eval_formations=8,
                        clean_tolerance=10.0,
                        rung_tolerance=10.0,
                    ),
                    device=gate_device,
                )
                candidate = None
                gate_deadline = min(deadline, time.time() + 120)
                while time.time() < gate_deadline and candidate is None:
                    candidate = latest_checkpoint(seb_dir)
                    if candidate is None:
                        time.sleep(0.2)
                gate_walls = []
                if candidate is not None:
                    gate.evaluate(candidate)  # warm: compile + baseline
                    for _ in range(5):
                        if (
                            time.time() > deadline
                            or not train_thread.is_alive()
                        ):
                            break
                        fresh = latest_checkpoint(seb_dir) or candidate
                        g0 = time.perf_counter()
                        gate.evaluate(fresh)
                        gate_walls.append(time.perf_counter() - g0)
                    if not gate_walls and time.time() < deadline:
                        # The run outran the gate's warm compile (short
                        # bench budgets) — still record the steady-state
                        # eval wall, honestly annotated: the learner was
                        # idle for these.
                        notes.append(
                            "sebulba gate evals ran after the learner "
                            "finished (run shorter than the gate's "
                            "warm compile)"
                        )
                        for _ in range(3):
                            fresh = latest_checkpoint(seb_dir) or candidate
                            g0 = time.perf_counter()
                            gate.evaluate(fresh)
                            gate_walls.append(time.perf_counter() - g0)
                else:
                    notes.append(
                        "sebulba gate leg skipped: no checkpoint "
                        "appeared before the gate deadline"
                    )
                train_thread.join(
                    timeout=max(10.0, deadline - time.time() + 60)
                )
                wall = time.perf_counter() - t0
                if train_thread.is_alive() or not train_box:
                    notes.append(
                        "sebulba phase failed: pipelined run did not "
                        "finish inside the bench deadline"
                    )
                else:
                    queue = driver.transfer_queue
                    result["sebulba_env_steps_per_sec"] = round(
                        driver.num_timesteps / wall, 1
                    )
                    result["sebulba_learner_steps_per_sec"] = round(
                        len(queue.consumed_seqs) / wall, 2
                    )
                    result["transfer_queue_occupancy_p95"] = round(
                        driver.occupancy_p95(), 2
                    )
                    result["param_staleness_p95_updates"] = round(
                        driver.staleness_p95(), 2
                    )
                    result["sebulba_actor_compiles"] = int(
                        driver.actor_guard.count
                    )
                    result["sebulba_learner_compiles"] = int(
                        driver.learner_guard.count
                    )
                    result["sebulba_stale_dropped"] = int(
                        driver.stale_dropped
                    )
                    result["sebulba_gate_device"] = str(gate_device)
                    if gate_walls:
                        result["gate_eval_p50_under_load_s"] = round(
                            sorted(gate_walls)[len(gate_walls) // 2], 4
                        )
                        result["sebulba_gate_compiles"] = int(
                            gate.program.compile_count
                            if gate.program is not None
                            else 0
                        )
                    print(
                        "[bench] sebulba (pipelined, chunk="
                        f"{seb_chunk}): "
                        f"{result['sebulba_env_steps_per_sec']:,.0f} "
                        "env-steps/s acted, "
                        f"{result['sebulba_learner_steps_per_sec']:.1f} "
                        "batches/s learned, occupancy p95 "
                        f"{result['transfer_queue_occupancy_p95']}, "
                        "staleness p95 "
                        f"{result['param_staleness_p95_updates']}, gate "
                        f"p50 {result.get('gate_eval_p50_under_load_s')}"
                        f"s on {gate_device}",
                        file=sys.stderr,
                    )
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                notes.append(f"sebulba phase failed: {e!r}"[:200])
        else:
            notes.append("sebulba phase skipped: deadline")
    except Exception as e:  # noqa: BLE001 — the JSON line must still print
        result["error"] = repr(e)[:300]
    if notes:
        result["notes"] = "; ".join(notes)
    emit()


if __name__ == "__main__":
    main()
