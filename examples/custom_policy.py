#!/usr/bin/env python
"""Plug a user-defined policy network into the trainer.

The trainer shells (`Trainer`, `SweepTrainer`, `HeteroTrainer`) accept any
flax module through ``model=`` as long as it satisfies the actor-critic
contract the built-ins follow (models/mlp.py):

- ``__call__(obs) -> (action_mean, log_std, value)`` where ``obs`` carries
  any leading batch axes, ``action_mean`` has trailing dim ``act_dim``,
  ``log_std`` is the Gaussian's state-independent log-scale, and ``value``
  drops the trailing dim;
- an optional class attribute ``per_formation`` (default False): False
  means the model is applied per agent row (the reference's
  parameter-sharing trick, vectorized_env.py:32); True means it sees whole
  ``(M, N, obs_dim)`` formations (like the CTDE critic).

This example defines a residual LayerNorm actor-critic — an architecture
the built-in zoo does not ship — trains it briefly on CPU, and compares it
against the scripted baseline controller on held-out formations.

Run from the repo root (~2 minutes on one CPU core):

    python examples/custom_policy.py
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Tuple

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
from flax import linen as nn

Array = jax.Array


class ResidualActorCritic(nn.Module):
    """Pre-LayerNorm residual MLP actor-critic (per-agent, shared params)."""

    act_dim: int = 2
    width: int = 64
    blocks: int = 2
    log_std_init: float = 0.0

    @nn.compact
    def __call__(self, obs: Array) -> Tuple[Array, Array, Array]:
        def trunk(x: Array, tag: str) -> Array:
            x = nn.Dense(self.width, name=f"{tag}_in")(x)
            for i in range(self.blocks):
                h = nn.LayerNorm(name=f"{tag}_ln{i}")(x)
                h = nn.tanh(nn.Dense(self.width, name=f"{tag}_fc{i}")(h))
                x = x + h  # residual: keeps gradients healthy when deep
            return x

        mean = nn.Dense(
            self.act_dim,
            kernel_init=nn.initializers.orthogonal(0.01),
            name="pi_head",
        )(trunk(obs, "pi"))
        value = nn.Dense(
            1, kernel_init=nn.initializers.orthogonal(1.0), name="vf_head"
        )(trunk(obs, "vf"))
        log_std = self.param(
            "log_std",
            nn.initializers.constant(self.log_std_init),
            (self.act_dim,),
        )
        return mean, log_std, value[..., 0]


def main() -> None:
    from marl_distributedformation_tpu.algo import PPOConfig
    from marl_distributedformation_tpu.env import EnvParams
    from marl_distributedformation_tpu.eval import (
        baseline_act_fn,
        evaluate,
        policy_act_fn,
    )
    from marl_distributedformation_tpu.train import TrainConfig, Trainer
    from marl_distributedformation_tpu.utils import setup_platform

    setup_platform("cpu")  # the example targets a laptop; drop for TPU

    env = EnvParams(num_agents=5)
    model = ResidualActorCritic(act_dim=env.act_dim)
    trainer = Trainer(
        env,
        # 1600 divides the rollout (64 formations x 5 agents x 10 steps =
        # 3200 transitions) so every collected transition trains.
        ppo=PPOConfig(batch_size=1600),
        config=TrainConfig(
            num_formations=64,
            # EXAMPLE_TOTAL_TIMESTEPS / EXAMPLE_LOG_DIR let the test suite
            # smoke this script end-to-end at a tiny budget in a tmp dir.
            total_timesteps=int(
                os.environ.get("EXAMPLE_TOTAL_TIMESTEPS", 320_000)
            ),
            name="example_custom_policy",
            log_dir=os.environ.get(
                "EXAMPLE_LOG_DIR", "logs/example_custom_policy"
            ),
            use_wandb=False,
            # A demo's only output is the printed comparison — don't pay
            # a checkpoint serialization every iteration.
            checkpoint=False,
        ),
        model=model,
    )
    last = trainer.train()
    print(f"final training reward: {last['reward']:.2f}")

    act = policy_act_fn(model, trainer.train_state.params, env)
    ours = evaluate(act, env, num_formations=256)
    base = evaluate(baseline_act_fn(env), env, num_formations=256)
    print(
        f"episode return/agent: custom policy "
        f"{ours['episode_return_per_agent']:.1f} vs scripted baseline "
        f"{base['episode_return_per_agent']:.1f}"
    )


if __name__ == "__main__":
    main()
