#!/usr/bin/env python
"""Drive the pure-functional environment in your own loop.

Everything in this framework builds on one pattern: env state is a pytree,
stepping is a pure function, and batching is `vmap` — so M formations step
in ONE compiled XLA program (the reference iterates M Python objects
sequentially, vectorized_env.py:71-81). If you want a custom training
loop, a different RL algorithm, or to embed the env in another system,
this is the whole API surface you need:

    reset_fn(key)            -> (state, obs)      # M formations at once
    step_fn(state, actions)  -> (state, transition)

Actions are policy-space ([-1, 1], scaled by max_speed inside — the L1
adapter semantics); `transition` carries obs/reward/done/metrics, with
auto-reset already applied (SB3 VecEnv convention: the obs returned on a
done row is the NEXT episode's first observation).

Run from the repo root (~20 seconds on CPU):

    python examples/functional_env.py
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp


def main() -> None:
    import marl_distributedformation_tpu as mdf
    from marl_distributedformation_tpu.env import control
    from marl_distributedformation_tpu.utils import setup_platform

    setup_platform("cpu")  # the example targets a laptop; drop for TPU

    params = mdf.EnvParams(num_agents=10)
    M = 256
    reset_fn, step_fn = mdf.make_vec_env(params, num_formations=M)
    state, obs = reset_fn(jax.random.PRNGKey(0))

    # Any controller works here: a policy network, a scripted rule, your
    # own code. The baseline potential-field controller is a pure jittable
    # function, so the whole control+step composition compiles to one
    # XLA program.
    vctrl = jax.jit(
        jax.vmap(control, in_axes=(0, 0, 0, None)), static_argnums=3
    )

    # Warm up: the first call compiles (the repo bench convention,
    # bench.py); time steady-state execution only.
    vel = vctrl(state.agents, state.goal, state.obstacles, params)
    warm_state, _ = step_fn(state, vel / params.max_speed)
    jax.block_until_ready(warm_state.agents)

    t0 = time.perf_counter()
    for t in range(300):
        vel = vctrl(state.agents, state.goal, state.obstacles, params)
        # step_fn takes policy-space actions; the scripted controller
        # emits raw velocities (the L0 contract, SURVEY.md Q8) — divide
        # by max_speed to cross between the two conventions.
        state, tr = step_fn(state, vel / params.max_speed)
        if (t + 1) % 100 == 0:
            d = float(tr.metrics["avg_dist_to_goal"].mean())
            s = float(tr.metrics["ave_dist_to_neighbor"].mean())
            print(
                f"t={t+1:3d}  avg_dist_to_goal={d:7.2f}  "
                f"ave_dist_to_neighbor={s:6.2f}"
            )
    jax.block_until_ready(state.agents)
    dt = time.perf_counter() - t0
    print(
        f"{300 * M / dt:,.0f} formation-steps/s "
        f"({M} formations x 10 agents, scripted control, one CPU)"
    )
    final = float(tr.metrics["avg_dist_to_goal"].mean())
    assert final < 100, f"formation failed to converge: {final}"
    print("converged: the ring formed around the goal")


if __name__ == "__main__":
    main()
