#!/usr/bin/env python
"""Compatibility shim: the reference's training invocation, verbatim.

The reference trains with ``python vectorized_env.py name=x``
(reference README.md:18, vectorized_env.py:112-137). This repo's
training entry point is ``train.py`` (same ``key=value`` CLI contract);
this forwarder makes the reference's muscle-memory command work
unchanged on the TPU-native backend.

The reference module also *defines* ``FormationEnv(cfg)``
(vectorized_env.py:16-109); importers get a same-signature construction
over the host-side VecEnv adapter
(marl_distributedformation_tpu/compat/vec_env.py).
"""

from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv
from marl_distributedformation_tpu.utils import env_params_from_config


def __getattr__(name):
    # Lazy so `import vectorized_env` for FormationEnv doesn't pull the
    # whole training stack; `vectorized_env.main` still IS train.main.
    if name == "main":
        from train import main

        return main
    raise AttributeError(name)


class FormationEnv(FormationVecEnv):
    """Reference-signature constructor: takes the loaded config object
    (reference vectorized_env.py:17 ``FormationEnv(cfg)``) instead of
    explicit ``EnvParams``."""

    def __init__(self, cfg):
        super().__init__(
            env_params_from_config(cfg),
            num_formations=cfg.num_formation,
            seed=int(cfg.get("seed", 0)),
        )


if __name__ == "__main__":
    from train import main

    main()
