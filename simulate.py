#!/usr/bin/env python
"""Baseline potential-field controller demo — the reference's
``python simulate.py`` workflow (simulate.py:321-329): N=10 agents driven by
the scripted formation controller for 1000 frames with live rendering.

Extras over the reference: ``key=value`` overrides (``num_agents=6``,
``steps=200``), ``headless=true`` to run without a display and print
metrics (useful over SSH; the reference hard-requires a GUI),
``platform=cpu`` to keep the demo off the TPU, and a *working* obstacle
demo — ``python simulate.py num_obstacles=4 obstacle_mode=fixed`` exercises
the controller's obstacle repulsion against the consistent box geometry and
the renderer's red-on-collision feedback (the reference ships obstacle code
but guards it off with ``assert num_obstacles == 0``, SURVEY.md Q2).
"""

from __future__ import annotations

import sys

import numpy as np


def main(argv=None) -> None:
    from marl_distributedformation_tpu.utils import Config, apply_overrides

    cfg = Config(
        num_agents=10,
        steps=1000,
        headless=False,
        seed=0,
        platform=None,
        num_obstacles=0,
        obstacle_mode="fixed",
    )
    apply_overrides(cfg, sys.argv[1:] if argv is None else argv)
    num_agents = int(cfg.num_agents)
    steps = int(cfg.steps)
    headless = bool(cfg.headless)
    seed = int(cfg.seed)

    from marl_distributedformation_tpu.utils import setup_platform

    setup_platform(cfg.platform)

    import jax

    from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv
    from marl_distributedformation_tpu.env import EnvParams, control

    params = EnvParams(
        num_agents=num_agents,
        num_obstacles=int(cfg.num_obstacles),
        obstacle_mode=str(cfg.obstacle_mode),
    )
    env = FormationVecEnv(params, num_formations=1, seed=seed)
    env.reset()
    vctrl = jax.jit(
        lambda agents, goal, obstacles: control(agents, goal, obstacles, params)
    )

    def controller_step():
        state = env.state
        vel = np.asarray(
            vctrl(state.agents[0], state.goal[0], state.obstacles[0])
        )
        _, rewards, _, _ = env.step_velocities(vel[None])
        return rewards

    if headless:
        from marl_distributedformation_tpu.compat.render import obstacle_hits

        for t in range(steps):
            rewards = controller_step()
            if t % 100 == 0 or t == steps - 1:
                m = env.last_metrics
                if params.num_obstacles > 0:
                    # Sampled at print time only — a per-step host pull of
                    # agents/obstacles would make the demo RTT-bound on a
                    # tunneled device.
                    hits = int(
                        obstacle_hits(
                            env.agents_np(), env.obstacles_np(), params
                        ).sum()
                    )
                    extra = f" obstacle_hits={hits}"
                else:
                    extra = ""
                print(
                    f"step {t:4d} reward={rewards.mean():8.3f} "
                    f"avg_dist_to_goal={m['avg_dist_to_goal']:7.2f} "
                    f"std_neighbor={m['std_dist_to_neighbor']:6.2f}"
                    + extra
                )
        return

    import matplotlib.animation as animation
    import matplotlib.pyplot as plt

    from marl_distributedformation_tpu.compat.render import FormationRenderer

    renderer = FormationRenderer(params, title="baseline controller")

    def frame(i):
        controller_step()
        renderer.update(env.agents_np(), env.goal_np(), env.obstacles_np())

    ani = animation.FuncAnimation(  # noqa: F841 (kept alive for the show loop)
        renderer.fig, frame, frames=range(steps), interval=1
    )
    plt.show()


if __name__ == "__main__":
    main()
