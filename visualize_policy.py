#!/usr/bin/env python
"""Trained-policy playback — the reference's ``python visualize_policy.py
name=x`` workflow (visualize_policy.py:11-48): discover the newest
``rl_model_*_steps`` checkpoint under ``logs/{name}/``, load it, run one
formation with deterministic actions, render and print every transition.

Extras: ``headless=true`` runs without a display, ``steps=N`` limits the
horizon, ``platform=cpu`` keeps playback off the TPU (recommended — it is a
single formation), and ``gif=docs/demo.gif`` records the playback to an
animated gif instead of opening a window (the reference ships a committed
``animation.gif`` in its README; this is how ours is produced —
``gif_every=K`` subsamples to every K-th step to keep the file small).
"""

from __future__ import annotations

import sys


def main(argv=None) -> None:
    from marl_distributedformation_tpu.utils import (
        env_params_from_config,
        latest_checkpoint,
        load_config,
        repo_root,
        setup_platform,
    )

    cfg = load_config(sys.argv[1:] if argv is None else argv)
    setup_platform(cfg.get("platform"))

    from marl_distributedformation_tpu.compat.policy import LoadedPolicy
    from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv

    checkpoint_dir = repo_root() / "logs" / str(cfg.name)
    path = latest_checkpoint(checkpoint_dir)
    if path is None:
        # Sweep run (train/sweep.py): descend into the ranked-best
        # member so `name=pop` plays back what sweep_summary.json points
        # at (members are under seed{i}/; `name=pop/seed3` still works).
        # An INTERRUPTED sweep has member checkpoints but no summary —
        # fall back to the furthest-trained member rather than claiming
        # nothing exists.
        import json
        import re

        summary = checkpoint_dir / "sweep_summary.json"
        members = sorted(
            (
                p for p in checkpoint_dir.glob("seed*")
                if p.is_dir() and re.fullmatch(r"seed\d+", p.name)
            ),
            key=lambda p: int(p.name.removeprefix("seed")),
        )
        if summary.exists():
            best = json.loads(summary.read_text())["best_dir"]
            path = latest_checkpoint(checkpoint_dir / best)
            if path is not None:
                print(f"sweep run: playing best member {best}")
            else:
                # The summary's best_dir checkpoint is gone (e.g. deleted
                # by hand) — fall through to the members scan below
                # instead of claiming no checkpoint exists (ADVICE r3).
                print(
                    f"sweep summary points at {best} but it has no "
                    "checkpoint; falling back to furthest-trained member"
                )
        if path is None and members:
            candidates = [
                (latest_checkpoint(d), d.name) for d in members
            ]
            candidates = [(p, n) for p, n in candidates if p is not None]
            if candidates:
                path, member = max(
                    candidates,
                    key=lambda c: int(c[0].stem.split("_")[-2]),
                )
                why = (
                    "best member missing" if summary.exists()
                    else "no final summary (interrupted?)"
                )
                print(
                    f"sweep run, {why}: "
                    f"playing furthest-trained member {member}"
                )
    if path is None:
        raise SystemExit(
            f"no rl_model_*_steps checkpoint found in {checkpoint_dir} — "
            f"train first: python train.py name={cfg.name}"
        )
    cfg.num_formation = 1  # override, visualize_policy.py:36
    params = env_params_from_config(cfg)

    print(f"Loading model from {path}")  # visualize_policy.py:33
    policy = LoadedPolicy.from_checkpoint(path, env_params=params)
    env = FormationVecEnv(params, num_formations=1, seed=cfg.get("seed", 0))
    obs = env.reset()

    steps = int(cfg.get("steps", 1000))
    headless = bool(cfg.get("headless", False))
    gif = cfg.get("gif")
    quiet = bool(gif)  # gif recording skips the per-step transition dump
    # deterministic=false plays the policy as it behaves during training
    # (actions sampled from its Gaussian — evaluate.py's
    # eval_deterministic knob; noise-reliant policies like the hetero5
    # artifact only hold their ring spacing this way). Default matches
    # the reference's model.predict(deterministic=True)
    # (visualize_policy.py:16).
    deterministic = bool(cfg.get("deterministic", True))

    def playback_step(i, obs):
        if not quiet:
            print("-" * 10)
            print(f"Step {i}")
        actions, _ = policy.predict(obs, deterministic=deterministic)
        obs, rewards, dones, _ = env.step(actions)
        if not quiet:
            print(f"actions: {actions}")
            print(f"obs: {obs}")
            print(f"rewards: {rewards}")
            print(f"dones: {dones}")
        return obs

    if headless and not gif:
        for i in range(steps):
            obs = playback_step(i, obs)
        return

    if gif:
        import matplotlib

        matplotlib.use("Agg")
        from matplotlib.animation import PillowWriter

        from marl_distributedformation_tpu.compat.render import (
            FormationRenderer,
        )

        every = int(cfg.get("gif_every", 5))
        renderer = FormationRenderer(params, title=f"policy: {path.name}")
        writer = PillowWriter(fps=int(cfg.get("gif_fps", 20)))
        with writer.saving(renderer.fig, str(gif), dpi=60):
            for i in range(steps):
                obs = playback_step(i, obs)
                if i % every == 0:
                    renderer.update(
                        env.agents_np(), env.goal_np(), env.obstacles_np()
                    )
                    writer.grab_frame()
        print(f"wrote {steps // every} frames to {gif}")
        return

    import matplotlib.animation as animation
    import matplotlib.pyplot as plt

    from marl_distributedformation_tpu.compat.render import FormationRenderer

    renderer = FormationRenderer(params, title=f"policy: {path.name}")
    obs_holder = [obs]

    def frame(i):
        obs_holder[0] = playback_step(i, obs_holder[0])
        renderer.update(env.agents_np(), env.goal_np(), env.obstacles_np())

    ani = animation.FuncAnimation(  # noqa: F841
        renderer.fig, frame, frames=range(steps), interval=200
    )
    plt.show()


if __name__ == "__main__":
    main()
