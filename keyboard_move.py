#!/usr/bin/env python
"""Keyboard teleop — the reference's ``python keyboard_move.py`` workflow
(keyboard_move.py:6-49): N=3 agents, digit keys select an agent, arrow keys
move it at speed 10, ESC quits; every transition (action/obs/reward/done/
info) is printed for human inspection of the env contract (README.md:10-12).

Uses matplotlib's native key events instead of the reference's pynput
global-listener thread — same keys, no second thread mutating env state
(SURVEY.md §3.4). One behavioral caveat: the reference's listener is
system-global (keyboard_move.py:47 captures keys from any window), while
mpl key events only arrive when **the figure window has focus** — click
the plot first if keys seem dead. Extras: ``num_agents=K``,
``platform=cpu``.
"""

from __future__ import annotations

import sys

import numpy as np


def main(argv=None) -> None:
    from marl_distributedformation_tpu.utils import (
        Config,
        apply_overrides,
        setup_platform,
    )

    cfg = Config(num_agents=3, platform=None)
    apply_overrides(cfg, sys.argv[1:] if argv is None else argv)
    num_agents = int(cfg.num_agents)
    setup_platform(cfg.platform)

    import matplotlib.pyplot as plt

    from marl_distributedformation_tpu.compat.render import FormationRenderer
    from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv
    from marl_distributedformation_tpu.env import EnvParams

    params = EnvParams(num_agents=num_agents)
    env = FormationVecEnv(params, num_formations=1)
    env.reset()

    state = {"agent": 0}
    speed = 10.0  # keyboard_move.py:24

    renderer = FormationRenderer(params, title="teleop (0-9 select, arrows move)")
    renderer.update(env.agents_np(), env.goal_np(), env.obstacles_np())

    def on_key(event) -> None:
        key = event.key
        if key == "escape":
            plt.close("all")
            return
        if key is not None and key.isdigit() and int(key) < num_agents:
            state["agent"] = int(key)
            print(f"Moving agent {state['agent']} from next move...")
            return
        direction = {
            "up": (0.0, speed),
            "down": (0.0, -speed),
            "left": (-speed, 0.0),
            "right": (speed, 0.0),
        }.get(key)
        if direction is None:
            return
        action = np.zeros((num_agents, 2), np.float32)
        action[state["agent"]] = direction
        obs, rewards, done, info = env.step_velocities(action[None])
        renderer.update(env.agents_np(), env.goal_np(), env.obstacles_np())
        renderer.draw()
        print("-" * 10)
        print(f"{action=}\n{obs=}\n{rewards=}\n{done=}\n{info=}")

    renderer.fig.canvas.mpl_connect("key_press_event", on_key)
    print(f"Press 0-{num_agents - 1} to choose which agent to move.")
    print("Arrow keys move the selected agent; ESC exits.")
    print("(Keys go to the figure window — click the plot to focus it.)")
    plt.show()


if __name__ == "__main__":
    main()
