"""RollbackMonitor: the serving-side tripwire behind the gate.

The gate judges candidates OFFLINE (eval episodes on the eval seed); a
regression that only manifests under real serving conditions — latency
blowups from a pathological parameter pattern, a quality signal a
frontend computes, any number the fleet's ``/v1/metrics``-level
snapshot carries — needs a second, online line of defense. The monitor
samples one configured metric from a snapshot function (typically
``FleetRouter.snapshot`` in-process, or an HTTP ``GET /v1/metrics``
reader), establishes a baseline over the first samples after each
promotion, and trips after ``trip_after`` consecutive breaches of the
configured limit. Tripping is a SIGNAL — the supervisor owns the
demotion itself (retract + monotonicity-exempt pinned reload,
``docs/pipeline.md`` has the state machine).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional


class RollbackMonitor:
    """Watch one served metric; report when it regresses.

    Args:
      sample_fn: zero-arg callable returning a flat ``{name: float}``
        snapshot (``FleetRouter.snapshot()`` shape). Missing metric in a
        sample = the sample is skipped (a cold fleet has no latency
        percentiles yet).
      metric: key to watch.
      threshold: absolute limit; breach when the value crosses it in
        ``direction``. Takes precedence over ``ratio``.
      ratio: relative limit vs the post-promotion baseline (mean of the
        first ``baseline_samples`` observations): the limit sits
        ``|baseline| * (ratio - 1)`` away from the baseline in the
        breach ``direction`` — offset by magnitude, not multiplied, so
        negative-valued baselines (this env's episode returns are
        penalty sums) keep the limit on the breach side. Ratio > 1.
      direction: ``"above"`` for cost-like metrics (latency, error
        counts), ``"below"`` for quality-like metrics.
      baseline_samples: observations averaged into the baseline before
        breach checking starts (ignored with an absolute threshold).
      trip_after: consecutive breaches required — one noisy sample must
        not demote a healthy fleet.
    """

    def __init__(
        self,
        sample_fn: Callable[[], Dict[str, float]],
        metric: str,
        threshold: Optional[float] = None,
        ratio: Optional[float] = None,
        direction: str = "above",
        baseline_samples: int = 3,
        trip_after: int = 2,
    ) -> None:
        if direction not in ("above", "below"):
            raise ValueError(
                f"direction must be 'above' or 'below', got {direction!r}"
            )
        if threshold is None and ratio is None:
            raise ValueError(
                "RollbackMonitor needs an absolute threshold or a "
                "baseline ratio"
            )
        if ratio is not None and ratio <= 1.0:
            raise ValueError(f"ratio must be > 1, got {ratio}")
        self.sample_fn = sample_fn
        self.metric = metric
        self.threshold = threshold
        self.ratio = ratio
        self.direction = direction
        self.baseline_samples = max(1, int(baseline_samples))
        self.trip_after = max(1, int(trip_after))
        self._window: List[float] = []
        self.baseline: Optional[float] = None
        self.last_value: Optional[float] = None
        self._breaches = 0

    def reset(self) -> None:
        """Forget the baseline and breach streak — called after every
        promotion or rollback (a new checkpoint serves under a new
        normal)."""
        self._window = []
        self.baseline = None
        self._breaches = 0

    def limit(self) -> Optional[float]:
        """The current breach limit, or None while the baseline is
        still forming."""
        if self.threshold is not None:
            return self.threshold
        if self.baseline is None:
            return None
        # Offset by |baseline|, never multiply: baseline * ratio flips
        # to the WRONG side of a negative baseline (b=-10, ratio=1.5
        # puts the "above" limit at -15, below the baseline — every
        # healthy sample would breach).
        margin = abs(self.baseline) * (self.ratio - 1.0)
        return (
            self.baseline + margin
            if self.direction == "above"
            else self.baseline - margin
        )

    def observe(self) -> bool:
        """Take one sample; True when the regression streak trips."""
        try:
            value = self.sample_fn().get(self.metric)
        except Exception:  # noqa: BLE001 — a flaky sampler is not a
            # regression; the next sample decides.
            return False
        if value is None:
            return False
        value = float(value)
        self.last_value = value
        if self.threshold is None and self.baseline is None:
            self._window.append(value)
            if len(self._window) < self.baseline_samples:
                return False
            self.baseline = sum(self._window) / len(self._window)
            return False  # baseline sample, never a breach
        limit = self.limit()
        breached = (
            value > limit if self.direction == "above" else value < limit
        )
        self._breaches = self._breaches + 1 if breached else 0
        return self._breaches >= self.trip_after
