"""AlwaysLearningPipeline: the control plane over trainer, gate, fleet.

The loop (docs/pipeline.md has the full state machine):

    trainer writes logs/{name}/rl_model_*  ──►  CheckpointStream
        │ new candidate, step order
        ▼
    PromotionGate.evaluate  ── reject ──►  promotions.jsonl "rejected"
        │ pass
        ▼
    Promoter.publish ──► promoted/ ──► FleetReloadCoordinator.refresh
        │ fleet serves the step (globally monotonic model_step)
        ▼
    promotions.jsonl "promoted" (+ promotion_latency_s)
        ▲
    RollbackMonitor regression  ──►  demote: retract above last-good,
        reload_pinned(last-good, monotonic=False), gate.rebase,
        promotions.jsonl "rolled_back"

Everything is driven by explicit ``poll_once()`` calls — deterministic
for tests — and ``run()`` wraps them in the background loop the CLI
uses. The fleet attaches AFTER the first promotion exists (a fleet
cannot boot from an empty promoted directory); until then passing
candidates are published and the verdicts logged, so
``wait_first_promotion`` + ``fleet_from_checkpoint_dir(promoted_dir)``
is the bootstrap sequence (scripts/always_learning.py).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.chaos.watchdog import Heartbeat
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.obs import (
    get_registry,
    get_tracer,
    new_trace_id,
)
from marl_distributedformation_tpu.pipeline.gate import (
    GateConfig,
    GateVerdict,
    PromotionGate,
)
from marl_distributedformation_tpu.pipeline.promote import (
    Promoter,
    PromotionLog,
)
from marl_distributedformation_tpu.pipeline.rollback import RollbackMonitor
from marl_distributedformation_tpu.pipeline.stream import CheckpointStream


@dataclasses.dataclass
class PromotionRecord:
    """One served promotion: where it came from, where it serves from,
    and how long train-step -> served took."""

    step: int
    source: str
    promoted: str
    latency_s: Optional[float]  # None before a fleet is attached
    trace_id: Optional[str] = None  # the candidate's promotion trace
    spans: Optional[Dict[str, float]] = None  # per-stage decomposition


class _PromotionTrace:
    """One candidate's trace identity plus its stage clock.

    The stages are the promotion-latency decomposition the obs spine
    exists to measure (ISSUE 8): ``stream_poll_s`` (durable write ->
    gate start, including the poll interval and any queue wait behind
    earlier candidates), ``gate_eval_s``, ``publish_s``,
    ``barrier_commit_s``, ``first_serve_s`` (commit -> a post-commit
    dispatch answering with this step), and — only when a wedged commit
    deferred the candidate — ``deferred_wait_s``. The measurement points
    are back-to-back in ``process_candidate``, so the stage sum tracks
    ``promotion_latency_s`` to within clock-read noise."""

    def __init__(self, path: Path) -> None:
        self.trace_id = new_trace_id()
        self.stages: Dict[str, float] = {}
        self.deferred_at: Optional[float] = None
        try:
            self.t_write: Optional[float] = path.stat().st_mtime
        except OSError:
            self.t_write = None

    def add(self, stage: str, seconds: float) -> None:
        self.stages[stage] = self.stages.get(stage, 0.0) + max(0.0, seconds)

    def rounded(self) -> Dict[str, float]:
        return {k: round(v, 4) for k, v in self.stages.items()}


class AlwaysLearningPipeline:
    """Wire stream -> gate -> promoter -> fleet, with rollback."""

    def __init__(
        self,
        log_dir: str | Path,
        env_params: EnvParams,
        gate_config: GateConfig = GateConfig(),
        promoted_dir: Optional[str | Path] = None,
        poll_interval_s: float = 0.25,
        start_after_step: int = -1,
        feedback_rollouts: int = 50,
        gate_device=None,
        model_id: Optional[str] = None,
    ) -> None:
        # The tenant lane this pipeline promotes into (serving/tenancy):
        # stamped on every promotions.jsonl line (schema 5) and sent
        # with the first-serve probe so a lane-keyed fleet routes it
        # down the right lane. None = single-model pipeline, unchanged.
        self.model_id = model_id
        self.log_dir = Path(log_dir)
        self.env_params = env_params  # sized requests (first-serve probe)
        self.stream = CheckpointStream(
            self.log_dir,
            poll_interval_s=poll_interval_s,
            start_after_step=start_after_step,
        )
        # gate_device: the gate's own device-slice assignment
        # (train/sebulba's partition — docs/sebulba.md). The promotion
        # span breakdown and the verdict log then record which slice
        # served each eval.
        self.gate = PromotionGate(env_params, gate_config, device=gate_device)
        self.promoted_dir = Path(
            promoted_dir if promoted_dir is not None
            else self.log_dir / "promoted"
        )
        self.promoter = Promoter(self.promoted_dir)
        self.log = PromotionLog(
            self.log_dir / "promotions.jsonl", model_id=model_id
        )
        self.router: Optional[Any] = None
        self.coordinator: Optional[Any] = None
        self.monitor: Optional[RollbackMonitor] = None
        self.trainer: Optional[Any] = None
        # Auto-curriculum feedback (docs/adversarial.md): rejections
        # whose verdict carries falsifiers are fed back into an attached
        # trainer's scenario schedule as a from_falsifiers stage of this
        # many rollouts.
        self.feedback_rollouts = int(feedback_rollouts)
        self.curriculum_updates = 0
        self.promotions: List[PromotionRecord] = []
        self.rejections: List[GateVerdict] = []
        self.rollbacks: List[dict] = []
        # Candidates discovered but not yet judged (wait_first_promotion
        # stops at the first pass; the backlog is served once the fleet
        # is attached, so every later promotion actually swaps).
        self._pending: List[Path] = []
        # Published candidates whose fleet commit did NOT land (a wedged
        # replica aborts the batch-barrier swap) — retried each poll;
        # they only become promotions when the fleet actually serves
        # them. Step-ascending by construction.
        self._deferred: List[tuple] = []
        # Background-loop errors (run() must survive them, not die
        # silently) — newest last, surfaced in summary().
        self.errors: List[str] = []
        # The serving stack: promoted records still considered good
        # (rollback pops). Top = what the fleet serves.
        self._good: List[PromotionRecord] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Self-healing supervision (chaos/watchdog.py): the run loop
        # heartbeats every iteration; a LaneWatchdog watching this lane
        # restarts it on wedge/death via restart_loop(). The generation
        # token is how a wedged thread is ABANDONED — it exits at its
        # next generation check instead of racing its replacement.
        self.heartbeat = Heartbeat("pipeline_loop")
        self._generation = 0
        self._interval_s = 0.25

    # -- wiring ----------------------------------------------------------

    def attach_fleet(self, router: Any, coordinator: Any) -> None:
        """Hand over the serving side. The coordinator MUST watch the
        promoted directory — watching the trainer's own directory would
        serve unvetted candidates, the exact hole this subsystem
        closes."""
        if Path(coordinator.log_dir).resolve() != self.promoted_dir.resolve():
            raise ValueError(
                f"coordinator watches {coordinator.log_dir}, but only "
                f"the promoted directory {self.promoted_dir} holds "
                "vetted checkpoints — build the fleet with "
                "fleet_from_checkpoint_dir(pipeline.promoted_dir)"
            )
        self.router = router
        self.coordinator = coordinator

    def attach_monitor(self, monitor: RollbackMonitor) -> None:
        self.monitor = monitor

    def attach_trainer(self, trainer: Any) -> None:
        """Push-path hookup: the trainer nudges the stream the moment a
        checkpoint is durable (no poll-interval floor on promotion
        latency) — and, with the gate's adversarial rung on, receives
        rejected candidates' falsifiers back as curriculum stages (the
        train -> gate -> train robustness loop)."""
        trainer.on_checkpoint = self.stream.nudge
        self.trainer = trainer

    # -- the loop --------------------------------------------------------

    def process_candidate(self, path: Path) -> GateVerdict:
        """Gate one candidate; publish + swap + log on pass, log on
        reject. A passing candidate whose FLEET COMMIT does not land (a
        wedged replica aborts the barrier swap — reload.py's abort path)
        is 'promotion_deferred', not 'promoted': the baseline, the
        good-stack, and the audit log only ever advance to checkpoints
        that actually serve; the commit is retried on later polls.

        Every candidate gets ONE trace ID (obs/) that labels the gate
        eval span, the reload barrier spans, the first-serve batch span,
        and the ``promotions.jsonl`` line — one trace reconstructs the
        whole promotion."""
        tracer = get_tracer()
        registry = get_registry()
        tr = _PromotionTrace(path)
        t_gate_start = time.time()
        if tr.t_write is not None:
            # On-disk wait from durable write to gate pickup — back-dated
            # to the checkpoint's mtime on the tracer's shared clock.
            tr.add("stream_poll_s", t_gate_start - tr.t_write)
            tracer.add_span(
                "promotion.stream_poll",
                tracer.epoch_to_mono(tr.t_write),
                tracer.epoch_to_mono(t_gate_start),
                trace_id=tr.trace_id,
                path=str(path),
            )
            # Live lag gauge: how far behind the trainer's durable
            # writes the gate is running right now.
            registry.gauge("pipeline_stream_poll_lag_seconds").set(
                t_gate_start - tr.t_write
            )
        t0 = time.perf_counter()
        with tracer.span(
            "promotion.gate_eval",
            trace_id=tr.trace_id,
            device=self.gate.device_str(),
        ):
            verdict = self.gate.evaluate(path, trace_id=tr.trace_id)
        gate_eval_s = time.perf_counter() - t0
        tr.add("gate_eval_s", gate_eval_s)
        registry.histogram("pipeline_gate_eval_seconds").observe(gate_eval_s)
        registry.gauge("gate_eval_steps_per_sec").set(
            self.gate.eval_steps_per_sec()
        )
        if not verdict.passed:
            self.rejections.append(verdict)
            registry.counter("pipeline_rejections_total").inc()
            self.log.append(
                "rejected", **verdict.record(), trace_id=tr.trace_id
            )
            self._feed_falsifiers(verdict, tr.trace_id)
            return verdict
        t0 = time.perf_counter()
        try:
            with tracer.span(
                "promotion.publish", trace_id=tr.trace_id, step=verdict.step
            ):
                promoted = self.promoter.publish(path)
        except FileNotFoundError:
            # The candidate vanished between gate verdict and publish —
            # the trainer's retention ring pruned it (keep_last_n sized
            # under the pipeline's lag, docs/recovery.md) or a rollback
            # retracted it. A missing FILE is a skipped candidate, never
            # a dead supervisor: audit it and let the stream move on (a
            # newer checkpoint is usually the reason the old one was
            # prunable at all).
            registry.counter("pipeline_candidates_vanished_total").inc()
            self.log.append(
                "candidate_vanished",
                step=verdict.step,
                checkpoint=str(path),
                trace_id=tr.trace_id,
            )
            return verdict
        tr.add("publish_s", time.perf_counter() - t0)
        if self.coordinator is not None:
            t0 = time.perf_counter()
            with tracer.span(
                "promotion.barrier_commit", trace_id=tr.trace_id,
                step=verdict.step,
            ):
                self.coordinator.refresh(trace_id=tr.trace_id)
            tr.add("barrier_commit_s", time.perf_counter() - t0)
            # refresh() may return False for benign reasons (a started
            # background watcher raced us to the swap) — what matters is
            # whether the fleet now serves at least this step.
            if self.coordinator.fleet_step < verdict.step:
                tr.deferred_at = time.time()
                self._deferred.append((verdict, str(promoted), path, tr))
                get_registry().counter("pipeline_deferred_total").inc()
                self.log.append(
                    "promotion_deferred",
                    **verdict.record(),
                    trace_id=tr.trace_id,
                    promoted_path=str(promoted),
                    reason="fleet commit did not land (see coordinator "
                    "load_errors); retrying on later polls",
                )
                return verdict
            self._probe_first_serve(tr, verdict.step)
            # Served wall-clock: from the moment the trainer's write
            # became durable (the file's mtime) to the moment every
            # post-commit dispatch answers with this step (the probe
            # above just witnessed one).
            latency = self._latency_since_write(path)
        else:
            latency = None
        self._finalize_promotion(verdict, str(promoted), path, latency, tr)
        return verdict

    def _feed_falsifiers(
        self, verdict: GateVerdict, trace_id: Optional[str]
    ) -> None:
        """Close the train -> gate -> train loop: a rejection that
        carries discovered falsifiers becomes a new curriculum stage in
        the attached trainer (``scenarios.from_falsifiers``, applied by
        the training thread at its next dispatch boundary). Audit-logged
        as ``curriculum_updated`` with the falsifier payloads — the
        schedule the trainer runs is reconstructible from the log. A
        trainer without the scenario seam degrades to a logged
        ``curriculum_update_failed``, never a crashed control plane."""
        falsifiers = getattr(verdict, "falsifiers", None) or []
        if self.trainer is None or not falsifiers:
            return
        from marl_distributedformation_tpu.scenarios import from_falsifiers

        try:
            schedule = from_falsifiers(
                falsifiers, rollouts=self.feedback_rollouts
            )
            self.trainer.request_scenario_schedule(schedule)
        except Exception as e:  # noqa: BLE001 — feedback is advisory;
            # a mis-wired trainer must not kill the promotion loop.
            self.log.append(
                "curriculum_update_failed",
                step=verdict.step,
                reason=repr(e)[:300],
                trace_id=trace_id,
            )
            return
        self.curriculum_updates += 1
        self.log.append(
            "curriculum_updated",
            step=verdict.step,
            falsifiers=list(falsifiers),
            feedback_rollouts=self.feedback_rollouts,
            scenarios=list(schedule.names),
            trace_id=trace_id,
        )

    def _probe_first_serve(self, tr: _PromotionTrace, step: int) -> None:
        """Witness the first post-commit response at the promoted step:
        one 1-row request through the router, timed as the
        ``first_serve`` stage. Best-effort — a probe failure (per-
        formation row shapes, transient backpressure) leaves the stage
        unmeasured and never blocks the promotion itself."""
        if self.router is None:
            return
        t0 = time.perf_counter()
        try:
            obs = np.zeros((1, self.env_params.obs_dim), np.float32)
            kwargs = (
                {} if self.model_id is None
                else {"model_id": self.model_id}
            )
            result = self.router.submit(
                obs, trace_id=tr.trace_id, **kwargs
            ).result(timeout=self.router.default_timeout_s + 5.0)
            done = time.perf_counter()
            tr.add("first_serve_s", done - t0)
            get_tracer().add_span(
                "promotion.first_serve",
                t0,
                done,
                trace_id=tr.trace_id,
                step=step,
                served_step=int(result.model_step),
            )
        except Exception:  # noqa: BLE001 — observability never gates serving
            pass

    @staticmethod
    def _latency_since_write(path: Path) -> Optional[float]:
        try:
            return max(0.0, time.time() - path.stat().st_mtime)
        except OSError:  # source pruned after the gate read it — the
            # promotion stands, only its latency is unmeasurable
            return None

    def _finalize_promotion(
        self,
        verdict: GateVerdict,
        promoted: str,
        path: Path,
        latency: Optional[float],
        tr: Optional[_PromotionTrace] = None,
    ) -> None:
        """The candidate SERVES (or no fleet is attached yet): install
        it as the gate baseline and the new last-good."""
        self.gate.accept(verdict)
        record = PromotionRecord(
            step=verdict.step,
            source=str(path),
            promoted=promoted,
            latency_s=latency,
            trace_id=tr.trace_id if tr is not None else None,
            spans=tr.rounded() if tr is not None else None,
        )
        self.promotions.append(record)
        self._good.append(record)
        registry = get_registry()
        registry.counter("pipeline_promotions_total").inc()
        registry.gauge("pipeline_served_step").set(verdict.step)
        if latency is not None:
            registry.histogram("promotion_latency_seconds").observe(latency)
        if self.monitor is not None:
            self.monitor.reset()
        # Schema-4 commit attribution: which coordinator round served
        # this candidate and how many hosts it committed (1 for a
        # single-host fleet; the mesh coordinator reports the real
        # round's host count). Only claimed when the newest landed
        # commit is EXACTLY this candidate's step — an aborted refresh
        # (benign at line level, the deferred path owns it) must not
        # stamp this promotion with the PREVIOUS round's attribution.
        # No fleet attached yet -> None.
        commit = getattr(self.coordinator, "last_commit", None) or {}
        if commit.get("step") != verdict.step:
            commit = {}
        self.log.append(
            "promoted",
            **verdict.record(),
            trace_id=record.trace_id,
            spans=record.spans,
            promoted_path=promoted,
            promotion_latency_s=(
                round(latency, 4) if latency is not None else None
            ),
            host_count=commit.get("host_count"),
            commit_round=commit.get("commit_round"),
        )

    def _retry_deferred(self) -> None:
        """Re-attempt the fleet commit for published-but-unserved
        candidates. A deferred candidate finalizes ONLY when the fleet
        serves EXACTLY its step; if the fleet moved past it (refresh
        always commits the newest published checkpoint, so clearing a
        wedge with several candidates queued jumps straight to the
        latest), the older candidate never served and never will — it
        terminates as 'promotion_superseded', not 'promoted', and never
        becomes the gate baseline or a rollback target."""
        if not self._deferred or self.coordinator is None:
            return
        # refresh commits the NEWEST published checkpoint — label its
        # spans with that candidate's trace so the retry leg joins the
        # same promotion trace as the original attempt.
        retry_trace = self._deferred[-1][3]
        # The deferred wait ends where the retry commit begins — snapshot
        # the boundary BEFORE refresh() so the commit seconds land only
        # in barrier_commit_s and the stages still sum to the latency.
        wait_end = time.time()
        t_retry = time.perf_counter()
        self.coordinator.refresh(trace_id=retry_trace.trace_id)
        retry_commit_s = time.perf_counter() - t_retry
        still_deferred = []
        for verdict, promoted, path, tr in self._deferred:
            fleet_step = self.coordinator.fleet_step
            if fleet_step == verdict.step:
                if tr.deferred_at is not None:
                    tr.add("deferred_wait_s", wait_end - tr.deferred_at)
                tr.add("barrier_commit_s", retry_commit_s)
                self._probe_first_serve(tr, verdict.step)
                self._finalize_promotion(
                    verdict, promoted, path,
                    self._latency_since_write(path), tr,
                )
            elif fleet_step > verdict.step:
                self.log.append(
                    "promotion_superseded",
                    step=verdict.step,
                    checkpoint=verdict.path,
                    reason=f"fleet committed step {fleet_step} while this "
                    "candidate's swap was deferred; it never served",
                    trace_id=tr.trace_id,
                )
            else:
                still_deferred.append((verdict, promoted, path, tr))
        self._deferred = still_deferred

    def check_rollback(self) -> bool:
        """One monitor sample; demote to last-good on a tripped
        regression. Returns True iff a rollback happened."""
        if (
            self.monitor is None
            or self.coordinator is None
            or len(self._good) < 2
            # With one good checkpoint there is nothing to demote TO —
            # an empty fleet is strictly worse than a suspect one.
        ):
            return False
        if not self.monitor.observe():
            return False
        bad = self._good.pop()
        last_good = self._good[-1]
        entry = {
            "from_step": bad.step,
            "to_step": last_good.step,
            "metric": self.monitor.metric,
            "value": self.monitor.last_value,
            "limit": self.monitor.limit(),
            "baseline": self.monitor.baseline,
        }
        # The tripped alarm is a postmortem-grade incident BEFORE the
        # demotion is attempted: the flight recorder snapshots the ring
        # while the regressed checkpoint's serving history is still in
        # it. The demotion itself shares the rollback's trace ID.
        rollback_trace = new_trace_id()
        get_tracer().incident(
            "rollback_trip", trace_id=rollback_trace, **entry
        )
        # Retract FIRST so a concurrently-polling coordinator cannot
        # re-promote the demoted step between the swap and the cleanup.
        # Deferred candidates above last-good lose their published files
        # here too — terminate them (they can never commit now; leaving
        # them queued would retry forever and could later finalize a
        # retracted, never-served checkpoint).
        self.promoter.retract_above(last_good.step)
        still_deferred = []
        for verdict, promoted, path, tr in self._deferred:
            if verdict.step > last_good.step:
                self.log.append(
                    "promotion_superseded",
                    step=verdict.step,
                    checkpoint=verdict.path,
                    reason=f"retracted by the rollback to step "
                    f"{last_good.step} while its swap was deferred",
                    trace_id=tr.trace_id,
                )
            else:
                still_deferred.append((verdict, promoted, path, tr))
        self._deferred = still_deferred
        if not self.coordinator.reload_pinned(
            last_good.promoted, monotonic=False, trace_id=rollback_trace
        ):
            # The demotion commit itself failed (wedged replica /
            # unreadable last-good): the regressed checkpoint is STILL
            # serving — record that truthfully, restore the good-stack
            # AND its published file (retract_above already removed it;
            # without the re-publish, a later rollback TO this record
            # would pin a nonexistent path forever), and leave the
            # breach streak alive so the next poll retries
            # (monitor.reset here would silence the alarm).
            try:
                self.promoter.publish(bad.source)
            except OSError:  # source pruned: the record stays, only
                pass  # its file is gone — reload_pinned will record it
            self._good.append(bad)
            self.log.append(
                "rollback_failed",
                **entry,
                reason="pinned reload did not commit (see coordinator "
                "load_errors); retrying on later polls",
                trace_id=rollback_trace,
            )
            return False
        self.gate.rebase(last_good.step)
        self.monitor.reset()
        self.rollbacks.append(entry)
        registry = get_registry()
        registry.counter("pipeline_rollbacks_total").inc()
        registry.gauge("pipeline_served_step").set(last_good.step)
        commit = getattr(self.coordinator, "last_commit", None) or {}
        if commit.get("step") != last_good.step:
            commit = {}  # attribution must be THIS demotion's round
        self.log.append(
            "rolled_back",
            **entry,
            trace_id=rollback_trace,
            host_count=commit.get("host_count"),
            commit_round=commit.get("commit_round"),
        )
        return True

    def poll_once(self) -> int:
        """One supervision step: retry deferred fleet commits, gate
        every queued + newly-discovered candidate, then sample the
        rollback monitor once. Returns candidates processed."""
        self._retry_deferred()
        self._pending.extend(self.stream.poll())
        processed = 0
        while self._pending:
            self.process_candidate(self._pending.pop(0))
            processed += 1
        self.check_rollback()
        return processed

    def wait_first_promotion(self, timeout_s: float = 60.0) -> bool:
        """Bootstrap: block until the first candidate PASSES the gate
        (rejecting failures along the way — one candidate at a time, so
        everything after the first pass stays queued for the
        fleet-attached loop). After this the promoted directory is
        non-empty and a fleet can boot from it."""
        deadline = time.monotonic() + timeout_s
        while not self.promotions:
            if not self._pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._pending.extend(self.stream.wait(min(remaining, 5.0)))
                continue
            self.process_candidate(self._pending.pop(0))
        return True

    # -- background loop (the CLI's mode) --------------------------------

    def run(self, interval_s: float = 0.25) -> "AlwaysLearningPipeline":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._interval_s = interval_s
        self._start_loop()
        return self

    def _start_loop(self) -> None:
        """Spawn one generation of the supervision loop. The generation
        token gates every blocking boundary: a superseded (restarted-
        over) thread exits before touching the gate or the pending
        queue again, so a watchdog restart can never double-process a
        candidate or double-compile the eval program."""
        self._generation += 1
        gen = self._generation
        interval_s = self._interval_s

        def live() -> bool:
            return not self._stop.is_set() and self._generation == gen

        def loop() -> None:
            while live():
                # A transient failure (full disk during publish/log, a
                # checkpoint pruned mid-judgment) must not silently kill
                # the control plane — record it and keep supervising. A
                # SimulatedCrash (BaseException) is NOT contained: it
                # kills this lane like a real kill and the watchdog owns
                # the restart.
                try:
                    self.heartbeat.beat()
                    fault_point("pipeline.poll")
                    if not live():
                        return  # restarted over while wedged: abandon
                    self._retry_deferred()
                    self._pending.extend(self.stream.wait(interval_s))
                    while self._pending and live():
                        # Beat per candidate: a healthy lane working
                        # through a deep backlog must not read as
                        # wedged. (One eval LONGER than the watchdog's
                        # wedge_timeout_s still trips — size the
                        # timeout past a gate eval; the gate's eval
                        # lock keeps an overlapping restart from
                        # double-compiling either way.)
                        self.heartbeat.beat()
                        self.process_candidate(self._pending.pop(0))
                    self.check_rollback()
                except Exception as e:  # noqa: BLE001
                    self.errors.append(repr(e))
                    del self.errors[:-32]  # bounded
                    self._stop.wait(interval_s)

        self._thread = threading.Thread(
            target=loop,
            name=f"always-learning-pipeline-g{gen}",
            daemon=True,
        )
        self._thread.start()

    def loop_alive(self) -> bool:
        """Liveness probe for the watchdog: is the CURRENT generation's
        thread running?"""
        return self._thread is not None and self._thread.is_alive()

    def restart_loop(self) -> None:
        """Abandon-and-replace the supervision lane (the watchdog's
        restart hook): bump the generation — the old thread, wedged or
        dead, exits at its next generation check — and start a fresh
        one. No-op after stop()."""
        if self._stop.is_set():
            return
        self._start_loop()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self.stream.nudge()
        self._thread.join(timeout=30.0)
        self._thread = None

    def __enter__(self) -> "AlwaysLearningPipeline":
        return self.run()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- observability ---------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Flat report (the CLI's JSON line feeds off it)."""
        latencies = sorted(
            r.latency_s for r in self.promotions if r.latency_s is not None
        )

        def pct(q: float) -> Optional[float]:
            if not latencies:
                return None
            idx = min(len(latencies) - 1, int(q * len(latencies)))
            return round(latencies[idx], 4)

        # Per-stage p50s over every traced promotion — the bench's
        # promotion_span_breakdown (where did the promotion seconds go).
        by_stage: Dict[str, List[float]] = {}
        for r in self.promotions:
            for stage, seconds in (r.spans or {}).items():
                by_stage.setdefault(stage, []).append(seconds)
        breakdown = {}
        for stage, values in by_stage.items():
            values.sort()
            breakdown[stage] = round(
                values[min(len(values) - 1, int(0.5 * len(values)))], 4
            )

        return {
            "promotion_span_breakdown": breakdown,
            # Which device-slice served the gate evals (None = default
            # placement / Anakin time-share) — pairs with the breakdown's
            # gate_eval_s so a latency report names its silicon.
            "gate_device": self.gate.device_str(),
            "promotions": len(self.promotions),
            "rejections": len(self.rejections),
            "rollbacks": len(self.rollbacks),
            "curriculum_updates": self.curriculum_updates,
            "deferred_promotions": len(self._deferred),
            "pipeline_errors": list(self.errors),
            "served_step": (
                self.coordinator.fleet_step
                if self.coordinator is not None
                else (self._good[-1].step if self._good else None)
            ),
            "promotion_latency_s_p50": pct(0.50),
            "promotion_latency_s_p95": pct(0.95),
            "gate_eval_steps_per_sec": round(
                self.gate.eval_steps_per_sec(), 1
            ),
            "gate_eval_compiles": (
                self.gate.program.compile_count
                if self.gate.program is not None
                else 0
            ),
        }
