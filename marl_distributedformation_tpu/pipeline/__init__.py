"""The always-learning fleet: trainer -> promotion gate -> fleet reload.

Every piece existed separately — a fused-scan trainer streaming async
checkpoints (train/), a compile-once robustness eval matrix
(scenarios/matrix.py), and a serving fleet with step-monotonic
coordinated hot reload (serving/fleet/) — this package composes them
into ONE supervised continuous-learning loop, in the Podracer idiom
(arXiv:2104.06272) of keeping the accelerator training loop hot while
host-side control planes run alongside:

- :class:`~.stream.CheckpointStream` tails the trainer's ``logs/{name}/``
  output incrementally (never a torn file, O(new) per poll).
- :class:`~.gate.PromotionGate` runs every candidate through the
  compiled robustness matrix plus a clean-return regression check
  against the currently-served baseline — ONE jitted program across all
  candidates (budget-1 RetraceGuard receipt).
- :class:`~.promote.Promoter` publishes only passing checkpoints into
  the ``promoted/`` directory the fleet's reload coordinator watches,
  preserving fleet-wide step monotonicity.
- :class:`~.rollback.RollbackMonitor` samples fleet serving stats and
  demotes to the last-good checkpoint when a served-metric regression
  trips (a monotonicity-exempt pinned reload —
  ``FleetReloadCoordinator.reload_pinned``).
- :class:`~.supervisor.AlwaysLearningPipeline` wires the above and
  writes the versioned ``promotions.jsonl`` verdict log.

Entry point: ``scripts/always_learning.py``. Loop topology, the
promotion/rollback state machine, and the verdict-log schema are in
``docs/pipeline.md``.
"""

from marl_distributedformation_tpu.pipeline.stream import (  # noqa: F401
    CheckpointStream,
)
from marl_distributedformation_tpu.pipeline.gate import (  # noqa: F401
    GateConfig,
    GateVerdict,
    PromotionGate,
    judge_candidate,
    judge_falsifiers,
)
from marl_distributedformation_tpu.pipeline.promote import (  # noqa: F401
    PromotionLog,
    Promoter,
)
from marl_distributedformation_tpu.pipeline.rollback import (  # noqa: F401
    RollbackMonitor,
)
from marl_distributedformation_tpu.pipeline.supervisor import (  # noqa: F401
    AlwaysLearningPipeline,
    PromotionRecord,
)

__all__ = [
    "AlwaysLearningPipeline",
    "CheckpointStream",
    "GateConfig",
    "GateVerdict",
    "PromotionGate",
    "PromotionLog",
    "PromotionRecord",
    "Promoter",
    "RollbackMonitor",
    "judge_candidate",
    "judge_falsifiers",
]
