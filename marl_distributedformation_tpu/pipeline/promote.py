"""Promoter + PromotionLog: publication and the audit trail.

The fleet's reload coordinator must only ever see VETTED checkpoints —
pointing it at the trainer's own directory would serve candidates the
gate has not judged yet. The Promoter therefore owns a separate
``promoted/`` directory: passing checkpoints are published into it with
the same atomic-rename discipline the trainer uses (hardlink or copy to
a dot-prefixed temp name, then ``os.replace``), the original
``rl_model_{steps}_steps`` naming preserved so every discovery/step
contract keeps working, and the coordinator watches ONLY this
directory. ``retract_above`` is the rollback half: demoted checkpoints
are removed so the coordinator's next poll cannot re-promote them.

``PromotionLog`` is the versioned ``promotions.jsonl`` verdict log: one
JSON object per line, schema-stamped, append-only — the audit trail of
every promote / reject / rollback decision the pipeline ever made.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Dict, List

from marl_distributedformation_tpu.utils.checkpoint import checkpoint_step

# Bump when the line shape changes; scripts/check_bench_record.py and the
# schema unit test pin the current shape.
#
# Schema history:
#   1 — PR 7: event/time/step/checkpoint + gate verdict payload.
#   2 — obs spine: verdict-bearing lines additionally carry ``trace_id``
#       (the candidate's promotion trace, minted by the supervisor) and
#       promoted lines a ``spans`` dict — the per-stage decomposition
#       (``stream_poll_s`` / ``gate_eval_s`` / ``publish_s`` /
#       ``barrier_commit_s`` / ``first_serve_s`` [+ ``deferred_wait_s``])
#       whose values sum to ``promotion_latency_s`` (within clock skew).
#   3 — adversarial gate rung (scenarios/adversary.py): when the rung
#       ran, verdict lines carry ``falsifiers`` (the search's
#       ``Falsifier.record()`` list — scenario, minimal severity, drop
#       vs clean, and the concrete ScenarioParams knob dict) plus
#       ``gate_adversary_compiles`` (the search program's budget-1
#       receipt); new event ``curriculum_updated`` records the
#       supervisor feeding a rejection's falsifiers back into the
#       trainer's schedule (and ``curriculum_update_failed`` when the
#       trainer has no scenario seam to feed).
#   4 — mesh tier (serving/mesh/): ``promoted`` and ``rolled_back``
#       lines carry ``host_count`` (hosts the coordinator's barrier
#       round committed — 1 for a single-host fleet) and
#       ``commit_round`` (the coordinator's monotone round number), so
#       the audit log attributes every swap to the cross-host commit
#       that served it.
#   5 — tenant lanes (serving/tenancy/): EVERY line carries
#       ``model_id`` — the named lane this pipeline promotes into
#       (None for a single-model pipeline). N independent pipelines
#       promoting into one fleet write N logs; the stamp is what lets
#       a merged audit view attribute each verdict to its lane.
PROMOTIONS_SCHEMA = 5

# Schemas the reader accepts. Older lines stay readable forever: the
# reader backfills ``trace_id``/``spans`` (schema 2), ``falsifiers``
# (schema 3), ``host_count``/``commit_round`` (schema 4), and
# ``model_id`` (schema 5) as None.
READABLE_SCHEMAS = (1, 2, 3, 4, 5)


class PromotionLog:
    """Append-only JSONL verdict log. Every line carries ``schema``,
    ``event`` (``promoted`` / ``rejected`` / ``rolled_back`` /
    ``curriculum_updated`` / ...), and ``time`` (epoch seconds); the
    rest is the event's payload. ``model_id`` names the tenant lane
    this log's pipeline promotes into (schema 5) — stamped on every
    line, None for a single-model pipeline."""

    def __init__(
        self, path: str | Path, model_id: str | None = None
    ) -> None:
        self.path = Path(path)
        self.model_id = model_id
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    def append(self, event: str, **fields) -> dict:
        record = {
            "schema": PROMOTIONS_SCHEMA,
            "event": event,
            "time": round(time.time(), 3),
            "model_id": self.model_id,
            **fields,
        }
        line = json.dumps(record)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
        return record

    @staticmethod
    def read(path: str | Path) -> List[dict]:
        """Every record in the log, oldest first. Accepts all
        ``READABLE_SCHEMAS`` — schema-1 lines come back with
        ``trace_id``/``spans`` backfilled to None so readers written
        against schema 2 need no per-line branching. A line stamped
        with an UNKNOWN schema raises: silently misreading a future
        shape is worse than failing loudly."""
        p = Path(path)
        if not p.exists():
            return []
        records: List[dict] = []
        for line in p.read_text().splitlines():
            if not line.strip():
                continue
            rec = json.loads(line)
            schema = rec.get("schema", 1)
            if schema not in READABLE_SCHEMAS:
                raise ValueError(
                    f"promotions.jsonl line has schema {schema!r}; this "
                    f"reader understands {READABLE_SCHEMAS} — upgrade "
                    "the reader before consuming this log"
                )
            if schema < 2:
                rec.setdefault("trace_id", None)
                rec.setdefault("spans", None)
            # Unconditional: schema-3 lines carry `falsifiers` only when
            # the adversarial rung RAN — readers get None, never a
            # KeyError, whichever way the gate was configured.
            rec.setdefault("falsifiers", None)
            # Same discipline for the schema-4 commit attribution:
            # non-swap events (rejections, curriculum updates) never
            # carry them either.
            rec.setdefault("host_count", None)
            rec.setdefault("commit_round", None)
            # Schema 5: pre-tenancy logs are single-model by
            # construction — their lane is the None lane.
            rec.setdefault("model_id", None)
            records.append(rec)
        return records


class Promoter:
    """Publish passing checkpoints into the coordinator-watched
    directory; retract demoted ones."""

    def __init__(self, promoted_dir: str | Path) -> None:
        self.promoted_dir = Path(promoted_dir)
        self.promoted_dir.mkdir(parents=True, exist_ok=True)

    def publish(self, source: str | Path) -> Path:
        """Atomically land ``source`` in the promoted directory under
        its own name. Hardlink when the filesystem allows (zero-copy —
        the trainer's file IS the promoted file), bytewise copy
        otherwise; either way the visible name appears complete-or-not
        via ``os.replace``, the same torn-write invariant as
        ``_write_atomic``."""
        source = Path(source)
        dst = self.promoted_dir / source.name
        tmp = self.promoted_dir / f".{source.name}.tmp"
        tmp.unlink(missing_ok=True)
        try:
            os.link(source, tmp)
        except OSError:  # cross-device / no-hardlink filesystem
            shutil.copyfile(source, tmp)
        os.replace(tmp, dst)
        return dst

    def retract_above(self, step: int) -> List[Path]:
        """Remove every promoted checkpoint with a step strictly above
        ``step`` (the rollback path: a demoted checkpoint must not be
        re-promotable by the coordinator's next poll). Returns what was
        removed."""
        removed: List[Path] = []
        for p in sorted(self.promoted_dir.glob("rl_model_*_steps.msgpack")):
            if checkpoint_step(p) > step:
                p.unlink(missing_ok=True)
                removed.append(p)
        return removed

    def published_steps(self) -> Dict[int, Path]:
        return {
            checkpoint_step(p): p
            for p in self.promoted_dir.glob("rl_model_*_steps.msgpack")
        }
