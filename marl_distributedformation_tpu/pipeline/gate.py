"""PromotionGate: the quality door between training and serving.

Every candidate checkpoint runs through the SAME compiled eval program
(``scenarios.matrix.MatrixProgram`` — model params and scenario params
are traced inputs, so the program compiles exactly once for the life of
the gate; the budget-1 RetraceGuard receipt spans every candidate of an
always-learning run) and is judged on two axes:

- **Clean-return regression** vs the currently-served baseline: a
  candidate whose clean-env ``episode_return_per_agent`` falls more than
  ``clean_tolerance`` (relative) below the served checkpoint's is
  rejected — training divergence, a corrupted file (NaN params evaluate
  to NaN returns, which never pass the finite check), or a genuinely
  worse policy all land here.
- **Severity-rung regression** on the robustness matrix: for each
  configured scenario x severity cell, the candidate may not fall more
  than ``rung_tolerance`` (relative) below the baseline's cell — a
  policy that got better on the clean env by sacrificing robustness is
  caught at the rung that regressed.

The first loadable candidate bootstraps the baseline (there is nothing
served to regress against); thereafter :meth:`PromotionGate.accept`
installs each promoted candidate's already-computed cells as the new
baseline — promotion never re-evaluates anything. ``rebase(step)``
reverts the baseline after a rollback so later candidates are judged
against what is actually serving again.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.eval import episode_length
from marl_distributedformation_tpu.obs import get_registry, get_tracer
from marl_distributedformation_tpu.utils.checkpoint import checkpoint_step

# Cells: {scenario: {"{severity:g}": {metric: float}}}
Cells = Dict[str, Dict[str, Dict[str, float]]]


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """What the gate evaluates and how much regression it tolerates.

    ``adversarial=True`` adds the worst-case rung: every candidate also
    runs the falsifier search (``scenarios.adversary.AdversarySearch`` —
    one more compiled program, built once, budget-1 across all
    candidates), and a falsifier discovered BELOW
    ``adversarial_min_severity`` is a rejection carrying the falsifier's
    concrete params in the verdict — the supervisor feeds those back
    into the trainer's schedule (docs/adversarial.md). Unlike the
    matrix rungs this is an ABSOLUTE floor, not a baseline regression:
    "must survive every family up to severity S" is the robustness
    contract a served policy owes, whoever served before it.
    """

    scenarios: Tuple[str, ...] = ("wind", "sensor_noise")
    severities: Tuple[float, ...] = (0.5, 1.0)
    eval_formations: int = 256
    eval_seed: int = 1234
    deterministic: bool = True
    metric: str = "episode_return_per_agent"
    clean_tolerance: float = 0.05  # relative clean-return slack vs served
    rung_tolerance: float = 0.10  # relative per-cell slack vs served
    # -- adversarial rung (off by default: it costs a second compiled
    # program and generations x population eval cells per candidate) --
    adversarial: bool = False
    adversarial_scenarios: Tuple[str, ...] = ()  # () -> `scenarios`
    adversarial_min_severity: float = 0.5  # falsifier below this rejects
    adversarial_drop_tolerance: float = 0.2
    adversarial_max_severity: float = 1.5
    adversarial_grid: int = 4
    adversarial_generations: int = 3
    adversarial_formations: int = 64
    # -- eval deadline (chaos hardening) ---------------------------------
    # A candidate wedged past this many seconds (a hung device op, an
    # injected wedge) yields a ``gate_timeout`` verdict and the stream
    # moves on — one stuck eval must not stall the always-learning loop
    # forever. None/0 disables the deadline (the compiled program's
    # FIRST eval includes its compile, so size this past the cold
    # compile or run a warmup candidate first).
    gate_timeout_s: Optional[float] = None


@dataclasses.dataclass
class GateVerdict:
    """One candidate's judgment — everything ``promotions.jsonl`` needs.

    ``falsifiers`` is None when the adversarial rung did not run, else
    the search's ``Falsifier.record()`` list (possibly empty) — so a
    rejection carries the exact disturbance params that broke the
    candidate, ready for ``scenarios.from_falsifiers`` (promotions.jsonl
    schema 3)."""

    step: int
    path: str
    passed: bool
    reasons: List[str]  # empty iff passed
    clean: Dict[str, float]
    cells: Cells
    baseline_step: Optional[int]
    eval_compiles: int
    eval_seconds: float
    falsifiers: Optional[List[dict]] = None
    adversary_compiles: int = 0
    # The eval deadline fired: the candidate wedged past gate_timeout_s
    # and was failed WITHOUT a completed eval (reasons[0] carries the
    # ``gate_timeout:`` taxonomy).
    timed_out: bool = False

    def record(self) -> dict:
        """The flat payload logged per candidate (PromotionLog adds
        schema/event/time)."""
        out = {
            "step": self.step,
            "checkpoint": self.path,
            "passed": self.passed,
            "reasons": list(self.reasons),
            "clean": self.clean,
            "cells": self.cells,
            "baseline_step": self.baseline_step,
            "gate_eval_compiles": self.eval_compiles,
            "gate_eval_seconds": round(self.eval_seconds, 4),
        }
        if self.falsifiers is not None:
            out["falsifiers"] = list(self.falsifiers)
            out["gate_adversary_compiles"] = self.adversary_compiles
        if self.timed_out:
            out["gate_timeout"] = True
        return out


def _relative_regression(candidate: float, baseline: float) -> float:
    """Scale-free drop of ``candidate`` below ``baseline`` (positive =
    worse). Denominated on |baseline| with a floor of 1 so a
    near-zero baseline cannot turn noise into infinity."""
    return (baseline - candidate) / max(abs(baseline), 1.0)


def judge_candidate(
    metric: str,
    clean: Dict[str, float],
    cells: Cells,
    baseline_clean: Optional[Dict[str, float]],
    baseline_cells: Optional[Cells],
    clean_tolerance: float,
    rung_tolerance: float,
) -> List[str]:
    """Pure verdict logic: the list of rejection reasons (empty = pass).

    Separated from the gate so the rejection taxonomy is unit-testable
    without a single eval (tests/test_pipeline.py feeds it synthetic
    numbers for every branch).
    """
    reasons: List[str] = []
    outputs = [clean] + [
        m for per_sev in cells.values() for m in per_sev.values()
    ]
    missing = [m for m in outputs if metric not in m]
    if missing and any(m for m in outputs):
        # The eval ran and emitted metrics, just not THIS one: a config
        # typo, not corruption — name the fix, don't blame the params.
        emitted = sorted({k for m in outputs for k in m})
        reasons.append(
            f"gate metric {metric!r} absent from eval output (emitted: "
            f"{', '.join(emitted)}) — check the gate metric config"
        )
        return reasons
    values = [m.get(metric, math.nan) for m in outputs]
    if not all(math.isfinite(v) for v in values):
        reasons.append(
            f"non-finite {metric} in candidate eval (corrupted or "
            "diverged parameters)"
        )
        return reasons  # NaN poisons every comparison below; stop here
    if baseline_clean is None:
        return reasons  # bootstrap: nothing served to regress against
    drop = _relative_regression(
        clean.get(metric, math.nan), baseline_clean.get(metric, math.nan)
    )
    if not math.isfinite(drop) or drop > clean_tolerance:
        reasons.append(
            f"clean {metric} regressed {drop * 100.0:.1f}% vs served "
            f"baseline (tolerance {clean_tolerance * 100.0:.1f}%)"
        )
    for scenario, per_sev in cells.items():
        base_sev = (baseline_cells or {}).get(scenario, {})
        for sev, metrics in per_sev.items():
            base = base_sev.get(sev)
            if base is None:
                continue  # no baseline cell: nothing to regress against
            drop = _relative_regression(
                metrics.get(metric, math.nan), base.get(metric, math.nan)
            )
            if not math.isfinite(drop) or drop > rung_tolerance:
                reasons.append(
                    f"severity rung {scenario}@{sev} {metric} regressed "
                    f"{drop * 100.0:.1f}% vs served baseline (tolerance "
                    f"{rung_tolerance * 100.0:.1f}%)"
                )
    return reasons


def judge_falsifiers(
    falsifiers: List[dict], min_severity: float, metric: str
) -> List[str]:
    """Pure adversarial-rung verdict: rejection reasons for falsifiers
    below the severity floor (empty = the candidate survives every
    searched family up to the floor). Unit-testable without an eval,
    like :func:`judge_candidate`."""
    reasons: List[str] = []
    for falsifier in falsifiers:
        severity = float(falsifier.get("severity", math.nan))
        if not math.isfinite(severity) or severity < min_severity:
            drop = float(falsifier.get("drop", math.nan))
            reasons.append(
                f"adversarial falsifier {falsifier.get('scenario')}"
                f"@{severity:g}: {metric} drops {drop * 100.0:.1f}% vs "
                f"clean below the severity floor {min_severity:g}"
            )
    return reasons


class PromotionGate:
    """Judge candidates against the served baseline with one compiled
    eval program.

    The program is built lazily from the FIRST loadable candidate (the
    checkpoint records its own architecture) and reused for every later
    one; a candidate with a different architecture is a rejection, not a
    recompile (``MatrixProgram.check_params``).
    """

    def __init__(
        self,
        env_params: EnvParams,
        config: GateConfig = GateConfig(),
        device=None,
    ) -> None:
        self.env_params = env_params
        self.config = config
        # Slice assignment (train/sebulba, docs/sebulba.md): pin the
        # gate's compiled programs to this device so candidate evals run
        # beside — not interleaved with — the learner's update stream.
        # None keeps jax's default placement (the Anakin time-share).
        self.device = device
        self.program = None  # scenarios.matrix.MatrixProgram, lazy
        self.adversary = None  # scenarios.adversary.AdversarySearch, lazy
        self._baseline_step: Optional[int] = None  # graftlock: guarded-by=_eval_lock
        self._baseline_clean: Optional[Dict[str, float]] = None  # graftlock: guarded-by=_eval_lock
        self._baseline_cells: Optional[Cells] = None  # graftlock: guarded-by=_eval_lock
        # Serializes eval bodies. The deadline wrapper ABANDONS a
        # wedged eval thread, but CPython cannot kill it — when it
        # wakes it would otherwise race the next candidate's eval on
        # shared gate state (the lazy program/adversary builds would
        # double-compile, breaking the budget-1 receipt). Under the
        # lock a still-wedged gate makes later candidates time out too
        # (honest: the gate IS wedged) until the stuck thread drains.
        self._eval_lock = threading.Lock()
        # Promoted-step history so a rollback can rebase the comparison
        # point without re-evaluating (bounded: serving history is short).
        self._history: Dict[int, Tuple[Dict[str, float], Cells]] = {}  # graftlock: guarded-by=_eval_lock
        self._history_order: List[int] = []  # graftlock: guarded-by=_eval_lock
        self.eval_seconds_total = 0.0  # graftlock: guarded-by=_eval_lock
        self.cells_evaluated = 0  # graftlock: guarded-by=_eval_lock

    # -- evaluation ------------------------------------------------------

    @property
    def baseline_step(self) -> Optional[int]:
        return self._baseline_step

    def evaluate(
        self, path: str | Path, trace_id: Optional[str] = None
    ) -> GateVerdict:
        """Run one candidate through the matrix + regression checks.
        Never raises for a bad candidate — unloadable / wrong-
        architecture / non-finite candidates are failed verdicts with
        the reason recorded. ``trace_id`` labels the eval span (obs/)
        so the gate leg of a promotion trace carries the candidate's
        identity.

        With ``gate_timeout_s`` set, the eval runs on a worker thread
        under a deadline: a candidate wedged past it (hung device op,
        injected wedge) yields a ``gate_timeout`` verdict and the
        stream moves on — the wedged thread is abandoned (CPython
        cannot kill it) and its late result discarded."""
        path = Path(path)
        timeout = self.config.gate_timeout_s
        if not timeout:
            return self._evaluate_inner(path, trace_id)
        box: List[GateVerdict] = []
        worker = threading.Thread(
            target=lambda: box.append(self._evaluate_inner(path, trace_id)),
            name="gate-eval",
            daemon=True,
        )
        worker.start()
        worker.join(float(timeout))
        if box:
            return box[0]
        try:
            step = checkpoint_step(path)
        except ValueError:
            step = -1
        if worker.is_alive():
            reason = (
                f"gate_timeout: eval exceeded gate_timeout_s="
                f"{float(timeout):g}s (wedged candidate; the stream "
                "moves on, the stuck eval thread is abandoned)"
            )
        else:
            # The worker died without producing a verdict — an
            # uncontained (BaseException-grade) kill. Same taxonomy:
            # this candidate never finished its eval.
            reason = (
                "gate_timeout: eval thread died before producing a "
                "verdict (crashed candidate)"
            )
        get_registry().counter("pipeline_gate_timeouts_total").inc()
        get_tracer().incident(
            "gate_timeout", trace_id=trace_id, step=step, path=str(path),
            gate_timeout_s=float(timeout),
        )
        return GateVerdict(
            step=step,
            path=str(path),
            passed=False,
            reasons=[reason],
            clean={},
            cells={},
            baseline_step=self._baseline_step,
            eval_compiles=(
                self.program.compile_count if self.program else 0
            ),
            eval_seconds=float(timeout),
            timed_out=True,
        )

    def _evaluate_inner(
        self, path: Path, trace_id: Optional[str] = None
    ) -> GateVerdict:
        with self._eval_lock:
            return self._evaluate_unlocked(path, trace_id)

    # graftlock: holds=_eval_lock
    def _evaluate_unlocked(
        self, path: Path, trace_id: Optional[str] = None
    ) -> GateVerdict:
        from marl_distributedformation_tpu.compat.policy import LoadedPolicy
        from marl_distributedformation_tpu.scenarios.matrix import (
            MatrixProgram,
        )

        path = Path(path)
        cfg = self.config
        try:
            step = checkpoint_step(path)
        except ValueError as e:
            # Not a checkpoint-shaped filename — unreachable via the
            # stream (regex-filtered) but a direct caller still gets a
            # rejected verdict, not an exception.
            return GateVerdict(
                step=-1,
                path=str(path),
                passed=False,
                reasons=[f"not a checkpoint path: {e!r}"],
                clean={},
                cells={},
                baseline_step=self._baseline_step,
                eval_compiles=(
                    self.program.compile_count if self.program else 0
                ),
                eval_seconds=0.0,
            )
        try:
            # The chaos seam for the whole eval body: a wedge here (on
            # the deadline wrapper's worker thread) exercises
            # gate_timeout_s; a raise is a contained rejected verdict.
            fault_point("gate.eval", path=path)
            pol = LoadedPolicy.from_checkpoint(
                path,
                act_dim=self.env_params.act_dim,
                env_params=self.env_params,
            )
            if self.program is None:
                self.program = MatrixProgram(
                    pol.model,
                    self.env_params,
                    num_formations=cfg.eval_formations,
                    deterministic=cfg.deterministic,
                    seed=cfg.eval_seed,
                    device=self.device,
                )
            t0 = time.perf_counter()
            # The span wraps the compiled MatrixProgram calls from the
            # HOST side (dispatch + drain) — recording happens after the
            # program returns, never inside it (graftlint rule 15).
            with get_tracer().span(
                "gate.matrix_eval", trace_id=trace_id, step=step,
                cells=1 + len(cfg.scenarios) * len(cfg.severities),
            ):
                clean = self.program.evaluate_clean(
                    pol.params, origin=str(path)
                )
                cells = self.program.evaluate_cells(
                    pol.params, cfg.scenarios, cfg.severities,
                    origin=str(path),
                )
            falsifiers = None
            if cfg.adversarial:
                # The adversarial rung: its OWN compiled population
                # program (a different shape than the matrix runner's),
                # built once from the first candidate and budget-1
                # across every later one, like the matrix itself.
                if self.adversary is None:
                    from marl_distributedformation_tpu.scenarios import (
                        AdversaryConfig,
                        AdversarySearch,
                    )

                    self.adversary = AdversarySearch(
                        pol.model,
                        self.env_params,
                        AdversaryConfig(
                            scenarios=(
                                cfg.adversarial_scenarios or cfg.scenarios
                            ),
                            metric=cfg.metric,
                            drop_tolerance=cfg.adversarial_drop_tolerance,
                            max_severity=cfg.adversarial_max_severity,
                            grid=cfg.adversarial_grid,
                            generations=cfg.adversarial_generations,
                            num_formations=cfg.adversarial_formations,
                            seed=cfg.eval_seed,
                            deterministic=cfg.deterministic,
                        ),
                        device=self.device,
                    )
                with get_tracer().span(
                    "gate.adversary_search", trace_id=trace_id, step=step,
                ):
                    search_report = self.adversary.search(
                        pol.params, origin=str(path)
                    )
                falsifiers = search_report["falsifiers"]
        except Exception as e:  # noqa: BLE001 — a bad candidate must
            # never kill the pipeline; it is a rejected verdict.
            return GateVerdict(
                step=step,
                path=str(path),
                passed=False,
                reasons=[f"candidate failed to load/evaluate: {e!r}"],
                clean={},
                cells={},
                baseline_step=self._baseline_step,
                eval_compiles=(
                    self.program.compile_count if self.program else 0
                ),
                eval_seconds=0.0,
            )
        seconds = time.perf_counter() - t0
        self.eval_seconds_total += seconds
        self.cells_evaluated += 1 + len(cfg.scenarios) * len(cfg.severities)
        reasons = judge_candidate(
            cfg.metric,
            clean,
            cells,
            self._baseline_clean,
            self._baseline_cells,
            cfg.clean_tolerance,
            cfg.rung_tolerance,
        )
        if falsifiers is not None:
            reasons.extend(
                judge_falsifiers(
                    falsifiers, cfg.adversarial_min_severity, cfg.metric
                )
            )
        return GateVerdict(
            step=step,
            path=str(path),
            passed=not reasons,
            reasons=reasons,
            clean=clean,
            cells=cells,
            baseline_step=self._baseline_step,
            eval_compiles=self.program.compile_count,
            eval_seconds=seconds,
            falsifiers=falsifiers,
            adversary_compiles=(
                self.adversary.compile_count if self.adversary else 0
            ),
        )

    # -- baseline management ---------------------------------------------

    def accept(self, verdict: GateVerdict, keep_history: int = 8) -> None:
        """Install a PROMOTED candidate's already-computed evals as the
        new comparison baseline (no re-eval, ever). Takes the eval lock:
        an ABANDONED eval thread (deadline wrapper gave up on it) that
        wakes mid-install must not judge against a half-replaced
        baseline — the same wedge hazard the lock already serializes
        between candidate evals."""
        assert verdict.passed, "only promoted candidates become baselines"
        with self._eval_lock:
            self._baseline_step = verdict.step
            self._baseline_clean = verdict.clean
            self._baseline_cells = verdict.cells
            self._history[verdict.step] = (verdict.clean, verdict.cells)
            self._history_order.append(verdict.step)
            while len(self._history_order) > keep_history:
                dropped = self._history_order.pop(0)
                if dropped != self._baseline_step:
                    self._history.pop(dropped, None)

    def rebase(self, step: int) -> None:
        """After a rollback: judge future candidates against the
        checkpoint that is serving AGAIN. A step evicted from the
        bounded history (a demotion cascade longer than
        ``keep_history``) degrades to bootstrap judging — finite
        candidates pass until the next promotion re-establishes a real
        baseline — rather than crashing the control plane. Locked like
        :meth:`accept` (same abandoned-eval race)."""
        with self._eval_lock:
            entry = self._history.get(step)
            if entry is None:
                self._baseline_step = step
                self._baseline_clean = None
                self._baseline_cells = None
                return
            clean, cells = entry
            self._baseline_step = step
            self._baseline_clean = clean
            self._baseline_cells = cells

    # -- observability ---------------------------------------------------

    def device_str(self) -> Optional[str]:
        """The assigned eval device as a stable label (None = default
        placement) — the promotion span breakdown records which slice
        served each gate eval."""
        return str(self.device) if self.device is not None else None

    def eval_steps_per_sec(self) -> float:
        """Gate throughput in formation-env-steps evaluated per second
        (cells x formations x episode length over cumulative eval
        wall-clock) — the bench's ``gate_eval_steps_per_sec``."""
        if self.eval_seconds_total <= 0:
            return 0.0
        steps = (
            self.cells_evaluated
            * self.config.eval_formations
            * episode_length(self.env_params)
        )
        return steps / self.eval_seconds_total
