"""CheckpointStream: tail a trainer's checkpoint directory, cheaply.

The trainer (host-loop or Anakin fused-scan) drops
``rl_model_{steps}_steps.msgpack`` files into ``logs/{name}/`` — each
one written to a dot-prefixed temp name and atomically renamed
(``utils.checkpoint._write_atomic``), so the rename IS the publication
anchor: a discovered file is always complete, a torn write is never
visible (the population sweeps extend the same convention with a
``sweep_state`` anchor written last). The stream therefore never needs
content-level handshakes — it only has to notice new names, in step
order, without re-paying discovery for every historic checkpoint on
every poll (``utils.checkpoint.CheckpointDiscovery`` is the incremental
engine: idle polls are one ``stat``, active polls parse only unseen
names).

``nudge()`` is the push path: the trainer's ``on_checkpoint`` hook
(called on the async writer thread AFTER the rename lands) wakes a
blocked ``wait()`` immediately, so promotion latency is not floored at
the poll interval.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Any, List, Optional

from marl_distributedformation_tpu.chaos.plane import fault_point
from marl_distributedformation_tpu.utils.checkpoint import (
    CheckpointDiscovery,
)


class CheckpointStream:
    """Consuming, step-ordered view of a checkpoint directory.

    Each checkpoint is yielded exactly once, in ascending step order;
    steps at or below the consumed high-water mark are ignored (the
    registry's never-go-backward semantics). ``start_after_step`` skips
    history — e.g. resume a pipeline without re-gating already-judged
    candidates.
    """

    def __init__(
        self,
        log_dir: str | Path,
        poll_interval_s: float = 0.25,
        start_after_step: int = -1,
    ) -> None:
        self.log_dir = Path(log_dir)
        self.poll_interval_s = poll_interval_s
        self._discovery = CheckpointDiscovery(
            self.log_dir, start_after_step=start_after_step
        )
        self._nudge = threading.Event()

    def nudge(self, path: Optional[Any] = None) -> None:
        """Wake a blocked :meth:`wait` now (signature-compatible with
        ``Trainer.on_checkpoint``; the path is advisory — discovery
        stays the single source of truth)."""
        del path
        self._nudge.set()

    def poll(self) -> List[Path]:
        """New checkpoints since the last poll, ascending step order.
        Non-blocking."""
        fault_point("stream.poll")
        return self._discovery.poll_new()

    def wait(self, timeout_s: float) -> List[Path]:
        """Block until at least one new checkpoint appears or
        ``timeout_s`` elapses; returns possibly-empty list. A trainer
        ``nudge`` short-circuits the poll interval."""
        deadline = time.monotonic() + timeout_s
        while True:
            fresh = self.poll()
            if fresh:
                return fresh
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return []
            self._nudge.wait(min(self.poll_interval_s, remaining))
            self._nudge.clear()
