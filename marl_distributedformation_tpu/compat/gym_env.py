"""Single-formation ``gymnasium.Env`` adapter — ecosystem interop.

The reference couples its env to SB3's VecEnv ABC (reference
vectorized_env.py:16-109); ``compat.vec_env`` mirrors that contract. This
module is the other half of interop: ONE formation exposed through the
standard ``gymnasium.Env`` API, so the functional JAX env plugs into any
RL library (and gymnasium tooling like wrappers and the env checker),
treating the whole formation as a single centralized-control agent:

- observation: ``(N, obs_dim)`` Box — every agent's local view;
- action: ``(N, 2)`` Box in [-1, 1], scaled by ``max_speed`` inside
  (the reference adapter's convention, vectorized_env.py:69-70);
- reward: the MEAN per-agent reward (scalar, as gymnasium requires);
- episodes end by truncation at the step limit (the reference's
  timeout-only termination, SURVEY.md Q3); ``terminated`` fires only
  when ``goal_termination`` is enabled with ``strict_parity=False``.

Parity caveat, inherited deliberately: the underlying step auto-resets on
episode end and returns the NEXT episode's first observation with the
terminal reward (the SB3 VecEnv convention the reference trains under,
reference simulate.py:113-116). A gymnasium consumer that bootstraps
from the final observation on truncation sees the same bias the
reference does (Q4); ``info["steps"]`` carries the episode step counter
so callers can tell.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from marl_distributedformation_tpu.env import EnvParams, make_vec_env

try:
    import gymnasium as gym
except ImportError as e:  # pragma: no cover - optional extra
    raise ImportError(
        "compat.gym_env needs gymnasium (pip install "
        "'marl-distributedformation-tpu[gym]')"
    ) from e


class FormationGymEnv(gym.Env):
    """One formation as a ``gymnasium.Env`` (centralized control view)."""

    metadata = {"render_modes": ["human", "rgb_array"], "render_fps": 10}

    def __init__(
        self,
        params: Optional[EnvParams] = None,
        render_mode: Optional[str] = None,
    ) -> None:
        self.params = params or EnvParams()
        n, d = self.params.num_agents, self.params.obs_dim
        # Component ranges: own pos in [0,1], offsets/goal in [-1,1]
        # (SURVEY.md Q10); knn observations additionally carry RAW
        # neighbor indices up to N-1, so their envelope widens — the
        # declared bounds must actually contain observations here
        # (check_env enforces it; the reference's are declarative only).
        high = float(max(1, n - 1)) if self.params.obs_mode == "knn" else 1.0
        self.observation_space = gym.spaces.Box(
            low=-1.0, high=high, shape=(n, d), dtype=np.float32
        )
        self.action_space = gym.spaces.Box(
            low=-1.0, high=1.0, shape=(n, 2), dtype=np.float32
        )
        assert render_mode is None or render_mode in self.metadata[
            "render_modes"
        ], render_mode
        self.render_mode = render_mode
        self._renderer = None
        self._reset_fn, self._step_fn = make_vec_env(self.params, 1)
        self._key = jax.random.PRNGKey(0)
        self._state = None
        self._steps = 0

    # -- gymnasium API ------------------------------------------------

    def reset(
        self,
        *,
        seed: Optional[int] = None,
        options: Optional[dict] = None,
    ) -> Tuple[np.ndarray, Dict[str, Any]]:
        super().reset(seed=seed)
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self._key, k = jax.random.split(self._key)
        self._state, obs = self._reset_fn(k)
        self._steps = 0
        return np.asarray(obs[0], np.float32), {}

    def step(
        self, action: np.ndarray
    ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        assert self._state is not None, "call reset() first"
        act = np.asarray(action, np.float32).reshape(
            1, self.params.num_agents, 2
        )
        self._state, tr = self._step_fn(self._state, jax.numpy.asarray(act))
        self._steps += 1
        # ONE device fetch for the whole transition: per-field np.asarray
        # would pay ~a dozen blocking round trips per step (obs, reward,
        # done, each metric) — ruinous on a tunneled device for exactly
        # the per-step external training loops this adapter serves.
        tr = jax.device_get(tr)
        done = bool(tr.done[0])
        # Timeout-only episodes (Q3) are truncation in gymnasium terms. A
        # true goal termination exists only off-parity — and even there a
        # done at the step limit is still the timeout (formation.py ORs
        # the two conditions), so distinguish by the step counter: the
        # non-strict limit fires at exactly max_steps steps.
        timeout = self._steps >= self.params.max_steps
        terminated = bool(
            done
            and not self.params.strict_parity
            and self.params.goal_termination
            and not timeout
        )
        truncated = done and not terminated
        info: Dict[str, Any] = {
            "steps": self._steps,
            **{k: float(v[0]) for k, v in tr.metrics.items()},
        }
        if done:
            self._steps = 0  # the underlying env auto-reset (see module doc)
        if self.render_mode == "human":
            self.render()
        return (
            np.asarray(tr.obs[0], np.float32),
            float(tr.reward[0].mean()),
            terminated,
            truncated,
            info,
        )

    def render(self):
        if self.render_mode is None:
            return None
        assert self._state is not None, "call reset() before render()"
        if self._renderer is None:
            if self.render_mode == "rgb_array":
                import matplotlib

                matplotlib.use("Agg")
            from marl_distributedformation_tpu.compat.render import (
                FormationRenderer,
            )

            self._renderer = FormationRenderer(
                self.params, title="FormationGymEnv"
            )
        s = self._state
        self._renderer.update(
            np.asarray(s.agents[0]),
            np.asarray(s.goal[0]),
            np.asarray(s.obstacles[0]),
        )
        if self.render_mode == "rgb_array":
            fig = self._renderer.fig
            fig.canvas.draw()
            buf = np.asarray(fig.canvas.buffer_rgba())
            return buf[..., :3].copy()
        # human: update() only moves artists — flush them to the screen
        # (plt.pause runs the GUI event loop one tick, the standard
        # incremental-display idiom).
        import matplotlib.pyplot as plt

        self._renderer.fig.canvas.draw_idle()
        plt.pause(0.001)
        return None

    def close(self) -> None:
        if self._renderer is not None:
            import matplotlib.pyplot as plt

            plt.close(self._renderer.fig)
            self._renderer = None
