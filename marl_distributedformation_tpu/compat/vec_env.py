"""Host-side vectorized-env adapter: numpy in, numpy out.

Mirrors the reference's ``FormationEnv`` VecEnv contract
(vectorized_env.py:16-109) for CPU frontends (playback, teleop): M formations
x N agents flattened to ``num_envs = M*N`` rows, actions in [-1, 1] scaled by
``max_speed`` (vectorized_env.py:69-70), ``done``/``infos`` broadcast per
formation (vectorized_env.py:75-79). The compute path stays the jitted
functional env; this class only converts at the host boundary.

Unlike the reference, ``seed`` works (SURVEY.md Q9) and ``close`` is a no-op
instead of raising (Q4).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from marl_distributedformation_tpu.env import (
    EnvParams,
    action_space,
    make_vec_env,
    observation_space,
)


class FormationVecEnv:
    def __init__(
        self,
        params: EnvParams,
        num_formations: int,
        seed: int = 0,
    ) -> None:
        self.params = params
        self.num_formations = num_formations
        self.num_agents = params.num_agents
        self.num_envs = num_formations * params.num_agents
        self.observation_space = observation_space(params)
        self.action_space = action_space(params)
        self._reset_fn, self._step_fn = make_vec_env(params, num_formations)
        self._key = jax.random.PRNGKey(seed)
        self._state = None
        self.last_metrics: Dict[str, float] = {}

    # -- VecEnv surface (reference vectorized_env.py:52-82) ---------------

    def seed(self, seed: Optional[int] = None) -> None:
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)

    def reset(self) -> np.ndarray:
        self._key, k = jax.random.split(self._key)
        self._state, obs = self._reset_fn(k)
        return np.asarray(obs).reshape(self.num_envs, -1)

    def step(
        self, actions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[dict]]:
        """``actions``: ``(num_envs, 2)`` in [-1, 1] (policy space)."""
        assert self._state is not None, "call reset() first"
        actions = np.asarray(actions, np.float32).reshape(
            self.num_formations, self.num_agents, 2
        )
        self._state, tr = self._step_fn(self._state, jax.numpy.asarray(actions))
        obs = np.asarray(tr.obs).reshape(self.num_envs, -1)
        rewards = np.asarray(tr.reward).reshape(self.num_envs)
        dones = np.repeat(np.asarray(tr.done), self.num_agents)
        self.last_metrics = {
            k: float(np.asarray(v).mean()) for k, v in tr.metrics.items()
        }
        infos: List[dict] = [{} for _ in range(self.num_envs)]  # Q4 parity
        return obs, rewards, dones, infos

    def close(self) -> None:
        pass

    # -- host views for renderers/controllers ------------------------------

    @property
    def state(self):
        return self._state

    def agents_np(self, formation: int = 0) -> np.ndarray:
        return np.asarray(self._state.agents[formation])

    def goal_np(self, formation: int = 0) -> np.ndarray:
        return np.asarray(self._state.goal[formation])

    def obstacles_np(self, formation: int = 0) -> np.ndarray:
        return np.asarray(self._state.obstacles[formation])

    def step_velocities(self, velocity: np.ndarray) -> Tuple[Any, ...]:
        """L0 contract: drive with raw velocities (simulate.py:70), like the
        reference's teleop/baseline-controller frontends (SURVEY.md Q8)."""
        return self.step(
            np.asarray(velocity, np.float32) / self.params.max_speed
        )
