"""Matplotlib renderer for a single formation — the reference's live view
(simulate.py:33-67): world box, blue agent circles with thin ring edges, red
goal circle, green obstacle rectangles that flash red while an agent is
inside them (simulate.py:101-106). Pulls device state to host once per
frame; rendering never touches the compute path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from marl_distributedformation_tpu.env import EnvParams


def obstacle_hits(
    agents: np.ndarray, obstacles: np.ndarray, params: EnvParams
) -> np.ndarray:
    """Per-obstacle collision flag ``(K,) bool``: any agent inside.

    Host-side mirror of the env's containment geometry
    (env/formation.py:_in_obstacle, reduced per obstacle instead of per
    agent): ``parity`` mode uses the reference's lower-left-corner
    ``obstacle_size`` box (SURVEY.md Q2), ``fixed`` mode the centered
    ``2*obstacle_size`` box that matches placement and rendering.
    ``tests/test_compat.py`` pins this against the env's jax implementation.
    """
    if obstacles.shape[0] == 0:
        return np.zeros((0,), dtype=bool)
    if params.obstacle_mode == "parity":
        lo = obstacles[:, None, :]
        hi = lo + params.obstacle_size
    else:  # "fixed"
        lo = obstacles[:, None, :] - params.obstacle_size
        hi = obstacles[:, None, :] + params.obstacle_size
    inside = (lo <= agents[None]) & (agents[None] <= hi)  # (K, N, 2)
    return inside.all(axis=-1).any(axis=1)


class FormationRenderer:
    def __init__(self, params: EnvParams, title: str = "") -> None:
        import matplotlib.pyplot as plt

        self.params = params
        self.fig = plt.figure(
            figsize=(params.width / 100, params.height / 100)
        )
        self.ax = self.fig.add_subplot(111)
        margin = 10  # simulate.py:37
        self.ax.set_xlim(-margin, params.width + margin)
        self.ax.set_ylim(-margin, params.height + margin)
        if title:
            self.ax.set_title(title)
        # World boundary (simulate.py:41).
        self.ax.plot(
            [0, params.width, params.width, 0, 0],
            [0, 0, params.height, params.height, 0],
            color="black",
        )

        self.agent_circles = []
        self.agent_lines = []
        for _ in range(params.num_agents):
            circle = plt.Circle((0, 0), radius=2, color="blue")
            self.agent_circles.append(circle)
            self.ax.add_artist(circle)
            line = plt.Line2D([0, 0], [0, 0], color="blue", linewidth=0.2)
            self.agent_lines.append(line)
            self.ax.add_artist(line)

        self.obstacle_rects = []
        for _ in range(params.num_obstacles):
            # Rendered as a 2*obstacle_size box about the obstacle point
            # (simulate.py:55,129-130) — in "fixed" mode collision matches
            # this geometry; in "parity" mode it deliberately doesn't (Q2).
            rect = plt.Rectangle(
                (0, 0),
                width=2 * params.obstacle_size,
                height=2 * params.obstacle_size,
                color="green",
            )
            self.obstacle_rects.append(rect)
            self.ax.add_artist(rect)

        self.goal_circle = plt.Circle((0, 0), radius=10, color="red")
        self.ax.add_artist(self.goal_circle)

    def update(
        self,
        agents: np.ndarray,
        goal: np.ndarray,
        obstacles: Optional[np.ndarray] = None,
    ) -> None:
        for pos, circle in zip(agents, self.agent_circles):
            circle.center = (pos[0], pos[1])
        ring = np.roll(agents, -1, axis=0)
        for pos, nxt, line in zip(agents, ring, self.agent_lines):
            line.set_data([pos[0], nxt[0]], [pos[1], nxt[1]])
        self.goal_circle.center = (goal[0], goal[1])
        if obstacles is not None and len(self.obstacle_rects) > 0:
            # Collision feedback (simulate.py:101-106): an obstacle turns
            # red while any agent is inside it, green otherwise.
            hits = obstacle_hits(
                np.asarray(agents), np.asarray(obstacles), self.params
            )
            for pos, hit, rect in zip(obstacles, hits, self.obstacle_rects):
                rect.xy = (
                    pos[0] - self.params.obstacle_size,
                    pos[1] - self.params.obstacle_size,
                )
                rect.set_color("red" if hit else "green")

    def draw(self) -> None:
        self.fig.canvas.draw_idle()
