"""Reference-workflow-compatible host-side adapters and frontends."""

from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv  # noqa: F401
from marl_distributedformation_tpu.compat.policy import (  # noqa: F401
    LoadedPolicy,
    load_checkpoint_raw,
)
from marl_distributedformation_tpu.compat.sb3_import import (  # noqa: F401
    import_sb3_checkpoint,
    sb3_state_dict_to_flax,
)
