"""Reference-workflow-compatible host-side adapters and frontends."""

from marl_distributedformation_tpu.compat.vec_env import FormationVecEnv  # noqa: F401
from marl_distributedformation_tpu.compat.policy import (  # noqa: F401
    LoadedPolicy,
    load_checkpoint_raw,
)
