"""Batched ``gymnasium.vector.VectorEnv`` adapter — ecosystem interop.

``compat.gym_env`` exposes ONE formation through ``gymnasium.Env``;
this is the batched half: M formations stepping as one device program
behind the standard ``VectorEnv`` API, so vector-native libraries
(gymnasium wrappers, CleanRL-style loops) drive the jitted JAX env
without ever seeing a Python per-env loop — each "sub-env" is a whole
formation under centralized control, exactly the ``FormationGymEnv``
view.

Autoreset: declared ``SAME_STEP`` (``metadata["autoreset_mode"]``) —
the underlying step auto-resets finished formations and returns the
NEXT episode's first observation with the terminal reward, the SB3
VecEnv convention the reference trains under (reference
simulate.py:113-116). The true final observation is discarded by that
convention (SURVEY.md Q4), so ``infos`` carries NO ``final_obs`` — a
consumer that needs it should bootstrap the way the reference does
(accepting the same bias) or use the single-env adapter with an outer
wrapper. ``infos["steps"]`` has each formation's episode step counter.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from marl_distributedformation_tpu.env import EnvParams, make_vec_env

try:
    import gymnasium as gym
    from gymnasium.vector.utils import batch_space
except ImportError as e:  # pragma: no cover - optional extra
    raise ImportError(
        "compat.gym_vector_env needs gymnasium (pip install "
        "'marl-distributedformation-tpu[gym]')"
    ) from e


class FormationVectorEnv(gym.vector.VectorEnv):
    """M formations as a ``gymnasium.vector.VectorEnv`` (one jitted
    device program per step — no per-env Python loop)."""

    metadata = {
        "autoreset_mode": gym.vector.AutoresetMode.SAME_STEP,
        "render_modes": [],
    }

    def __init__(
        self,
        params: Optional[EnvParams] = None,
        num_envs: int = 16,
    ) -> None:
        self.params = params or EnvParams()
        self.num_envs = int(num_envs)
        n, d = self.params.num_agents, self.params.obs_dim
        high = (
            float(max(1, n - 1)) if self.params.obs_mode == "knn" else 1.0
        )  # knn obs carry raw neighbor indices (see compat.gym_env)
        self.single_observation_space = gym.spaces.Box(
            low=-1.0, high=high, shape=(n, d), dtype=np.float32
        )
        self.single_action_space = gym.spaces.Box(
            low=-1.0, high=1.0, shape=(n, 2), dtype=np.float32
        )
        self.observation_space = batch_space(
            self.single_observation_space, self.num_envs
        )
        self.action_space = batch_space(
            self.single_action_space, self.num_envs
        )
        self._reset_fn, self._step_fn = make_vec_env(
            self.params, self.num_envs
        )
        self._key = jax.random.PRNGKey(0)
        self._state = None
        self._steps = np.zeros(self.num_envs, np.int64)

    # -- gymnasium.vector API -----------------------------------------

    def reset(
        self,
        *,
        seed: Optional[int] = None,
        options: Optional[dict] = None,
    ) -> Tuple[np.ndarray, Dict[str, Any]]:
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self._key, k = jax.random.split(self._key)
        self._state, obs = self._reset_fn(k)
        self._steps[:] = 0
        return np.asarray(obs, np.float32), {}

    def step(
        self, actions: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, dict]:
        assert self._state is not None, "call reset() first"
        act = np.asarray(actions, np.float32).reshape(
            self.num_envs, self.params.num_agents, 2
        )
        self._state, tr = self._step_fn(self._state, jax.numpy.asarray(act))
        # ONE device fetch per step (see compat.gym_env on tunnel RTTs).
        tr = jax.device_get(tr)
        self._steps += 1
        done = np.asarray(tr.done, bool)
        # Timeout-only episodes are truncation (SURVEY.md Q3); a real
        # goal termination exists only off-parity and never at the step
        # limit (formation.py ORs the conditions — compat.gym_env).
        timeout = self._steps >= self.params.max_steps
        terminated = (
            done
            & ~timeout
            & (not self.params.strict_parity)
            & self.params.goal_termination
        )
        truncated = done & ~terminated
        infos: Dict[str, Any] = {
            "steps": self._steps.copy(),
            **{
                k: np.asarray(v, np.float32)
                for k, v in tr.metrics.items()
            },
        }
        self._steps[done] = 0  # those formations auto-reset (module doc)
        return (
            np.asarray(tr.obs, np.float32),
            np.asarray(tr.reward, np.float32).mean(axis=-1),
            terminated,
            truncated,
            infos,
        )

    def close_extras(self, **kwargs: Any) -> None:
        pass
