"""Import stable-baselines3 PPO checkpoints into this framework.

A reference user's trained artifacts are SB3 ``PPO.save`` zips named
``rl_model_{steps}_steps.zip`` (reference vectorized_env.py:124,
visualize_policy.py:31-35). This module converts them into this
framework's checkpoint format so existing policies carry over: playback
(``visualize_policy.py``), evaluation (``evaluate.py``), and warm-start
fine-tuning (``resume=true``) all work on a converted file.

Format facts (SB3 ``save_to_zip_file``): the zip contains ``data`` (JSON
of constructor args), ``policy.pth`` (a torch ``state_dict``), and
optimizer/system entries. For ``'MlpPolicy'`` (ActorCriticPolicy, the
reference's choice, vectorized_env.py:126) the state_dict keys are::

    log_std                                  (act_dim,)
    mlp_extractor.policy_net.{0,2,...}.weight/.bias   pi hidden layers
    mlp_extractor.value_net.{0,2,...}.weight/.bias    vf hidden layers
    action_net.weight/.bias                  pi head
    value_net.weight/.bias                   vf head
    (pi_/vf_)features_extractor.*            Flatten — parameterless

Mapping to :class:`~marl_distributedformation_tpu.models.MLPActorCritic`
(models/mlp.py — the same two separate tanh MLPs): torch ``Linear`` stores
``weight (out, in)``; flax ``Dense`` stores ``kernel (in, out)`` — every
weight transposes. Only torch's zip/pickle reader is needed, so the
import works without stable_baselines3 installed (it is not in this
image); torch itself is required and the loader fails with a clear error
without it.

Shared-trunk ``net_arch`` variants (``mlp_extractor.shared_net.*``, the
pre-1.6 SB3 default) are rejected explicitly — this framework's MLP is
the separate-networks shape the reference trains.
"""

from __future__ import annotations

import io
import re
import zipfile
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import numpy as np

# Deliberately NO jax import anywhere in this module: conversion is pure
# host-side work (torch unpickle -> numpy -> msgpack), and touching
# jax.numpy would initialize the device backend — on a machine whose TPU
# tunnel is down, that turns a file converter into an indefinite hang.

_LINEAR_KEY = re.compile(
    r"^mlp_extractor\.(policy|value)_net\.(\d+)\.(weight|bias)$"
)


def _load_policy_state_dict(path: Path) -> Dict[str, np.ndarray]:
    """Extract ``policy.pth`` from an SB3 zip (or load a bare ``.pth``)
    into plain numpy arrays."""
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in this image
        raise ImportError(
            "sb3_import needs torch to read SB3 .zip/.pth checkpoints"
        ) from e

    # Three on-disk shapes: an SB3 PPO.save zip (has a policy.pth entry),
    # a bare torch state_dict file — which since torch 1.6 is ITSELF a
    # zip (data.pkl + tensor blobs), so zip-ness alone identifies
    # nothing — or a legacy pickle.
    blob = None
    if zipfile.is_zipfile(path):
        with zipfile.ZipFile(path) as zf:
            names = zf.namelist()
            if "policy.pth" in names:
                blob = zf.read("policy.pth")
            elif not any(n.endswith("data.pkl") for n in names):
                raise ValueError(
                    f"{path} is a zip with neither policy.pth (SB3 "
                    f"PPO.save) nor data.pkl (torch state_dict) "
                    f"(entries: {sorted(names)[:8]}...)"
                )
    if blob is not None:
        state = torch.load(
            io.BytesIO(blob), map_location="cpu", weights_only=True
        )
    else:
        state = torch.load(path, map_location="cpu", weights_only=True)
    return {k: v.detach().numpy() for k, v in state.items()}


def sb3_state_dict_to_flax(
    state: Dict[str, np.ndarray],
) -> Tuple[dict, Dict[str, int]]:
    """Map an SB3 ActorCriticPolicy ``state_dict`` onto
    ``MLPActorCritic``'s flax param tree.

    Returns ``({"params": ...}, info)`` where ``info`` records the
    inferred ``obs_dim``, ``act_dim``, and hidden widths.
    """
    if any(k.startswith("mlp_extractor.shared_net") for k in state):
        raise ValueError(
            "SB3 checkpoint uses a shared-trunk net_arch "
            "(mlp_extractor.shared_net.*); only the separate pi/vf "
            "networks of the reference's 'MlpPolicy' are importable"
        )

    # Collect hidden Linear layers per network in module-index order.
    # torch.nn.Sequential interleaves activations, so Linear indices are
    # 0, 2, 4, ... — the sort below restores layer order.
    hidden: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {
        "policy": {},
        "value": {},
    }
    for key, arr in state.items():
        m = _LINEAR_KEY.match(key)
        if m:
            net, idx, part = m.group(1), int(m.group(2)), m.group(3)
            hidden[net].setdefault(idx, {})[part] = arr

    for head in (
        "action_net.weight",
        "action_net.bias",
        "value_net.weight",
        "value_net.bias",
        "log_std",
    ):
        if head not in state:
            raise ValueError(
                f"SB3 checkpoint missing {head!r} — keys: "
                f"{sorted(state)[:12]}..."
            )

    def dense(w: np.ndarray, b: np.ndarray) -> dict:
        return {
            # torch (out, in) -> flax (in, out); ascontiguousarray so the
            # transpose view serializes (msgpack needs C-order buffers)
            "kernel": np.ascontiguousarray(w.T),
            "bias": np.asarray(b),
        }

    params: dict = {}
    widths = []
    for net, prefix in (("policy", "pi"), ("value", "vf")):
        layers = [hidden[net][i] for i in sorted(hidden[net])]
        if not layers:
            raise ValueError(
                f"SB3 checkpoint has no mlp_extractor.{net}_net layers"
            )
        for j, layer in enumerate(layers):
            if "weight" not in layer or "bias" not in layer:
                raise ValueError(
                    f"SB3 checkpoint's mlp_extractor.{net}_net layer {j} "
                    f"is missing {'bias' if 'bias' not in layer else 'weight'}"
                    " — malformed state_dict"
                )
            params[f"{prefix}_{j}"] = dense(layer["weight"], layer["bias"])
        if net == "policy":
            widths = [layer["weight"].shape[0] for layer in layers]
    params["pi_head"] = dense(state["action_net.weight"],
                              state["action_net.bias"])
    params["vf_head"] = dense(state["value_net.weight"],
                              state["value_net.bias"])
    params["log_std"] = np.asarray(state["log_std"]).reshape(-1)

    first_pi = hidden["policy"][min(hidden["policy"])]
    info = {
        "obs_dim": int(first_pi["weight"].shape[1]),
        "act_dim": int(state["action_net.weight"].shape[0]),
        "hidden": tuple(widths),
    }
    return {"params": params}, info


def _steps_from_name(path: Path) -> Optional[int]:
    m = re.search(r"rl_model_(\d+)_steps", path.name)
    return int(m.group(1)) if m else None


def output_path(
    src: Path,
    out_dir: Optional[str | Path] = None,
    num_timesteps: Optional[int] = None,
) -> Path:
    """Where :func:`import_sb3_checkpoint` will write for these inputs."""
    steps = (
        num_timesteps
        if num_timesteps is not None
        else (_steps_from_name(src) or 0)
    )
    base = Path(out_dir) if out_dir is not None else src.parent
    return base / f"rl_model_{steps}_steps.msgpack"


def import_sb3_checkpoint(
    src: str | Path,
    out_dir: Optional[str | Path] = None,
    num_timesteps: Optional[int] = None,
) -> Path:
    """Convert one SB3 ``rl_model_{steps}_steps.zip`` into a framework
    checkpoint next to it (or under ``out_dir``), named so
    ``utils.latest_checkpoint`` discovery finds it.

    The converted file carries policy params only (fresh optimizer state
    on resume — SB3's Adam moments don't map onto optax pytrees, and a
    warm-started fine-tune re-estimates them within a few iterations).

    Single-host warm-start only: multi-host resume goes through
    ``utils.broadcast_restore``, which requires the full learner state
    (opt_state, key) and rejects params-only files loudly. To take an
    imported policy multi-host, fine-tune single-host for one iteration
    first — its save() mints a complete learner checkpoint.
    """
    from flax import serialization

    src = Path(src)
    state = _load_policy_state_dict(src)
    params, info = sb3_state_dict_to_flax(state)
    steps = (
        num_timesteps
        if num_timesteps is not None
        else (_steps_from_name(src) or 0)
    )
    out = output_path(src, out_dir, num_timesteps)
    out.parent.mkdir(parents=True, exist_ok=True)
    target = {
        "policy": "MLPActorCritic",
        "params": params,
        "num_timesteps": steps,
        "sb3_import": {
            "source": src.name,
            "obs_dim": info["obs_dim"],
            "act_dim": info["act_dim"],
            "hidden": list(info["hidden"]),
        },
    }
    out.write_bytes(serialization.msgpack_serialize(target))
    return out


def flax_params_to_sb3_state_dict(params: dict) -> Dict[str, Any]:
    """The reverse mapping: ``MLPActorCritic`` flax params -> a torch
    ``state_dict`` under SB3 ActorCriticPolicy naming.

    Deliberately scoped to the state_dict (a plain ``.pth``), NOT a full
    ``PPO.save`` zip: SB3's ``data`` entry is a version-dependent custom
    serialization we cannot produce faithfully without SB3 installed.
    The state_dict is the stable surface — on the reference stack, load
    with ``model.policy.load_state_dict(torch.load(path))`` after
    constructing ``PPO('MlpPolicy', env, ...)`` as usual. Round-trip
    (export -> import -> identical forward pass) is CI-pinned.
    """
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in this image
        raise ImportError("sb3 export needs torch to write .pth files") from e

    p = params["params"] if "params" in params else params

    def tensor(arr) -> Any:
        # np.array copies: jax/flax leaves surface as READ-ONLY numpy
        # views, which torch.from_numpy warns about (and writing through
        # the tensor would be UB).
        return torch.from_numpy(np.array(arr, dtype=np.float32))

    def linear(name: str) -> Dict[str, Any]:
        return {
            "weight": tensor(np.asarray(p[name]["kernel"]).T),
            "bias": tensor(p[name]["bias"]),
        }

    state: Dict[str, Any] = {"log_std": tensor(p["log_std"])}
    for prefix, net in (("pi", "policy"), ("vf", "value")):
        j = 0
        while f"{prefix}_{j}" in p:
            layer = linear(f"{prefix}_{j}")
            state[f"mlp_extractor.{net}_net.{2 * j}.weight"] = layer["weight"]
            state[f"mlp_extractor.{net}_net.{2 * j}.bias"] = layer["bias"]
            j += 1
        if j == 0:
            raise ValueError(
                f"params carry no {prefix}_0 layer — only MLPActorCritic "
                "checkpoints export to the SB3 MlpPolicy shape"
            )
    head = linear("pi_head")
    state["action_net.weight"], state["action_net.bias"] = (
        head["weight"], head["bias"],
    )
    head = linear("vf_head")
    state["value_net.weight"], state["value_net.bias"] = (
        head["weight"], head["bias"],
    )
    return state


def export_sb3_state_dict(
    src: str | Path, out: Optional[str | Path] = None
) -> Path:
    """Export a framework checkpoint's policy to ``{stem}.sb3.pth``."""
    import torch
    from flax import serialization

    src = Path(src)
    from marl_distributedformation_tpu.utils.checkpoint import (
        msgpack_restore_file,
    )

    # quarantine=False: ``src`` is a CALLER-supplied file, not a
    # trainer-owned checkpoint directory — a read-only conversion must
    # never rename a user's input aside, just fail loudly.
    raw = msgpack_restore_file(src, quarantine=False)
    policy = raw.get("policy", "MLPActorCritic")
    if policy != "MLPActorCritic":
        raise ValueError(
            f"checkpoint policy {policy!r} has no SB3 equivalent; only "
            "MLPActorCritic maps onto 'MlpPolicy'"
        )
    state = flax_params_to_sb3_state_dict(raw["params"])
    out = Path(out) if out is not None else src.with_suffix(".sb3.pth")
    out.parent.mkdir(parents=True, exist_ok=True)
    torch.save(state, out)
    return out


def main(argv: Optional[list] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="Convert SB3 PPO checkpoints (rl_model_*_steps.zip) "
        "to framework checkpoints for playback/eval/fine-tuning — or, "
        "with --export, framework checkpoints back to torch state_dicts "
        "under SB3 MlpPolicy naming."
    )
    ap.add_argument("src", nargs="+", help="SB3 .zip (or bare policy "
                    ".pth); with --export: framework .msgpack checkpoints")
    ap.add_argument("--out-dir", default=None, help="output directory "
                    "(default: next to each source file)")
    ap.add_argument("--steps", type=int, default=None,
                    help="override num_timesteps (default: parsed from "
                    "the rl_model_{steps}_steps filename)")
    ap.add_argument("--export", action="store_true",
                    help="reverse direction: framework checkpoint -> "
                    "{stem}.sb3.pth torch state_dict (load on the "
                    "reference stack via policy.load_state_dict)")
    args = ap.parse_args(argv)
    if args.export:
        if args.steps is not None:
            ap.error("--steps does not apply to --export")
        # Same pre-write collision guard as the import path: two sources
        # with one stem under --out-dir must not silently clobber.
        planned_out: Dict[Path, str] = {}
        for src in args.src:
            dest = (
                Path(args.out_dir) / (Path(src).stem + ".sb3.pth")
                if args.out_dir is not None
                else Path(src).with_suffix(".sb3.pth")
            )
            if dest in planned_out:
                ap.error(
                    f"output collision: {src} and {planned_out[dest]} "
                    f"both map to {dest}"
                )
            planned_out[dest] = src
        for dest, src in planned_out.items():
            out = export_sb3_state_dict(src, dest)
            print(f"{src} -> {out}")
        return
    if args.steps is not None and len(args.src) > 1:
        ap.error("--steps with multiple sources would write every input "
                 "to the same rl_model_{steps}_steps.msgpack")
    # Detect output collisions BEFORE any write (two sources with the same
    # step count under one --out-dir would silently clobber each other).
    planned: Dict[Path, str] = {}
    for src in args.src:
        out = output_path(Path(src), args.out_dir, args.steps)
        if out in planned:
            ap.error(
                f"output collision: {src} and {planned[out]} both map to "
                f"{out} — pass distinct --out-dir per run"
            )
        planned[out] = src
    for out, src in planned.items():
        import_sb3_checkpoint(src, args.out_dir, args.steps)
        print(f"{src} -> {out}")


if __name__ == "__main__":
    main()
