"""Checkpoint-backed policy for playback — the ``PPO.load`` / ``predict``
capability the reference gets from SB3 (visualize_policy.py:35,16).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from marl_distributedformation_tpu.models import MLPActorCritic, distributions


def load_checkpoint_raw(path: str | Path) -> dict:
    """Restore a checkpoint file into nested dicts without a template."""
    return serialization.msgpack_restore(Path(path).read_bytes())


class LoadedPolicy:
    """``predict(obs, deterministic)`` over restored parameters."""

    def __init__(self, params, act_dim: int = 2, seed: int = 0) -> None:
        self.model = MLPActorCritic(act_dim=act_dim)
        self.params = params
        self._key = jax.random.PRNGKey(seed)
        self._apply = jax.jit(self.model.apply)

    @classmethod
    def from_checkpoint(cls, path: str | Path, act_dim: int = 2) -> "LoadedPolicy":
        raw = load_checkpoint_raw(path)
        if "params" not in raw:
            raise ValueError(
                f"{path} does not look like a trainer checkpoint "
                f"(keys: {sorted(raw)})"
            )
        return cls({"params": raw["params"]["params"]}, act_dim=act_dim)

    def predict(
        self, obs: np.ndarray, deterministic: bool = True
    ) -> Tuple[np.ndarray, Optional[tuple]]:
        """SB3 ``predict`` contract: returns ``(actions, state)`` with
        actions clipped to the [-1, 1] action space."""
        mean, log_std, _ = self._apply(self.params, jnp.asarray(obs))
        if deterministic:
            actions = distributions.mode(mean)
        else:
            self._key, k = jax.random.split(self._key)
            actions = distributions.sample(k, mean, log_std)
        return np.asarray(jnp.clip(actions, -1.0, 1.0)), None
