"""Checkpoint-backed policy for playback — the ``PPO.load`` / ``predict``
capability the reference gets from SB3 (visualize_policy.py:35,16).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from marl_distributedformation_tpu.models import (
    CTDEActorCritic,
    GNNActorCritic,
    MLPActorCritic,
    distributions,
)

# Checkpoints record the policy architecture by class name (trainer
# ``_checkpoint_target``); this registry maps it back for playback.
POLICY_REGISTRY = {
    "MLPActorCritic": MLPActorCritic,
    "CTDEActorCritic": CTDEActorCritic,
    "GNNActorCritic": GNNActorCritic,
}


def model_kwargs_for(policy: str, env_params=None) -> dict:
    """Extra constructor arguments a policy needs beyond ``act_dim``,
    derived from the environment configuration (the checkpoint records only
    the architecture name)."""
    if policy == "GNNActorCritic":
        if env_params is None:
            raise ValueError(
                "GNNActorCritic playback needs env_params (for knn_k / "
                "goal_in_obs); pass env_params to from_checkpoint"
            )
        return {"k": env_params.knn_k, "goal_in_obs": env_params.goal_in_obs}
    return {}


def infer_hidden(params: dict, policy: str) -> Optional[tuple]:
    """Infer the policy-tower widths from checkpoint parameters, so
    checkpoints trained with non-default ``hidden_sizes`` (the SB3
    policy_kwargs/net_arch analog, cfg ``hidden_sizes``) restore without
    the caller re-supplying the architecture. The tower layers are named
    ``pi_{i}`` — at the top level for the plain MLP, under ``actor`` for
    the PolicyHead-based CTDE/GNN models. Returns None when no tower is
    found (leave the model's default)."""
    p = params
    if policy in ("CTDEActorCritic", "GNNActorCritic"):
        p = params.get("actor", {})
    widths = []
    i = 0
    while f"pi_{i}" in p:
        kernel = p[f"pi_{i}"].get("kernel")
        if kernel is None:
            return None
        widths.append(int(np.shape(kernel)[-1]))
        i += 1
    return tuple(widths) or None


def load_checkpoint_raw(path: str | Path) -> dict:
    """Restore a checkpoint file into nested dicts without a template.
    Validates the checksum footer: corrupt/truncated files are
    quarantined and raise ``CorruptCheckpointError`` (utils.checkpoint)
    instead of feeding damaged params to a gate or a fleet."""
    from marl_distributedformation_tpu.utils.checkpoint import (
        msgpack_restore_file,
    )

    return msgpack_restore_file(path)


class LoadedPolicy:
    """``predict(obs, deterministic)`` over restored parameters."""

    def __init__(
        self,
        params,
        act_dim: int = 2,
        seed: int = 0,
        policy: str = "MLPActorCritic",
        num_agents: int | None = None,
        model_kwargs: dict | None = None,
    ) -> None:
        if policy not in POLICY_REGISTRY:
            raise ValueError(
                f"unknown policy {policy!r} in checkpoint; known: "
                f"{sorted(POLICY_REGISTRY)}"
            )
        self.model = POLICY_REGISTRY[policy](
            act_dim=act_dim, **(model_kwargs or {})
        )
        self.params = params
        # Formation-level models need the agent axis second-to-last; predict
        # reshapes flat SB3-style (M*N, obs_dim) inputs using num_agents.
        self.per_formation = getattr(self.model, "per_formation", False)
        self.num_agents = num_agents
        self._key = jax.random.PRNGKey(seed)
        self._apply = jax.jit(self.model.apply)

    @classmethod
    def from_checkpoint(
        cls,
        path: str | Path,
        act_dim: int = 2,
        num_agents: int | None = None,
        env_params=None,
    ) -> "LoadedPolicy":
        raw = load_checkpoint_raw(path)
        if "params" not in raw:
            raise ValueError(
                f"{path} does not look like a trainer checkpoint "
                f"(keys: {sorted(raw)})"
            )
        policy = raw.get("policy", "MLPActorCritic")
        if num_agents is None and env_params is not None:
            num_agents = env_params.num_agents
        kwargs = model_kwargs_for(policy, env_params)
        hidden = infer_hidden(raw["params"]["params"], policy)
        if hidden:
            kwargs["hidden"] = hidden
        return cls(
            {"params": raw["params"]["params"]},
            act_dim=act_dim,
            policy=policy,
            num_agents=num_agents,
            model_kwargs=kwargs,
        )

    def predict(
        self, obs: np.ndarray, deterministic: bool = True
    ) -> Tuple[np.ndarray, Optional[tuple]]:
        """SB3 ``predict`` contract: returns ``(actions, state)`` with
        actions clipped to the [-1, 1] action space."""
        obs = jnp.asarray(obs)
        flat_in = None
        if self.per_formation and self.num_agents and obs.ndim == 2:
            # Flat SB3-style (M*N, obs_dim) rows -> (M, N, obs_dim) formations.
            flat_in = obs.shape
            obs = obs.reshape(-1, self.num_agents, obs.shape[-1])
        mean, log_std, _ = self._apply(self.params, obs)
        if flat_in is not None:
            mean = mean.reshape(flat_in[0], -1)
        if deterministic:
            actions = distributions.mode(mean)
        else:
            self._key, k = jax.random.split(self._key)
            actions = distributions.sample(k, mean, log_std)
        return np.asarray(jnp.clip(actions, -1.0, 1.0)), None
