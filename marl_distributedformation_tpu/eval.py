"""Policy evaluation: full-episode returns on the device, no host loop.

The reference has no quantitative evaluation at all — its only policy
assessment is watching ``visualize_policy.py`` animations and wandb curves
(SURVEY.md §4). This module adds the missing capability: roll complete
episodes for M formations entirely inside one jitted ``lax.scan`` and reduce
returns/metrics on-device, so a statistically meaningful evaluation (e.g.
M=1024 formations x 1002 steps) takes well under a second on a TPU chip.

The quantitative bar it enables (VERDICT.md r2 next-#2): compare a learned
policy's mean episode return and final ``avg_dist_to_goal`` against the
scripted potential-field baseline (env/baseline.py, the reference's
``control`` — simulate.py:256-319) on the *same* initial states.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.env.baseline import control
from marl_distributedformation_tpu.envs import spec_for_params

Array = jax.Array

# act_fn(agents (M,N,2), goal (M,2), obstacles (M,K,2), obs (M,N,obs_dim),
#        key) -> velocities (M,N,2)  [RAW velocities — the L0 contract,
# SURVEY.md Q8]. ``key`` is a fresh per-step PRNG key; deterministic
# controllers ignore it, a stochastic policy samples with it (SB3's
# ``evaluate_policy(deterministic=...)`` knob — some trained policies rely
# on their action noise and behave differently under the mode action).
ActFn = Callable[[Array, Array, Array, Array, Array], Array]


def episode_length(params: EnvParams) -> int:
    """Steps needed to cover one full episode from reset.

    Under strict parity an episode is ``max_steps + 2`` steps (the
    reference's off-by-one, SURVEY.md Q1: done fires when
    steps_since_reset > max_steps with the check before the increment).
    """
    return params.max_steps + (2 if params.strict_parity else 0)


def run_episode_metrics(
    key: Array,
    act_fn: ActFn,
    params: EnvParams,
    num_formations: int,
    scenario_params=None,
) -> Dict[str, Array]:
    """Full-episode metric scan — the traceable core shared by the jitted
    ``_run_episodes`` below and the robustness-matrix runner
    (``scenarios/matrix.py``, which threads model params AND scenario
    params as traced inputs so one compiled program serves the whole
    scenario x severity x checkpoint grid).

    ``scenario_params`` (``scenarios.ScenarioParams`` or None) routes the
    env step through the disturbance stack; None is the clean env.

    Env-generic: the environment is resolved from the params *type*
    (``envs.spec_for_params`` — formation params resolve to the legacy
    ``env/formation.py`` functions verbatim, so that path is bitwise
    unchanged; ``PursuitParams`` evaluates pursuit-evasion through the
    same compiled program structure, metrics keys included).
    """
    # Reset uses ``key`` unchanged (NOT a split): recorded eval artifacts
    # compare controllers on identical initial states across runs, so the
    # seed -> initial-state mapping must stay stable. The action-noise
    # stream is folded off it.
    env = spec_for_params(params)
    act_key = jax.random.fold_in(key, 1)
    state = env.reset_batch(key, params, num_formations)
    obs0 = env.obs(state, params)
    T = episode_length(params)

    if scenario_params is None:
        env_step = env.step_batch
    else:
        from marl_distributedformation_tpu.scenarios import (
            scenario_step_batch,
        )

        def env_step(state, vel, params):
            return scenario_step_batch(state, vel, scenario_params, params)

    def body(carry, _):
        state, obs, act_key = carry
        act_key, k = jax.random.split(act_key)
        vel = act_fn(state.agents, state.goal, state.obstacles, obs, k)
        state, tr = env_step(state, vel, params)
        step_out = {
            "reward": tr.reward.mean(),  # mean over formations x agents
            "avg_dist_to_goal": tr.metrics["avg_dist_to_goal"].mean(),
            "ave_dist_to_neighbor": tr.metrics["ave_dist_to_neighbor"].mean(),
            "done": tr.done.sum(),
        }
        return (state, tr.obs, act_key), step_out

    (_, _, _), out = jax.lax.scan(body, (state, obs0, act_key), None, length=T)
    # The step where done fires auto-resets the state BEFORE metrics are
    # computed (the reference's step order, simulate.py:113-117), so the
    # scan's last row reports a fresh random formation. In BOTH parity and
    # non-parity modes done fires on the scan's final row (episode_length
    # accounts for the Q1 off-by-one), so the last in-episode metrics row
    # is T-2.
    last = T - 2
    # Return denomination: per-agent episode return, the quantity SB3's
    # rollout reward tracks (mean step reward x episode length). Rewards
    # are computed on the pre-reset state, so every row counts.
    return {
        "episode_return_per_agent": out["reward"].sum(),
        "mean_step_reward": out["reward"].mean(),
        "final_avg_dist_to_goal": out["avg_dist_to_goal"][last],
        "last100_avg_dist_to_goal": out["avg_dist_to_goal"][
            last - 99 : last + 1
        ].mean(),
        "final_ave_dist_to_neighbor": out["ave_dist_to_neighbor"][last],
        "episodes": out["done"].sum(),
    }


# Jitted wrapper: act_fn/params/num_formations are static (an eval run
# compares a handful of controllers), scenario params ride as traced
# inputs — scenario/severity changes never recompile.
_run_episodes = jax.jit(
    run_episode_metrics,
    static_argnames=("act_fn", "params", "num_formations"),
)


def evaluate(
    act_fn: ActFn,
    params: EnvParams,
    num_formations: int = 1024,
    seed: int = 1234,
    scenario_params=None,
) -> Dict[str, float]:
    """Run one full episode on M formations; returns host-side floats.
    ``scenario_params`` evaluates under a disturbance scenario
    (``scenarios.scenario_params_for(name, severity)``)."""
    out = _run_episodes(
        jax.random.PRNGKey(seed), act_fn, params, num_formations,
        scenario_params,
    )
    return {k: float(v) for k, v in out.items()}


def evaluate_scenario(
    act_fn: ActFn,
    params: EnvParams,
    scenario: str,
    severity: float,
    num_formations: int = 1024,
    seed: int = 1234,
) -> Dict[str, float]:
    """``evaluate`` under a registered scenario by name — unknown names
    fail fast with the registry listing (scenarios/registry.py)."""
    from marl_distributedformation_tpu.scenarios import scenario_params_for

    return evaluate(
        act_fn,
        params,
        num_formations=num_formations,
        seed=seed,
        scenario_params=scenario_params_for(scenario, severity),
    )


def baseline_act_fn(params: EnvParams) -> ActFn:
    """The scripted potential-field controller as an ``ActFn``."""

    def act(agents, goal, obstacles, obs, key):
        del obs, key
        return jax.vmap(control, in_axes=(0, 0, 0, None))(
            agents, goal, obstacles, params
        )

    return act


def policy_act_fn(
    model, model_params, params: EnvParams, deterministic: bool = True
) -> ActFn:
    """A trained actor-critic as an ``ActFn``: the mode action by default,
    or (``deterministic=False``) actions sampled from the policy's Gaussian
    — SB3's ``evaluate_policy(deterministic=...)`` knob. Either way clipped
    to the [-1, 1] action space and scaled by max_speed (the L1 adapter
    semantics, reference vectorized_env.py:69-70).

    The stochastic mode matters: a policy trained with a high entropy
    bonus can RELY on its action noise (e.g. the hetero5 artifact holds
    N=5 ring spacing only through noise — its mode action collapses the
    formation, docs/acceptance/hetero5/), so the mode action alone can
    misrepresent what the policy actually does during training."""
    per_formation = getattr(model, "per_formation", False)

    def act(agents, goal, obstacles, obs, key):
        del agents, goal, obstacles
        m = obs.shape[0]
        if not per_formation:
            flat = obs.reshape(-1, obs.shape[-1])
            mean, log_std, _ = model.apply(model_params, flat)
            mean = mean.reshape(m, -1, mean.shape[-1])
        else:
            mean, log_std, _ = model.apply(model_params, obs)
        a = mean
        if not deterministic:
            from marl_distributedformation_tpu.models import distributions

            a = distributions.sample(key, mean, log_std)
        return params.max_speed * jnp.clip(a, -1.0, 1.0)

    return act


def zero_act_fn() -> ActFn:
    """Do-nothing control — the floor any learned policy must clear."""

    def act(agents, goal, obstacles, obs, key):
        del goal, obstacles, obs, key
        return jnp.zeros_like(agents)

    return act


def evaluate_checkpoint(
    checkpoint_path: str,
    params: EnvParams,
    num_formations: int = 1024,
    seed: int = 1234,
    deterministic: bool = True,
    scenario_params=None,
) -> Dict[str, float]:
    """Restore a trainer checkpoint and evaluate its policy (mode action
    by default; ``deterministic=False`` samples — see ``policy_act_fn``).
    ``scenario_params`` evaluates under a disturbance scenario."""
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy

    pol = LoadedPolicy.from_checkpoint(
        checkpoint_path, act_dim=params.act_dim, env_params=params
    )
    act = policy_act_fn(pol.model, pol.params, params, deterministic)
    return evaluate(
        act, params, num_formations=num_formations, seed=seed,
        scenario_params=scenario_params,
    )
