"""Unified tracing spine: trace-ID propagation, spans, flight recorder.

One observability substrate shared by every subsystem (docs/
observability.md has the span taxonomy and the propagation diagram):

- :class:`~.tracer.Tracer` — lock-cheap per-thread ring buffers of
  spans/events on a monotonic clock, with a process-global registry
  (:func:`get_tracer` / :func:`configure`). Recording sits strictly on
  host-side seams; graftlint rule 15 (``span-in-traced-scope``) rejects
  any span/event call reachable inside a compiled scope, so tracing can
  never perturb the budget-1 compile receipts.
- **Trace-context propagation** — an ``X-Trace-Id`` header accepted and
  echoed by the fleet frontend, carried through
  ``FleetRouter.submit -> MicroBatchScheduler -> engine dispatch``
  (batch spans link the coalesced request IDs), and a pipeline trace ID
  minted per candidate checkpoint that follows it through stream ->
  gate -> publish -> barrier commit -> first served response, so ONE
  trace reconstructs a promotion end to end (``promotions.jsonl``
  schema 2 carries ``trace_id`` + the span decomposition).
- **Exporters** (:mod:`~.export`) — Chrome trace-event JSON
  (Perfetto-loadable, ``scripts/trace_report.py``) and Prometheus text
  exposition (content-negotiated on the fleet's ``GET /v1/metrics``).
- :class:`~.flightrec.FlightRecorder` — incident-triggered last-N
  snapshots (circuit break, rollback trip, wedged-barrier abort,
  scheduler worker death, perf-regression trip) to ``flightrec-*.json``,
  so postmortems don't depend on having had logging enabled.
- :class:`~.metrics.MetricsRegistry` — the live-metrics plane: process-
  global counters/gauges/bounded-reservoir histograms recorded from
  every lane (trainer dispatch loop, pipeline gate, serving fleet) on
  lock-cheap per-thread shards, exposed as one merged Prometheus
  namespace via :class:`~.metrics.TelemetryServer` (``GET /metrics``)
  and the fleet's ``GET /v1/metrics``. graftlint rule 18
  (``metrics-in-traced-scope``) keeps recording off the compiled path.
- :class:`~.sentinel.RegressionSentinel` — compares live registry
  gauges against the newest committed ``BENCH_r*.json`` with a
  tolerance band and trip hysteresis; sustained degradation dumps a
  ``flightrec-perf_regression-*.json`` and an audit line.

This package never imports jax — it is pure host-side bookkeeping and
stays importable from the lint CLI and any frontend process.
"""

from marl_distributedformation_tpu.obs.export import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE,
    chrome_trace,
    escape_label_value,
    prometheus_exposition,
    wants_prometheus,
)
from marl_distributedformation_tpu.obs.flightrec import (  # noqa: F401
    FlightRecorder,
)
from marl_distributedformation_tpu.obs.ledger import (  # noqa: F401
    ProgramLedger,
    ProgramRecord,
    configure_ledger,
    get_ledger,
    load_census,
    set_ledger,
)
from marl_distributedformation_tpu.obs.metrics import (  # noqa: F401
    MetricsRegistry,
    TelemetryServer,
    configure_metrics,
    get_registry,
    set_registry,
)
from marl_distributedformation_tpu.obs.sentinel import (  # noqa: F401
    RegressionSentinel,
    Watch,
    default_watches,
    ledger_watches,
    load_bench_record,
    recovery_watches,
)
from marl_distributedformation_tpu.obs.tracer import (  # noqa: F401
    TRACE_HEADER,
    Event,
    Span,
    Tracer,
    configure,
    get_tracer,
    new_trace_id,
    sanitize_trace_id,
    set_tracer,
)

__all__ = [
    "Event",
    "FlightRecorder",
    "MetricsRegistry",
    "PROMETHEUS_CONTENT_TYPE",
    "ProgramLedger",
    "ProgramRecord",
    "RegressionSentinel",
    "Span",
    "TRACE_HEADER",
    "TelemetryServer",
    "Tracer",
    "Watch",
    "chrome_trace",
    "configure",
    "configure_ledger",
    "configure_metrics",
    "default_watches",
    "escape_label_value",
    "get_ledger",
    "get_registry",
    "get_tracer",
    "ledger_watches",
    "recovery_watches",
    "load_bench_record",
    "load_census",
    "new_trace_id",
    "set_ledger",
    "prometheus_exposition",
    "sanitize_trace_id",
    "set_registry",
    "set_tracer",
    "wants_prometheus",
]
