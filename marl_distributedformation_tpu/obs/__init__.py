"""Unified tracing spine: trace-ID propagation, spans, flight recorder.

One observability substrate shared by every subsystem (docs/
observability.md has the span taxonomy and the propagation diagram):

- :class:`~.tracer.Tracer` — lock-cheap per-thread ring buffers of
  spans/events on a monotonic clock, with a process-global registry
  (:func:`get_tracer` / :func:`configure`). Recording sits strictly on
  host-side seams; graftlint rule 15 (``span-in-traced-scope``) rejects
  any span/event call reachable inside a compiled scope, so tracing can
  never perturb the budget-1 compile receipts.
- **Trace-context propagation** — an ``X-Trace-Id`` header accepted and
  echoed by the fleet frontend, carried through
  ``FleetRouter.submit -> MicroBatchScheduler -> engine dispatch``
  (batch spans link the coalesced request IDs), and a pipeline trace ID
  minted per candidate checkpoint that follows it through stream ->
  gate -> publish -> barrier commit -> first served response, so ONE
  trace reconstructs a promotion end to end (``promotions.jsonl``
  schema 2 carries ``trace_id`` + the span decomposition).
- **Exporters** (:mod:`~.export`) — Chrome trace-event JSON
  (Perfetto-loadable, ``scripts/trace_report.py``) and Prometheus text
  exposition (content-negotiated on the fleet's ``GET /v1/metrics``).
- :class:`~.flightrec.FlightRecorder` — incident-triggered last-N
  snapshots (circuit break, rollback trip, wedged-barrier abort,
  scheduler worker death) to ``flightrec-*.json``, so postmortems don't
  depend on having had logging enabled.

This package never imports jax — it is pure host-side bookkeeping and
stays importable from the lint CLI and any frontend process.
"""

from marl_distributedformation_tpu.obs.export import (  # noqa: F401
    PROMETHEUS_CONTENT_TYPE,
    chrome_trace,
    escape_label_value,
    prometheus_exposition,
    wants_prometheus,
)
from marl_distributedformation_tpu.obs.flightrec import (  # noqa: F401
    FlightRecorder,
)
from marl_distributedformation_tpu.obs.tracer import (  # noqa: F401
    TRACE_HEADER,
    Event,
    Span,
    Tracer,
    configure,
    get_tracer,
    new_trace_id,
    sanitize_trace_id,
    set_tracer,
)

__all__ = [
    "Event",
    "FlightRecorder",
    "PROMETHEUS_CONTENT_TYPE",
    "Span",
    "TRACE_HEADER",
    "Tracer",
    "chrome_trace",
    "configure",
    "escape_label_value",
    "get_tracer",
    "new_trace_id",
    "prometheus_exposition",
    "sanitize_trace_id",
    "set_tracer",
    "wants_prometheus",
]
