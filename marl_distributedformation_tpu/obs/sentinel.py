"""RegressionSentinel: the committed bench record as a live tripwire.

The repo's ``BENCH_r*.json`` records are the performance ground truth —
but until now they were consulted by humans on bench day only. The
sentinel closes that loop: it loads the newest committed record,
compares the live :class:`~.metrics.MetricsRegistry` gauges against the
recorded fields with a tolerance band and ``trip_after``-style
hysteresis (the ``RollbackMonitor`` discipline: one noisy sample must
never page anyone), and on SUSTAINED degradation

- records a ``perf_regression`` incident through the tracer — which
  dumps a ``flightrec-perf_regression-*.json`` flight record with the
  metrics snapshot and the recent span history while the slow period is
  still in the rings, and
- appends an audit line to ``perf_incidents.jsonl`` —

making "slower than the record" an observable incident instead of a
bench-day surprise.

Taxonomy (``missing``) is explicit: a watch whose bench field is absent
from the record, explicitly ``"skipped"`` (a ``BENCH_SKIP_*`` phase),
or non-numeric is recorded as unmeasurable — never a breach, never
silently dropped. A live gauge that has not been recorded yet simply
leaves the streak untouched (a cold process is not evidence of
anything).

This module never imports jax.
"""

from __future__ import annotations

import dataclasses
import json
import re
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from marl_distributedformation_tpu.obs.metrics import (
    MetricsRegistry,
    get_registry,
)
from marl_distributedformation_tpu.obs.tracer import Tracer, get_tracer

# bench.py's explicit not-run marker (check_bench_record.py shares it).
SKIPPED = "skipped"

_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def load_bench_record(
    path: Optional[str | Path] = None, root: Optional[str | Path] = None
) -> Tuple[Dict[str, Any], Optional[Path]]:
    """The newest committed bench record as a flat dict.

    ``path`` pins an explicit file; otherwise the highest-numbered
    ``BENCH_r*.json`` under ``root`` (default: the repo root) wins —
    numeric order, so r10 beats r9. Both the driver wrapper shape
    (``{"parsed": {...}}``) and a bare bench JSON line are accepted.
    Returns ``({}, None)`` when nothing is loadable — the sentinel then
    reports every watch as unmeasurable instead of crashing the process
    it guards."""
    if path is not None:
        candidates = [Path(path)]
    else:
        if root is None:
            root = Path(__file__).resolve().parents[2]
        found = [
            p for p in Path(root).glob("BENCH_r*.json") if _BENCH_RE.match(p.name)
        ]
        candidates = sorted(
            found,
            key=lambda p: int(_BENCH_RE.match(p.name).group(1)),
            reverse=True,
        )
    for candidate in candidates:
        try:
            record = json.loads(Path(candidate).read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(record, dict) and isinstance(
            record.get("parsed"), dict
        ):
            record = record["parsed"]
        if isinstance(record, dict):
            return record, Path(candidate)
    return {}, None


@dataclasses.dataclass(frozen=True)
class Watch:
    """One live-gauge-vs-recorded-field comparison.

    ``direction="min"`` guards throughput (breach when the live value
    falls below ``(1 - tolerance) * recorded``); ``direction="max"``
    guards latency (breach above ``(1 + tolerance) * recorded``).
    ``bench_fields`` is a preference list — the first field present and
    numeric in the record is the reference (the bench's field
    generations: fused_scan beats tuned beats plain)."""

    gauge: str
    bench_fields: Tuple[str, ...]
    direction: str = "min"
    tolerance: float = 0.5

    def __post_init__(self) -> None:
        if self.direction not in ("min", "max"):
            raise ValueError(
                f"direction must be 'min' or 'max', got {self.direction!r}"
            )
        if not self.bench_fields:
            raise ValueError(f"watch {self.gauge!r} names no bench fields")
        if self.tolerance <= 0.0:
            raise ValueError(f"tolerance must be > 0, got {self.tolerance}")


def ledger_watches(tolerance: float = 0.5) -> Tuple[Watch, ...]:
    """Program-ledger guards (obs/ledger.py) against the committed
    record's bench phase-13 fields. Both gauges are deliberately
    RECOVERABLE — the Watch machinery latches while breached and
    re-arms in band, which a lifetime-cumulative value can never do:

    - ``ledger_compile_seconds_max`` (not the total, which
      legitimately grows with every curriculum-swap sampler rebuild
      over a long run): past the record means SOME program got
      materially more expensive to build — an XLA upgrade, an
      accidental program split.
    - ``device_memory_bytes_in_use`` (the instantaneous gauge, judged
      against the committed watermark): sustained residency past the
      recorded peak means the executables + live state no longer fit
      the budget the autoscaler packed against; a transient swap spike
      recovers in band instead of tripping forever.

    Same trip machinery as every other watch: flightrec + audit line."""
    return (
        Watch(
            gauge="ledger_compile_seconds_max",
            bench_fields=("ledger_compile_seconds_max",),
            direction="max",
            tolerance=tolerance,
        ),
        Watch(
            gauge="device_memory_bytes_in_use",
            bench_fields=("device_memory_watermark_bytes",),
            direction="max",
            tolerance=tolerance,
        ),
    )


def recovery_watches(tolerance: float = 1.0) -> Tuple[Watch, ...]:
    """Train-lane recovery guards (train/recovery.py) against the
    committed bench phase-15 field: the live rollback MTTR tail
    (``train_recovery_mttr_seconds_p95`` — the registry histogram's
    percentile gauge, recoverable by construction: a one-off slow
    restore re-arms once faster ones dominate the reservoir) judged
    against the recorded ``recovery_mttr_s``. A sustained breach means
    rollback restores got materially slower than the record — a grown
    checkpoint, a slow disk, a quarantine walk that keeps walking —
    exactly the degradation that turns "self-healing" back into
    downtime. Wide default band: recovery is rare, so samples are few.
    Same flightrec + audit trip machinery as every other watch."""
    return (
        Watch(
            gauge="train_recovery_mttr_seconds_p95",
            bench_fields=("recovery_mttr_s",),
            direction="max",
            tolerance=tolerance,
        ),
    )


def default_watches(tolerance: float = 0.5) -> Tuple[Watch, ...]:
    """The stock lane guards: trainer throughput, gate eval throughput,
    fleet tail latency. Generous default band — committed records are
    often measured on different hardware than the live run; tighten per
    deployment."""
    return (
        Watch(
            gauge="train_env_steps_per_sec",
            bench_fields=(
                "train_env_steps_per_sec_fused_scan",
                "train_env_steps_per_sec_tuned",
                "train_env_steps_per_sec",
            ),
            direction="min",
            tolerance=tolerance,
        ),
        Watch(
            gauge="gate_eval_steps_per_sec",
            bench_fields=("gate_eval_steps_per_sec",),
            direction="min",
            tolerance=tolerance,
        ),
        Watch(
            gauge="latency_p95_ms",
            bench_fields=("serving_fleet_p95_ms",),
            direction="max",
            tolerance=tolerance,
        ),
    )


class _WatchState:
    __slots__ = ("streak", "tripped")

    def __init__(self) -> None:
        self.streak = 0
        self.tripped = False


class RegressionSentinel:
    """Compare live registry gauges against the committed bench record.

    Args:
      watches: the comparisons to run each check.
      record: an explicit bench record dict (tests); otherwise loaded
        from ``record_path`` / the newest committed ``BENCH_r*.json``.
      trip_after: consecutive breaching checks before a watch trips
        (hysteresis — the RollbackMonitor shape).
      audit_dir: directory for ``perf_incidents.jsonl`` (None: no audit
        file, incidents still fire through the tracer).
      registry / tracer: explicit instances (tests); default to the
        process globals, resolved at check time.
    """

    AUDIT_NAME = "perf_incidents.jsonl"

    def __init__(
        self,
        watches: Sequence[Watch] = (),
        record: Optional[Dict[str, Any]] = None,
        record_path: Optional[str | Path] = None,
        bench_root: Optional[str | Path] = None,
        trip_after: int = 3,
        audit_dir: Optional[str | Path] = None,
        registry: Optional[MetricsRegistry] = None,
        tracer: Optional[Tracer] = None,
    ) -> None:
        self.watches = tuple(watches) or default_watches()
        if record is not None:
            self.record, self.record_source = dict(record), None
        else:
            self.record, self.record_source = load_bench_record(
                record_path, root=bench_root
            )
        self.trip_after = max(1, int(trip_after))
        self.audit_path = (
            Path(audit_dir) / self.AUDIT_NAME
            if audit_dir is not None
            else None
        )
        self._registry = registry
        self._tracer = tracer
        self._state: Dict[str, _WatchState] = {
            w.gauge: _WatchState() for w in self.watches
        }
        self.checks_total = 0
        self.trips: List[dict] = []
        # gauge -> reason, for watches that can never breach: the
        # missing-bench-field taxonomy (explicit, not silent).
        self.missing: Dict[str, str] = {}
        # Watches whose live gauge has appeared in at least one checked
        # snapshot — a watch that never shows up here is blind (nothing
        # feeds its gauge), which summary() surfaces explicitly.
        self._observed: set = set()

    # -- reference arithmetic --------------------------------------------

    def reference(self, watch: Watch) -> Optional[Tuple[str, float]]:
        """``(field, recorded_value)`` for the first usable bench field,
        recording the taxonomy for unusable ones."""
        reasons = []
        for field in watch.bench_fields:
            value = self.record.get(field)
            if value is None:
                reasons.append(f"{field}: absent")
                continue
            if value == SKIPPED:
                reasons.append(f"{field}: explicitly skipped (BENCH_SKIP_*)")
                continue
            try:
                v = float(value)
            except (TypeError, ValueError):
                reasons.append(f"{field}: non-numeric ({value!r})")
                continue
            self.missing.pop(watch.gauge, None)
            return field, v
        self.missing[watch.gauge] = "; ".join(reasons) or "no bench fields"
        return None

    @staticmethod
    def _band(watch: Watch, recorded: float) -> float:
        if watch.direction == "min":
            return recorded * (1.0 - watch.tolerance)
        return recorded * (1.0 + watch.tolerance)

    def limit(self, watch: Watch) -> Optional[float]:
        ref = self.reference(watch)
        if ref is None:
            return None
        return self._band(watch, ref[1])

    # -- the check --------------------------------------------------------

    def check(
        self, snapshot: Optional[Dict[str, float]] = None
    ) -> List[dict]:
        """One comparison pass over every watch; returns the incidents
        that TRIPPED on this check (usually empty). A tripped watch
        stays latched (no repeat dumps while the degradation persists)
        and re-arms once it recovers inside the band."""
        registry = self._registry or get_registry()
        if snapshot is None:
            # The default snapshot carries the program ledger's
            # aggregate gauges too, so ledger_watches() work without
            # every caller hand-merging namespaces (an explicit
            # snapshot argument is taken verbatim — tests).
            from marl_distributedformation_tpu.obs.ledger import (
                merge_ledger_snapshot,
            )

            snapshot = merge_ledger_snapshot(registry.snapshot())
        self.checks_total += 1
        tripped_now: List[dict] = []
        for watch in self.watches:
            ref = self.reference(watch)
            if ref is None:
                continue
            live = snapshot.get(watch.gauge)
            if live is None:
                continue  # not yet recorded: no evidence either way
            self._observed.add(watch.gauge)
            field, recorded = ref
            live = float(live)
            limit = self._band(watch, recorded)
            breached = (
                live < limit if watch.direction == "min" else live > limit
            )
            state = self._state[watch.gauge]
            if not breached:
                state.streak = 0
                state.tripped = False  # recovered: re-arm
                continue
            state.streak += 1
            if state.streak < self.trip_after or state.tripped:
                continue
            state.tripped = True
            incident = {
                "gauge": watch.gauge,
                "live": live,
                "bench_field": field,
                "recorded": recorded,
                "limit": limit,
                "direction": watch.direction,
                "tolerance": watch.tolerance,
                "streak": state.streak,
                "bench_record": (
                    str(self.record_source) if self.record_source else None
                ),
            }
            self._trip(incident, snapshot)
            tripped_now.append(incident)
        return tripped_now

    def _trip(self, incident: dict, snapshot: Dict[str, float]) -> None:
        """A sustained regression: flight-record the evidence and write
        the audit line. Never raises — the sentinel observes the system,
        it must not become its failure mode."""
        self.trips.append(incident)
        registry = self._registry or get_registry()
        registry.counter("sentinel_trips_total").inc()
        tracer = self._tracer or get_tracer()
        dump = tracer.incident(
            "perf_regression", metrics_snapshot=dict(snapshot), **incident
        )
        if self.audit_path is None:
            return
        line = dict(incident)
        line["event"] = "perf_regression"
        line["time"] = time.time()
        line["flightrec"] = str(dump) if dump is not None else None
        try:
            self.audit_path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.audit_path, "a") as f:
                f.write(json.dumps(line) + "\n")
        except OSError:
            pass

    # -- reporting --------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        return {
            "sentinel_checks": self.checks_total,
            "sentinel_trips": len(self.trips),
            "sentinel_missing": dict(self.missing),
            # Watches whose live gauge never appeared in any checked
            # snapshot: measurable against the record, but nothing in
            # this process feeds the gauge — a blind watch is reported,
            # never silent.
            "sentinel_never_observed": sorted(
                w.gauge
                for w in self.watches
                if w.gauge not in self._observed
                and w.gauge not in self.missing
            ),
            "sentinel_bench_record": (
                str(self.record_source) if self.record_source else None
            ),
        }
