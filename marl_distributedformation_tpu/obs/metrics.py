"""MetricsRegistry: the live-metrics plane every subsystem records into.

PR 8's tracing spine answers "where did this one request/candidate
spend its time"; this module answers "how fast is the system RIGHT NOW,
and is that normal". One process-global registry of counters, gauges,
and bounded-reservoir histograms, recorded from every lane — the
trainer's dispatch loop, the pipeline's gate, the serving fleet — and
rendered as one merged Prometheus namespace by
:func:`~.export.prometheus_exposition` (the fleet's ``GET /v1/metrics``
and the :class:`TelemetryServer` below share the exporter).

Design constraints, in order — the same discipline as the Tracer:

1. **Never in the compiled path.** Recording happens at host-side
   dispatch seams only; graftlint rule 18 (``metrics-in-traced-scope``)
   statically rejects any registry call reachable inside a jit/scan/
   vmap traced scope, so instrumentation can never perturb a budget-1
   compile receipt.
2. **Lock-cheap.** Each recording thread owns its own shard (plain
   dict/deque mutations are GIL-atomic); the only lock is taken once
   per thread at shard registration and once per ``snapshot()`` merge.
   A serving worker bumping one counter per micro-batch contends with
   nobody.
3. **Bounded memory.** Histograms keep a bounded reservoir of recent
   samples per thread (percentiles are over the recent window, the
   number an operator actually wants) plus exact ``count``/``sum``;
   counters and gauges are one float per (thread, name).

Snapshots are flat ``{name: float}`` dicts — the shape every metrics
object in this repo already emits — with histograms flattened to
``{name}_p50/_p95/_p99/_count/_sum``. ``*_total`` names render as
Prometheus counters, percentile triples fold into ``summary`` families
with ``quantile`` labels (export.py).

The process-global registry mirrors the tracer's:
:func:`get_registry` / :func:`set_registry` /
:func:`configure_metrics`. Disabled, every record call is one attribute
read and a return, so instrumentation stays wired in unconditionally.
This module never imports jax.
"""

from __future__ import annotations

import itertools
import json
import threading
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple


class _HistShard:
    """One thread's slice of one histogram: bounded recent samples plus
    exact lifetime count/sum."""

    __slots__ = ("samples", "count", "sum")

    def __init__(self, reservoir: int) -> None:
        self.samples: deque = deque(maxlen=reservoir)
        self.count = 0
        self.sum = 0.0


class _Shard:
    """One thread's private slice of the registry. Mutated only by its
    owning thread; read (never written) by ``snapshot()``."""

    __slots__ = ("counters", "gauges", "hists", "reservoir")

    def __init__(self, reservoir: int) -> None:
        self.counters: Dict[str, float] = {}
        # name -> (seq, value): the global seq makes last-write-wins
        # well-defined when several threads set the same gauge.
        self.gauges: Dict[str, Tuple[int, float]] = {}
        self.hists: Dict[str, _HistShard] = {}
        self.reservoir = reservoir


class Counter:
    """Monotone accumulator handle. Name it ``*_total`` to render as a
    Prometheus counter; callers may cache the handle or re-mint it per
    call (both are cheap)."""

    __slots__ = ("_registry", "name")

    def __init__(self, registry: "MetricsRegistry", name: str) -> None:
        self._registry = registry
        self.name = name

    def inc(self, n: float = 1.0) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        counters = reg._shard().counters
        counters[self.name] = counters.get(self.name, 0.0) + n


class Gauge:
    """Point-in-time value handle; last write (across all threads) wins
    at snapshot."""

    __slots__ = ("_registry", "name")

    def __init__(self, registry: "MetricsRegistry", name: str) -> None:
        self._registry = registry
        self.name = name

    def set(self, value: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        reg._shard().gauges[self.name] = (next(reg._seq), float(value))


class Histogram:
    """Bounded-reservoir distribution handle; snapshot reports
    p50/p95/p99 over the recent window plus exact count/sum."""

    __slots__ = ("_registry", "name")

    def __init__(self, registry: "MetricsRegistry", name: str) -> None:
        self._registry = registry
        self.name = name

    def observe(self, value: float) -> None:
        reg = self._registry
        if not reg.enabled:
            return
        shard = reg._shard()
        hist = shard.hists.get(self.name)
        if hist is None:
            hist = shard.hists[self.name] = _HistShard(shard.reservoir)
        value = float(value)
        hist.samples.append(value)
        hist.count += 1
        hist.sum += value


class MetricsRegistry:
    """Per-thread metric shards merged at snapshot time.

    Args:
      enabled: master switch; disabled handles are no-ops (one attribute
        read per call), so instrumentation stays wired unconditionally.
      reservoir: recent samples retained per (thread, histogram) —
        percentiles are over this window.
    """

    def __init__(self, enabled: bool = True, reservoir: int = 512) -> None:
        self.enabled = bool(enabled)
        self.reservoir = max(1, int(reservoir))
        self._local = threading.local()
        self._shards_lock = threading.Lock()
        # thread ident -> shard. Read by snapshot().
        self._shards: Dict[int, _Shard] = {}  # graftlock: guarded-by=_shards_lock
        # Dead threads' shards FOLD into these accumulators (on ident
        # recycling, reservoir resize, or the periodic dead-thread sweep
        # in _shard) instead of queueing whole shards: counter totals
        # and histogram count/sum are exact forever — a counter must
        # never go backward no matter how many short-lived writer
        # threads come and go — while memory stays bounded by distinct
        # metric names (x reservoir for the retained recent samples).
        self._retired_counters: Dict[str, float] = {}  # graftlock: guarded-by=_shards_lock
        self._retired_gauges: Dict[str, Tuple[int, float]] = {}  # graftlock: guarded-by=_shards_lock
        self._retired_hist_totals: Dict[str, Tuple[int, float]] = {}  # graftlock: guarded-by=_shards_lock
        self._retired_samples: Dict[str, deque] = {}  # graftlock: guarded-by=_shards_lock
        # Global write sequence for gauge last-write-wins merging.
        # itertools.count.__next__ is GIL-atomic in CPython.
        self._seq = itertools.count()

    # -- recording -------------------------------------------------------

    # graftlock: holds=_shards_lock
    def _fold_retired(self, shard: _Shard) -> None:
        """Fold a dead/displaced shard into the retired accumulators.
        Caller holds ``_shards_lock``."""
        for name, value in shard.counters.items():
            self._retired_counters[name] = (
                self._retired_counters.get(name, 0.0) + value
            )
        for name, seq_value in shard.gauges.items():
            prev = self._retired_gauges.get(name)
            if prev is None or seq_value[0] > prev[0]:
                self._retired_gauges[name] = seq_value
        for name, hist in shard.hists.items():
            count, total = self._retired_hist_totals.get(name, (0, 0.0))
            self._retired_hist_totals[name] = (
                count + hist.count, total + hist.sum
            )
            pool = self._retired_samples.get(name)
            if pool is None or pool.maxlen != self.reservoir:
                pool = deque(pool or (), maxlen=self.reservoir)
                self._retired_samples[name] = pool
            # Recent-window semantics: a short-lived thread's samples
            # (e.g. one checkpoint writer per write) stay visible to
            # percentiles through this bounded pool.
            pool.extend(hist.samples)

    def _shard(self) -> _Shard:
        prev = getattr(self._local, "shard", None)
        if prev is None or prev.reservoir != self.reservoir:
            shard = _Shard(self.reservoir)
            self._local.shard = shard
            ident = threading.get_ident()
            with self._shards_lock:
                old = self._shards.get(ident)
                if old is not None and old is not prev:
                    # Recycled ident: ``old`` belongs to a DEAD thread
                    # (idents are only reused after termination).
                    self._fold_retired(old)
                elif prev is not None:
                    # This thread's own resize.
                    self._fold_retired(prev)
                self._shards[ident] = shard
                # Periodic sweep at the (rare) registration seam: fold
                # shards whose threads are gone but whose idents were
                # never recycled, so _shards cannot grow one dead entry
                # per short-lived thread forever.
                live = {
                    t.ident for t in threading.enumerate()
                }
                for dead in [
                    i for i in self._shards if i not in live and i != ident
                ]:
                    self._fold_retired(self._shards.pop(dead))
            return shard
        return prev

    def counter(self, name: str) -> Counter:
        return Counter(self, name)

    def gauge(self, name: str) -> Gauge:
        return Gauge(self, name)

    def histogram(self, name: str) -> Histogram:
        return Histogram(self, name)

    def record_gauges(self, mapping: Dict[str, Any]) -> None:
        """Fold a flat ``{name: float}`` snapshot (the shape
        ``ServingMetrics``/``FleetMetrics`` already emit) into the
        registry as gauges — the bridge that merges the serving
        families into the one live namespace. Non-numeric values are
        skipped, same tolerance as the exposition renderer."""
        if not self.enabled:
            return
        shard = self._shard()
        for name, value in mapping.items():
            try:
                v = float(value)
            except (TypeError, ValueError):
                continue
            shard.gauges[name] = (next(self._seq), v)

    # -- reading ---------------------------------------------------------

    @staticmethod
    def _percentile(ordered: List[float], q: float) -> float:
        # Nearest-rank on the sorted window (ServingMetrics discipline):
        # cheap, monotone, exact at the tails.
        if not ordered:
            return 0.0
        idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
        return ordered[int(idx)]

    def snapshot(self) -> Dict[str, float]:
        """Merged flat view across every thread's shard: counters sum,
        gauges take the latest write, histograms flatten to
        ``{name}_p50/_p95/_p99/_count/_sum`` over the pooled recent
        samples (pooling raw samples, never averaging per-thread
        percentiles)."""
        with self._shards_lock:
            shards = list(self._shards.values())
            counters = dict(self._retired_counters)
            gauges = dict(self._retired_gauges)
            hists: Dict[str, Tuple[List[float], int, float]] = {
                name: (
                    list(self._retired_samples.get(name, ())),
                    count,
                    total,
                )
                for name, (count, total) in self._retired_hist_totals.items()
            }
        for shard in shards:
            # list()/dict() copies before iterating: the owning thread
            # may still be recording.
            for name, value in list(shard.counters.items()):
                counters[name] = counters.get(name, 0.0) + value
            for name, seq_value in list(shard.gauges.items()):
                prev = gauges.get(name)
                if prev is None or seq_value[0] > prev[0]:
                    gauges[name] = seq_value
            for name, hist in list(shard.hists.items()):
                samples, count, total = hists.get(name, ([], 0, 0.0))
                hists[name] = (
                    samples + list(hist.samples),
                    count + hist.count,
                    total + hist.sum,
                )
        out: Dict[str, float] = {}
        out.update(counters)
        for name, (_, value) in gauges.items():
            out[name] = value
        for name, (samples, count, total) in hists.items():
            ordered = sorted(samples)
            out[f"{name}_p50"] = self._percentile(ordered, 0.50)
            out[f"{name}_p95"] = self._percentile(ordered, 0.95)
            out[f"{name}_p99"] = self._percentile(ordered, 0.99)
            out[f"{name}_count"] = float(count)
            out[f"{name}_sum"] = total
        return out


# ----------------------------------------------------------------------
# Process-global registry
# ----------------------------------------------------------------------

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every instrumented seam resolves at
    call time."""
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the previous
    one."""
    global _default_registry
    previous = _default_registry
    _default_registry = registry
    return previous


def configure_metrics(
    enabled: Optional[bool] = None, reservoir: Optional[int] = None
) -> MetricsRegistry:
    """Re-shape the process-global registry in place (the entry points'
    ``telemetry`` / ``telemetry_reservoir`` knobs)."""
    registry = get_registry()
    if enabled is not None:
        registry.enabled = bool(enabled)
    if reservoir is not None:
        registry.reservoir = max(1, int(reservoir))
    return registry


# ----------------------------------------------------------------------
# TelemetryServer: GET /metrics for non-serving processes
# ----------------------------------------------------------------------


class TelemetryServer:
    """Stdlib HTTP endpoint over the registry, for processes that have
    no fleet frontend (a pipeline run, a bare ``train.py``):

    - ``GET /metrics`` — Prometheus text format 0.0.4 over the merged
      registry snapshot (the exporter the fleet already uses), i.e.
      everything a scraper/autoscaler needs from a training process.
    - ``GET /metrics.json`` — the same snapshot as flat JSON.

    ``extra_snapshot`` (zero-arg callable returning a flat dict) lets a
    caller merge live values computed outside the registry; it is
    re-read per request and failure-isolated — observability never
    takes down the process it observes.
    """

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[MetricsRegistry] = None,
        namespace: str = "marl",
        extra_snapshot: Optional[Callable[[], Dict[str, float]]] = None,
    ) -> None:
        self._registry = registry
        self.namespace = namespace
        self.extra_snapshot = extra_snapshot
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._port = int(port)

    def _snapshot(self) -> Dict[str, float]:
        snap = (self._registry or get_registry()).snapshot()
        # The program ledger's families (program{...} cost/memory/
        # dispatch gauges, device-memory watermark) ride the same
        # merged namespace. Lazy import: ledger.py imports this module
        # for its dispatch histograms.
        from marl_distributedformation_tpu.obs.ledger import (
            merge_ledger_snapshot,
        )

        merge_ledger_snapshot(snap)
        if self.extra_snapshot is not None:
            try:
                snap.update(self.extra_snapshot())
            except Exception:  # noqa: BLE001 — a broken extra source
                pass  # must not break the scrape of the registry itself
        return snap

    def start(self) -> "TelemetryServer":
        if self._server is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args: Any) -> None:  # quiet server
                pass

            def do_GET(self) -> None:  # noqa: N802 — stdlib handler API
                from marl_distributedformation_tpu.obs.export import (
                    PROMETHEUS_CONTENT_TYPE,
                    prometheus_exposition,
                )

                if self.path == "/metrics":
                    body = prometheus_exposition(
                        outer._snapshot(), namespace=outer.namespace
                    ).encode()
                    ctype = PROMETHEUS_CONTENT_TYPE
                elif self.path == "/metrics.json":
                    body = json.dumps(outer._snapshot()).encode()
                    ctype = "application/json"
                else:
                    body = json.dumps(
                        {"error": f"unknown path {self.path}"}
                    ).encode()
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="telemetry-server",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}/metrics"

    def stop(self) -> None:
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
