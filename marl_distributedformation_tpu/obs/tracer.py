"""Tracer: the low-overhead tracing spine every subsystem shares.

Design constraints, in order:

1. **Never in the compiled path.** Spans are recorded at host-side seams
   only — scheduler dispatch, reload commit, gate eval — and the recorder
   itself performs no device work, no host callbacks, no jax import.
   graftlint rule 15 (``span-in-traced-scope``) enforces this statically:
   a ``tracer.span``/``event`` call reachable inside a jit/scan/vmap
   traced scope is a lint error, so the spine stays budget-1-compatible
   by construction.
2. **Lock-cheap.** Each recording thread owns its own bounded ring
   buffer (``collections.deque(maxlen=...)`` — appends are GIL-atomic);
   the only lock is taken once per thread, at ring registration. A
   serving worker recording one span per micro-batch contends with
   nobody.
3. **Bounded memory.** Rings cap at ``ring_size`` records per thread;
   old spans fall off the back. The :class:`~.flightrec.FlightRecorder`
   exists precisely because the ring is a window, not an archive —
   incidents snapshot it before it scrolls away.

Identity: a **trace ID** is an opaque hex string minted once per logical
operation (one HTTP request, one checkpoint's promotion journey) and
carried explicitly through every layer — the ``X-Trace-Id`` header on
the wire (``serving/fleet/frontend.py``), a ``trace_id=`` kwarg in
process. Spans record the ID they were given; exporters
(``obs/export.py``) group by it.

Timestamps are monotonic (``time.perf_counter``) so intervals are
immune to wall-clock steps; the tracer keeps an epoch<->monotonic
anchor pair so exporters can place spans on the wall clock (and so a
span can be back-dated to a file mtime, e.g. the pipeline's
``stream_poll`` stage).

The **process-global registry** is the default tracer: ``get_tracer()``
returns it, ``configure(...)`` re-shapes it in place (enabled flag, ring
size, flight-recorder attachment), and every instrumented subsystem
resolves it at call time — tests can swap in a private
:class:`Tracer` via ``set_tracer`` and restore the old one.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import threading
import time
import uuid
from collections import deque
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

# The wire spelling of the trace identity (serving/fleet/frontend.py
# accepts and echoes it; clients may send their own).
TRACE_HEADER = "X-Trace-Id"

# Trace IDs are sanitized at trust boundaries: hex-ish, bounded length.
_MAX_TRACE_ID_LEN = 64
# Explicit ASCII set — str.isalnum() would admit non-ASCII Unicode
# alphanumerics, which are not URL/log/filename-safe.
_TRACE_ID_SAFE = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_."
)


def new_trace_id() -> str:
    """Mint an opaque 16-hex-char trace ID (collision-safe at the rates
    a single process mints them)."""
    return uuid.uuid4().hex[:16]


def sanitize_trace_id(raw: Optional[str]) -> Optional[str]:
    """A caller-supplied trace ID, defanged: stripped, length-bounded,
    restricted to URL/log-safe characters. Anything unusable -> None
    (the caller mints a fresh one)."""
    if not raw:
        return None
    raw = raw.strip()[:_MAX_TRACE_ID_LEN]
    if not raw or not all(c in _TRACE_ID_SAFE for c in raw):
        return None
    return raw


@dataclasses.dataclass
class Span:
    """One closed interval on one thread. ``t0``/``t1`` are monotonic
    (``perf_counter``); exporters convert via the tracer's anchor."""

    name: str
    t0: float
    t1: float
    trace_id: Optional[str] = None
    attrs: Optional[Dict[str, Any]] = None

    kind = "span"


@dataclasses.dataclass
class Event:
    """One instant on one thread (same clock as :class:`Span`)."""

    name: str
    t: float
    trace_id: Optional[str] = None
    attrs: Optional[Dict[str, Any]] = None

    kind = "event"


class Tracer:
    """Per-thread ring buffers of spans/events plus the epoch anchor.

    Args:
      enabled: master switch. Disabled, every record call is one
        attribute read and a return — the tracer can stay wired into hot
        host paths unconditionally.
      ring_size: per-thread bound on retained records (spans + events).
      flightrec: optional :class:`~.flightrec.FlightRecorder`;
        :meth:`incident` dumps through it.
    """

    def __init__(
        self,
        enabled: bool = True,
        ring_size: int = 4096,
        flightrec: Optional[Any] = None,
    ) -> None:
        self.enabled = bool(enabled)
        self.ring_size = max(1, int(ring_size))
        self.flightrec = flightrec
        self.incidents_total = 0
        self._local = threading.local()
        self._rings_lock = threading.Lock()
        # thread ident -> (thread name, ring). Read by snapshot().
        self._rings: Dict[int, Tuple[str, deque]] = {}  # graftlock: guarded-by=_rings_lock
        # Rings displaced by ident recycling: CPython reuses a dead
        # thread's ident, and a later thread registering under it must
        # not erase the dead thread's retained records — a flight dump
        # after a worker death exists to read exactly that history.
        # Bounded: at most maxlen dead rings of ring_size records each.
        self._retired: deque = deque(maxlen=8)  # graftlock: guarded-by=_rings_lock
        # Epoch<->monotonic anchor, sampled together at construction.
        self.epoch_anchor = time.time()
        self.mono_anchor = time.perf_counter()

    # -- clock -----------------------------------------------------------

    def mono_to_epoch(self, t: float) -> float:
        return self.epoch_anchor + (t - self.mono_anchor)

    def epoch_to_mono(self, t: float) -> float:
        return self.mono_anchor + (t - self.epoch_anchor)

    # -- recording -------------------------------------------------------

    def _ring(self) -> deque:
        prev = getattr(self._local, "ring", None)
        if prev is None or prev.maxlen != self.ring_size:
            ring = deque(maxlen=self.ring_size)
            self._local.ring = ring
            thread = threading.current_thread()
            with self._rings_lock:
                old = self._rings.get(thread.ident or 0)
                if old is not None and old[1] is not prev:
                    # Recycled ident: ``old`` belongs to a DEAD thread
                    # (idents are only reused after termination), not to
                    # this thread's own resize — keep its records.
                    self._retired.append(old)
                self._rings[thread.ident or 0] = (thread.name, ring)
            return ring
        return prev

    @contextlib.contextmanager
    def span(
        self, name: str, trace_id: Optional[str] = None, **attrs: Any
    ) -> Iterator[None]:
        """Record the wall time of the ``with`` body as one span.
        Disabled tracers yield immediately — the body runs either way."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._ring().append(
                Span(name, t0, time.perf_counter(), trace_id, attrs or None)
            )

    def add_span(
        self,
        name: str,
        t0: float,
        t1: float,
        trace_id: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Record an explicit interval (monotonic endpoints) — for spans
        whose start predates the tracer call site, e.g. a checkpoint's
        on-disk wait back-dated to its mtime (``epoch_to_mono`` converts)."""
        if not self.enabled:
            return
        self._ring().append(Span(name, t0, t1, trace_id, attrs or None))

    def event(
        self, name: str, trace_id: Optional[str] = None, **attrs: Any
    ) -> None:
        if not self.enabled:
            return
        self._ring().append(
            Event(name, time.perf_counter(), trace_id, attrs or None)
        )

    def incident(
        self, trigger: str, trace_id: Optional[str] = None, **context: Any
    ) -> Optional[Path]:
        """An operational event worth a postmortem — circuit break,
        rollback trip, wedged-barrier abort, worker death. Records an
        event (when enabled) and, when a flight recorder is attached,
        dumps the last-N records to disk REGARDLESS of the enabled flag
        (a disabled tracer has an empty ring, but the trigger context
        still lands). Returns the dump path, if any. Never raises —
        observability must not take down the path it observes."""
        self.incidents_total += 1
        try:
            self.event(f"incident.{trigger}", trace_id=trace_id, **context)
            if self.flightrec is not None:
                return self.flightrec.dump(
                    trigger, self, trace_id=trace_id, context=context
                )
        except Exception:  # noqa: BLE001
            pass
        return None

    # -- reading ---------------------------------------------------------

    def snapshot(self, last_n: Optional[int] = None) -> List[dict]:
        """All retained records across every thread's ring, as flat
        dicts with epoch timestamps, oldest first. ``last_n`` keeps only
        the newest N after the merge (the flight-recorder window)."""
        with self._rings_lock:
            rings = [(name, list(ring)) for name, ring in self._retired]
            rings += [
                (name, list(ring)) for name, ring in self._rings.values()
            ]
        out: List[dict] = []
        for thread_name, records in rings:
            for r in records:
                if r.kind == "span":
                    rec = {
                        "kind": "span",
                        "name": r.name,
                        "thread": thread_name,
                        "trace_id": r.trace_id,
                        "t0": self.mono_to_epoch(r.t0),
                        "t1": self.mono_to_epoch(r.t1),
                        "duration_s": r.t1 - r.t0,
                    }
                else:
                    rec = {
                        "kind": "event",
                        "name": r.name,
                        "thread": thread_name,
                        "trace_id": r.trace_id,
                        "t0": self.mono_to_epoch(r.t),
                    }
                if r.attrs:
                    rec["attrs"] = dict(r.attrs)
                out.append(rec)
        out.sort(key=lambda r: r["t0"])
        if last_n is not None:
            out = out[-last_n:]
        return out

    def dump(self, path: str | Path) -> Path:
        """Write every retained record to ``path`` as JSON (the input
        shape ``scripts/trace_report.py`` renders). Atomic via
        tmp+rename, same torn-write discipline as checkpoints."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f".{path.name}.tmp")
        payload = {
            "format": "marl-obs-spans",
            "version": 1,
            "time": time.time(),
            "records": self.snapshot(),
        }
        tmp.write_text(json.dumps(payload))
        tmp.replace(path)
        return path


# ----------------------------------------------------------------------
# Process-global registry
# ----------------------------------------------------------------------

_default_tracer = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer every instrumented seam resolves at
    call time."""
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-global tracer (tests); returns the previous one."""
    global _default_tracer
    previous = _default_tracer
    _default_tracer = tracer
    return previous


def configure(
    enabled: Optional[bool] = None,
    ring_size: Optional[int] = None,
    flightrec_dir: Optional[str] = None,
    flightrec_last_n: int = 512,
) -> Tracer:
    """Re-shape the process-global tracer in place (the entry points'
    ``obs_trace`` / ``obs_ring_size`` / ``obs_flightrec`` knobs).
    ``flightrec_dir`` attaches a :class:`~.flightrec.FlightRecorder`
    writing under that directory; ``flightrec_dir=None`` leaves any
    existing recorder in place (pass the empty string to detach)."""
    tracer = get_tracer()
    if enabled is not None:
        tracer.enabled = bool(enabled)
    if ring_size is not None:
        tracer.ring_size = max(1, int(ring_size))
    if flightrec_dir == "":
        tracer.flightrec = None
    elif flightrec_dir is not None:
        from marl_distributedformation_tpu.obs.flightrec import (
            FlightRecorder,
        )

        tracer.flightrec = FlightRecorder(
            flightrec_dir, last_n=flightrec_last_n
        )
    return tracer
