"""Exporters: Chrome trace-event JSON and Prometheus text exposition.

Two read-side formats over the same data:

- :func:`chrome_trace` turns a tracer snapshot (``Tracer.snapshot()`` /
  a ``Tracer.dump`` file's ``records``) into the Chrome trace-event
  format Perfetto and ``chrome://tracing`` load — one lane (tid) per
  recording thread, complete-events (``ph: "X"``) for spans, instants
  (``ph: "i"``) for events, trace IDs and attrs in ``args``. Timestamps
  are epoch microseconds, so a file produced here merges cleanly
  alongside ``TraceWindow``'s XLA captures in the same viewer session.
- :func:`prometheus_exposition` renders any flat ``{name: float}``
  snapshot (the shape every metrics object in this repo already emits)
  as Prometheus text format 0.0.4: ``# TYPE`` lines, ``_total`` keys as
  counters, everything else as gauges, ``replica{i}_*`` keys folded into
  one metric with a ``replica`` label, label values escaped per the
  exposition spec. ``serving/fleet/frontend.py`` serves it from
  ``GET /v1/metrics`` under content negotiation (JSON stays the
  default).
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional, Tuple

# ----------------------------------------------------------------------
# Chrome trace events
# ----------------------------------------------------------------------


def chrome_trace(
    records: Iterable[dict], process_name: str = "marl-obs"
) -> dict:
    """Chrome trace-event JSON (object form) from tracer snapshot
    records. Unknown/malformed records are skipped, not fatal — a
    partially-scrolled ring must still render."""
    lanes: Dict[str, int] = {}
    events: List[dict] = []

    def lane(thread: str) -> int:
        if thread not in lanes:
            lanes[thread] = len(lanes) + 1
        return lanes[thread]

    for rec in records:
        try:
            name = str(rec["name"])
            tid = lane(str(rec.get("thread", "main")))
            ts = float(rec["t0"]) * 1e6
            args = dict(rec.get("attrs") or {})
            if rec.get("trace_id"):
                args["trace_id"] = rec["trace_id"]
            if rec.get("kind") == "span":
                dur = max(0.0, float(rec["t1"]) - float(rec["t0"])) * 1e6
                events.append(
                    {
                        "name": name,
                        "ph": "X",
                        "ts": ts,
                        "dur": dur,
                        "pid": 1,
                        "tid": tid,
                        "args": args,
                    }
                )
            else:
                events.append(
                    {
                        "name": name,
                        "ph": "i",
                        "s": "t",
                        "ts": ts,
                        "pid": 1,
                        "tid": tid,
                        "args": args,
                    }
                )
        except (KeyError, TypeError, ValueError):
            continue
    meta: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "args": {"name": process_name},
        }
    ]
    for thread, tid in lanes.items():
        meta.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": thread},
            }
        )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


# ----------------------------------------------------------------------
# Prometheus text exposition (format 0.0.4)
# ----------------------------------------------------------------------

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_REPLICA_KEY = re.compile(r"^replica(\d+)_(.+)$")
# Tenant-lane keys (serving/tenancy): ``model_{id}__{metric}`` folds
# into a ``model_{metric}`` family with a ``model`` label — per-tenant
# series are one label dimension on one family, not a key explosion
# per lane (the PR-9 label-folding discipline). The delimiter is the
# FIRST double underscore: lane names forbid ``__``
# (tenancy/directory.py), so the split is unambiguous whatever the
# metric remainder contains.
_MODEL_KEY = re.compile(r"^model_(.+?)__(.+)$")
# Per-rung serving gauges (fleet/metrics.py): rung size + inference
# dtype (+ engine kind, where the key carries one — both kinds can
# serve the same rung, so e.g. compile receipts need the attribution)
# become labels, so "which rungs are sharded / bf16 / compiled" is one
# queryable family, not a key explosion. Kind-keyed first: the plain
# pattern would swallow "sharded_compiles" as the metric name.
_RUNG_KIND_KEY = re.compile(
    r"^rung(\d+)_(f32|bf16)_(replicated|sharded)_(.+)$"
)
_RUNG_KEY = re.compile(r"^rung(\d+)_(f32|bf16)_(.+)$")
# Percentile triples — the registry's histogram snapshot keys
# (``{name}_p50``) and the serving metrics' latency keys
# (``latency_p50_ms``) — fold into ONE ``summary``-typed family with a
# ``quantile`` label instead of three ad-hoc gauge names (the same
# naming discipline the rung gauges got in PR 9).
_QUANTILE_KEY = re.compile(r"^(.+)_p(50|95|99)(_(?:ms|us|s))?$")
_QUANTILES = {"50": "0.5", "95": "0.95", "99": "0.99"}
# Program-ledger keys (obs/ledger.py): ``program_{key}_{field}`` folds
# into a ``program_{field}`` family with a ``program`` label — one
# queryable family per cost/memory/timing fact across every compiled
# executable, instead of a key explosion per program. The field
# alternation is the ledger's closed suffix set, so the split is
# unambiguous whatever the program key contains.
_PROGRAM_KEY = re.compile(
    r"^program_(.+)_("
    r"flops|bytes_accessed|argument_bytes|output_bytes|temp_bytes|"
    r"alias_bytes|generated_code_bytes|trace_seconds|lower_seconds|"
    r"compile_seconds|first_dispatch_seconds|traces_total|"
    r"dispatches_total|dispatch_seconds_(?:p(?:50|95|99)|count|sum)"
    r")$"
)
_PROGRAM_QUANTILE = re.compile(r"^dispatch_seconds_p(50|95|99)$")
_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(key: str, namespace: str) -> str:
    name = _NAME_OK.sub("_", f"{namespace}_{key}" if namespace else key)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(value: str) -> str:
    """Label-value escaping per the exposition format: backslash, double
    quote, and newline."""
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _render_value(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


def prometheus_exposition(
    snapshot: Dict[str, float],
    namespace: str = "marl",
    labels: Optional[Dict[str, str]] = None,
) -> str:
    """Render a flat float snapshot as Prometheus text format.

    ``replica{i}_{metric}`` keys fold into one ``{metric}`` family with
    a ``replica="i"`` label (per-replica series belong under one metric
    name, not N names); ``model_{id}__{metric}`` keys (tenant lanes,
    serving/tenancy) fold into a ``model_{metric}`` family with a
    ``model`` label; ``rung{B}_{dtype}_{metric}`` keys fold into a
    ``rung_{metric}`` family with ``rung``/``dtype`` labels (the
    serving ladder's shard/bf16 gauges); ``{metric}_p50/_p95/_p99``
    percentile triples (registry histograms, serving latency keys) fold
    into one ``summary``-typed ``{metric}`` family with ``quantile``
    labels. ``*_total`` keys are typed ``counter``, the rest ``gauge``.
    Non-numeric values are skipped — a snapshot is allowed to carry
    annotations without breaking the scrape."""
    base_labels = [
        (k, str(v)) for k, v in sorted((labels or {}).items())
    ]
    # metric name -> (type, [(label pairs, value), ...]) preserving the
    # first-seen order of families.
    families: Dict[str, Tuple[str, List[Tuple[List[Tuple[str, str]], float]]]] = {}
    for key, value in snapshot.items():
        try:
            v = float(value)
        except (TypeError, ValueError):
            continue
        m = _REPLICA_KEY.match(key)
        model = _MODEL_KEY.match(key)
        rung_kind = _RUNG_KIND_KEY.match(key)
        rung = _RUNG_KEY.match(key)
        quantile = _QUANTILE_KEY.match(key)
        program = _PROGRAM_KEY.match(key)
        if program:
            field = program.group(2)
            extra = [("program", program.group(1))]
            pq = _PROGRAM_QUANTILE.match(field)
            if pq:
                metric = "program_dispatch_seconds"
                extra.append(("quantile", _QUANTILES[pq.group(1)]))
                quantile = pq  # summary-typed family
            else:
                metric = f"program_{field}"
                quantile = None
        elif model:
            rest = model.group(2)
            extra = [("model", model.group(1))]
            mq = _QUANTILE_KEY.match(rest)
            if mq:
                # A per-lane percentile triple composes both folds:
                # one summary family, model AND quantile labels.
                metric = "model_" + mq.group(1) + (mq.group(3) or "")
                extra.append(("quantile", _QUANTILES[mq.group(2)]))
                quantile = mq
            else:
                metric = f"model_{rest}"
                quantile = None
        elif m:
            metric, extra = m.group(2), [("replica", m.group(1))]
        elif rung_kind:
            metric = f"rung_{rung_kind.group(4)}"
            extra = [
                ("dtype", rung_kind.group(2)),
                ("kind", rung_kind.group(3)),
                ("rung", rung_kind.group(1)),
            ]
        elif rung:
            metric = f"rung_{rung.group(3)}"
            extra = [("dtype", rung.group(2)), ("rung", rung.group(1))]
        elif quantile:
            metric = quantile.group(1) + (quantile.group(3) or "")
            extra = [("quantile", _QUANTILES[quantile.group(2)])]
        else:
            metric, extra = key, []
        name = _metric_name(metric, namespace)
        if quantile and not (m or rung_kind or rung):
            kind = "summary"
        elif metric.endswith("_total"):
            kind = "counter"
        else:
            kind = "gauge"
        fam = families.setdefault(name, (kind, []))
        fam[1].append((base_labels + extra, v))
    lines: List[str] = []
    for name, (kind, series) in families.items():
        lines.append(f"# TYPE {name} {kind}")
        for pairs, v in series:
            if pairs:
                rendered = ",".join(
                    f'{k}="{escape_label_value(v_)}"' for k, v_ in pairs
                )
                lines.append(f"{name}{{{rendered}}} {_render_value(v)}")
            else:
                lines.append(f"{name} {_render_value(v)}")
    return "\n".join(lines) + "\n"


def wants_prometheus(accept_header: Optional[str]) -> bool:
    """Content negotiation for ``GET /v1/metrics``: Prometheus text only
    when the client PREFERS it (``text/plain`` or an openmetrics type
    outranking ``application/json`` by q-value in ``Accept``);
    bare/absent/wildcard Accept keeps the JSON default, so every
    existing client is untouched. Media ranges are parsed, not
    substring-matched — a JSON client sending a compound header like
    ``application/json, text/plain, */*`` (axios's default) still gets
    JSON; ties go to the JSON default."""
    if not accept_header:
        return False
    prom_q = 0.0
    json_q = 0.0
    for media_range in accept_header.lower().split(","):
        parts = media_range.split(";")
        mtype = parts[0].strip()
        q = 1.0
        for param in parts[1:]:
            k, _, v = param.partition("=")
            if k.strip() == "q":
                try:
                    q = float(v.strip())
                except ValueError:
                    q = 0.0
        if mtype in ("text/plain", "application/openmetrics-text"):
            prom_q = max(prom_q, q)
        elif mtype == "application/json":
            json_q = max(json_q, q)
    return prom_q > 0.0 and prom_q > json_q
