"""FlightRecorder: incident-triggered snapshots of the tracing ring.

The tracer's per-thread rings are a sliding window — perfect for live
export, useless for a postmortem that starts an hour after the incident.
The flight recorder closes that gap the way avionics do: when something
operationally notable happens (circuit break, rollback trip,
wedged-barrier abort, scheduler worker death — the ``Tracer.incident``
triggers), the last-N spans/events across every thread are written to
``{out_dir}/flightrec-{trigger}-{seq}.json`` immediately, so the
reconstruction does not depend on anyone having had logging enabled or
a scrape running at the time.

Dumps are atomic (tmp + rename), bounded in count (oldest pruned), and
failure-silent — a full disk during an incident must not add a second
incident.
"""

from __future__ import annotations

import json
import re
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

_DUMP_RE = re.compile(r"^flightrec-.+-(\d+)\.json$")


class FlightRecorder:
    """Write last-N tracer records to disk on demand.

    Args:
      out_dir: directory dumps land in (created on first dump).
      last_n: newest records kept per dump, merged across threads.
      max_files: dumps retained; older ones are pruned so a flapping
        replica cannot fill the disk with identical snapshots.
    """

    def __init__(
        self,
        out_dir: str | Path,
        last_n: int = 512,
        max_files: int = 16,
    ) -> None:
        self.out_dir = Path(out_dir)
        self.last_n = max(1, int(last_n))
        self.max_files = max(1, int(max_files))
        self.dumps_total = 0
        # Resume the sequence past any dumps already on disk: a restarted
        # process (the normal continuous-learning lifecycle) must never
        # overwrite a previous run's postmortem files, and _prune's
        # oldest-first ordering must keep meaning oldest.
        existing = self.dumps()
        self._seq = (
            int(_DUMP_RE.match(existing[-1].name).group(1))
            if existing
            else 0
        )
        self._lock = threading.Lock()

    def dump(
        self,
        trigger: str,
        tracer: Any,
        trace_id: Optional[str] = None,
        context: Optional[Dict[str, Any]] = None,
    ) -> Optional[Path]:
        """Snapshot ``tracer``'s rings under this trigger. Returns the
        dump path, or None when the write failed (never raises)."""
        safe_trigger = re.sub(r"[^A-Za-z0-9_\-]", "_", str(trigger))[:64]
        try:
            records = tracer.snapshot(last_n=self.last_n)
        except Exception:  # noqa: BLE001 — a broken tracer still dumps context
            records = []
        payload = {
            "format": "marl-obs-flightrec",
            "version": 1,
            "trigger": str(trigger),
            "time": time.time(),
            "trace_id": trace_id,
            "context": _jsonable(context or {}),
            "records": records,
        }
        with self._lock:
            self._seq += 1
            path = self.out_dir / f"flightrec-{safe_trigger}-{self._seq:04d}.json"
            try:
                self.out_dir.mkdir(parents=True, exist_ok=True)
                tmp = path.with_name(f".{path.name}.tmp")
                tmp.write_text(json.dumps(payload))
                tmp.replace(path)
                self.dumps_total += 1
                self._prune()
            except OSError:
                return None
        return path

    def dumps(self) -> List[Path]:
        """Existing dump files, oldest first (sequence order)."""
        try:
            found = [
                p
                for p in self.out_dir.iterdir()
                if _DUMP_RE.match(p.name)
            ]
        except OSError:
            return []
        return sorted(
            found, key=lambda p: int(_DUMP_RE.match(p.name).group(1))
        )

    def _prune(self) -> None:
        existing = self.dumps()
        for stale in existing[: max(0, len(existing) - self.max_files)]:
            stale.unlink(missing_ok=True)


def _jsonable(context: Dict[str, Any], depth: int = 2) -> Any:
    """Best-effort JSON-safe copy of incident context (bounded dict/
    list nesting preserved — the sentinel attaches a whole metrics
    snapshot, the chaos invariant checkers attach armed/fired fault
    schedules as lists of dicts — reprs for anything exotic; the dump
    must always serialize)."""
    out: Dict[str, Any] = {}
    for k, v in context.items():
        out[str(k)] = _jsonable_value(v, depth)
    return out


def _jsonable_value(v: Any, depth: int) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict) and depth > 0:
        return _jsonable(v, depth - 1)
    if isinstance(v, (list, tuple)) and depth > 0:
        converted = [_jsonable_value(e, depth - 1) for e in v]
        if all(
            isinstance(e, (str, int, float, bool, dict)) or e is None
            for e in converted
        ):
            return converted
    return repr(v)
