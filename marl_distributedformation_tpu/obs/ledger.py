"""ProgramLedger: per-executable cost, memory, and dispatch attribution.

The repo's whole performance story rests on a handful of compiled
programs — the train step, the fused chunk, the sweep chunks, the gate's
MatrixProgram, the adversary population program, the serving rungs — yet
until now nothing recorded what those programs *cost*: the tracing spine
(PR 8) times host seams and the metrics plane (PR 11) counts lanes, but
both are blind below the dispatch boundary. This module is the census
below it: one process-global ledger into which every compile site
registers its executable at lowering time, with

- **static facts** from the compiled executable's ``cost_analysis()`` /
  ``memory_analysis()`` — flops, bytes accessed, argument/output/temp/
  alias/generated-code bytes (present-or-explicitly-unavailable: the
  record says which analysis source produced them, or why none could);
- **build timings** — trace / MLIR-lowering / backend-compile wall
  seconds (attributed per program via ``jax.monitoring`` events) plus
  the first-dispatch wall;
- **live dispatch-latency histograms** per program, recorded at the
  existing host dispatch seams (the same per-thread-sharded reservoir
  machinery as the MetricsRegistry — this ledger owns a private one);
- a **device-memory watermark** gauge sampled at drain/swap boundaries.

Registration is automatic wherever a budget-1 RetraceGuard receipt
already exists: :func:`analysis.guards.ledgered_jit` wraps the guard
seam, detects each new compilation, and registers here — zero calls at
the individual subsystems beyond swapping ``jax.jit(guard.wrap(f))``
for ``ledgered_jit(f, guard)``. The AOT serving path registers its
explicitly lowered/compiled executables through
:func:`analysis.guards.register_aot_program`.

Design constraints, in order — the Tracer/MetricsRegistry discipline:

1. **Never in the compiled path.** graftlint rule 20
   (``ledger-record-in-traced-scope``) statically rejects any ledger
   call reachable inside a jit/scan/vmap traced scope.
2. **One attribute read when disabled.** Every record call checks
   ``enabled`` first and returns; instrumentation stays wired in
   unconditionally.
3. **Zero jax imports in the record path.** This module never imports
   jax — the jax-touching extraction glue lives in ``analysis/guards.py``
   and hands over plain floats/strings.

Read sides: :meth:`ProgramLedger.snapshot` (flat ``{name: float}``,
merged into the one Prometheus namespace as ``program{...}``-labeled
families by ``obs/export.py``), :meth:`ProgramLedger.census` (the
structured record ``scripts/program_report.py`` renders and
``scripts/check_bench_record.py --census`` diffs against a committed
copy), and the RegressionSentinel's ``ledger_watches`` over the
aggregate gauges.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from marl_distributedformation_tpu.obs.metrics import MetricsRegistry

# Census file schema (scripts/program_report.py and
# check_bench_record.py --census parse this).
CENSUS_SCHEMA = 1

# The cost/memory fact fields a record may carry. Order matters: it is
# the column order of the census and the unambiguous suffix set the
# Prometheus exporter uses to split ``program_{key}_{field}`` keys.
FACT_FIELDS = (
    "flops",
    "bytes_accessed",
    "argument_bytes",
    "output_bytes",
    "temp_bytes",
    "alias_bytes",
    "generated_code_bytes",
)
TIMING_FIELDS = (
    "trace_seconds",
    "lower_seconds",
    "compile_seconds",
    "first_dispatch_seconds",
)
# How the cost/memory facts were obtained. "executable": claimed from
# the backend's live compiled executable (full facts, zero extra
# compiles); "aot": an explicitly lowered+compiled jax.stages.Compiled
# (the sharded serving path — also full facts); "lowered": pre-compile
# HLO estimates only (flops/bytes, no memory footprint — the fallback
# when the backend exposes no executable handle); "unavailable": this
# backend/version yields neither, and ``analysis_error`` says why.
ANALYSIS_SOURCES = ("executable", "aot", "lowered", "unavailable")

_KEY_OK = "abcdefghijklmnopqrstuvwxyz0123456789_"


def sanitize_key(text: str) -> str:
    """A ledger/Prometheus-safe program key: lowercase ``[a-z0-9_]``."""
    out = []
    for ch in str(text).lower():
        out.append(ch if ch in _KEY_OK else "_")
    key = "".join(out).strip("_") or "program"
    while "__" in key:
        key = key.replace("__", "_")
    return key


class ProgramRecord:
    """One compiled executable's ledger entry (plain-Python facts)."""

    __slots__ = (
        "key",
        "dispatch_key",
        "name",
        "subsystem",
        "fingerprint",
        "donate_argnums",
        "backend",
        "created_unix",
        "traces",
        "analysis_source",
        "analysis_error",
        "timings",
        "facts",
    )

    def __init__(
        self,
        key: str,
        dispatch_key: str,
        name: str,
        subsystem: str,
        fingerprint: str,
        donate_argnums: Tuple[int, ...],
        backend: str,
        analysis_source: str,
        analysis_error: Optional[str],
        timings: Dict[str, float],
        facts: Dict[str, float],
    ) -> None:
        self.key = key
        self.dispatch_key = dispatch_key
        self.name = name
        self.subsystem = subsystem
        self.fingerprint = fingerprint
        self.donate_argnums = tuple(donate_argnums)
        self.backend = backend
        self.created_unix = time.time()
        self.traces = 1
        self.analysis_source = analysis_source
        self.analysis_error = analysis_error
        self.timings = dict(timings)
        self.facts = dict(facts)

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "key": self.key,
            "dispatch_key": self.dispatch_key,
            "name": self.name,
            "subsystem": self.subsystem,
            "fingerprint": self.fingerprint,
            "donate_argnums": list(self.donate_argnums),
            "backend": self.backend,
            "created_unix": self.created_unix,
            "traces": self.traces,
            "analysis_source": self.analysis_source,
            "analysis_error": self.analysis_error,
        }
        for field in TIMING_FIELDS:
            out[field] = self.timings.get(field)
        for field in FACT_FIELDS:
            out[field] = self.facts.get(field)
        return out


class ProgramLedger:
    """The process-global program census.

    Args:
      enabled: master switch; disabled, every record call is one
        attribute read and a return.
      reservoir: recent dispatch-latency samples retained per
        (thread, program) — the percentile window.
    """

    def __init__(self, enabled: bool = True, reservoir: int = 256) -> None:
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # key -> record, registration order preserved (dict semantics).
        self._entries: Dict[str, ProgramRecord] = {}  # graftlock: guarded-by=_lock
        # Dispatch-latency histograms ride a PRIVATE MetricsRegistry:
        # same per-thread shards, same dead-thread folding, zero new
        # concurrency code. Always-enabled internally — the gate is
        # this ledger's own ``enabled``.
        self._metrics = MetricsRegistry(
            enabled=True, reservoir=max(1, int(reservoir))
        )
        # dispatch_key -> (histogram name, counter name): the hot path
        # avoids two f-string builds per dispatch.
        # _dispatch_names stays unannotated: the dispatch hot path
        # writes it lock-free, and racing writers store an identical
        # tuple for the same key (benign by construction).
        self._dispatch_names: Dict[str, Tuple[str, str]] = {}
        self._watermark_bytes = 0.0  # graftlock: guarded-by=_lock
        self._memory_bytes = 0.0  # graftlock: guarded-by=_lock
        self._watermark_samples = 0  # graftlock: guarded-by=_lock

    # -- registration (once per compile — lock is fine) -------------------

    def register(
        self,
        *,
        name: str,
        subsystem: str,
        fingerprint: str = "",
        donate_argnums: Tuple[int, ...] = (),
        backend: str = "",
        timings: Optional[Dict[str, float]] = None,
        facts: Optional[Dict[str, float]] = None,
        analysis_source: str = "unavailable",
        analysis_error: Optional[str] = None,
        dispatch_key: Optional[str] = None,
    ) -> Optional[str]:
        """Register one compiled executable; returns its ledger key
        (None when disabled). Facts/timings are plain floats — the
        jax-side extraction lives in ``analysis/guards.py``."""
        if not self.enabled:
            return None
        if analysis_source not in ANALYSIS_SOURCES:
            analysis_source = "unavailable"
        base = sanitize_key(f"{subsystem}_{name}")
        dkey = sanitize_key(dispatch_key) if dispatch_key else base
        clean_facts = {
            k: float(v)
            for k, v in (facts or {}).items()
            if k in FACT_FIELDS and v is not None
        }
        clean_timings = {
            k: float(v)
            for k, v in (timings or {}).items()
            if k in TIMING_FIELDS and v is not None
        }
        with self._lock:
            key = base
            n = 1
            while key in self._entries:
                n += 1
                key = f"{base}_{n}"
            self._entries[key] = ProgramRecord(
                key=key,
                dispatch_key=dkey,
                name=str(name),
                subsystem=str(subsystem),
                fingerprint=str(fingerprint),
                donate_argnums=tuple(donate_argnums or ()),
                backend=str(backend),
                analysis_source=analysis_source,
                analysis_error=analysis_error,
                timings=clean_timings,
                facts=clean_facts,
            )
        return key

    # -- hot paths --------------------------------------------------------

    def dispatch(self, dispatch_key: str, seconds: float) -> None:
        """One program dispatch's host-side wall seconds (the existing
        dispatch seam — ledgered_jit calls this around every jitted
        call). Lock-free: per-thread histogram shards."""
        if not self.enabled:
            return
        names = self._dispatch_names.get(dispatch_key)
        if names is None:
            names = (
                f"program_{dispatch_key}_dispatch_seconds",
                f"program_{dispatch_key}_dispatches_total",
            )
            self._dispatch_names[dispatch_key] = names
        self._metrics.histogram(names[0]).observe(seconds)
        self._metrics.counter(names[1]).inc()

    def record_watermark(self, bytes_in_use: float) -> None:
        """Device-memory sample (drain/swap boundaries); the watermark
        is the max ever seen by this ledger."""
        if not self.enabled:
            return
        v = float(bytes_in_use)
        with self._lock:
            self._memory_bytes = v
            self._watermark_samples += 1
            if v > self._watermark_bytes:
                self._watermark_bytes = v

    # -- read side --------------------------------------------------------

    def entries(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._entries.values())

    @property
    def watermark_bytes(self) -> float:
        return self._watermark_bytes

    @staticmethod
    def _compile_seconds(rec: ProgramRecord) -> float:
        v = rec.timings.get("compile_seconds")
        if v is None:
            # First-dispatch wall when event attribution was
            # unavailable — an upper bound rather than a silent zero.
            v = rec.timings.get("first_dispatch_seconds", 0.0)
        return float(v)

    def compile_seconds_total(self) -> float:
        """Sum of attributed backend-compile seconds over every entry."""
        return sum(self._compile_seconds(rec) for rec in self.entries())

    def compile_seconds_max(self) -> float:
        """The most expensive single program's compile seconds — the
        sentinel's compile-time watch gauge. Unlike the cumulative
        total (which legitimately grows with every curriculum-swap
        sampler rebuild over a long run), the max only moves when SOME
        program got materially more expensive to build — a recoverable,
        regression-shaped signal."""
        return max(
            (self._compile_seconds(rec) for rec in self.entries()),
            default=0.0,
        )

    def snapshot(self) -> Dict[str, float]:
        """Flat ``{name: float}`` view for the merged Prometheus
        namespace: per-program static facts + build timings under
        ``program_{key}_{field}`` (folded into ``program``-labeled
        families by the exporter), the pooled dispatch-latency
        histograms, and the ledger aggregates."""
        if not self.enabled:
            return {}
        out: Dict[str, float] = {}
        entries = self.entries()
        for rec in entries:
            prefix = f"program_{rec.key}_"
            for field in FACT_FIELDS:
                v = rec.facts.get(field)
                if v is not None:
                    out[prefix + field] = v
            for field in TIMING_FIELDS:
                v = rec.timings.get(field)
                if v is not None:
                    out[prefix + field] = v
            out[prefix + "traces_total"] = float(rec.traces)
        out.update(self._metrics.snapshot())
        out["ledger_programs_total"] = float(len(entries))
        out["ledger_compile_seconds_total"] = self.compile_seconds_total()
        out["ledger_compile_seconds_max"] = self.compile_seconds_max()
        flops = [
            rec.facts["flops"] for rec in entries if "flops" in rec.facts
        ]
        if flops:
            out["ledger_flops_total"] = float(sum(flops))
        if self._watermark_samples:
            out["device_memory_bytes_in_use"] = self._memory_bytes
            out["device_memory_watermark_bytes"] = self._watermark_bytes
        return out

    def census(self) -> Dict[str, Any]:
        """The structured program census: every entry's full record plus
        the dispatch-latency summaries and the ledger totals — the
        artifact a chip window commits beside BENCH (see
        ``check_bench_record.py --census``)."""
        entries = self.entries()
        hists = self._metrics.snapshot()
        programs = []
        for rec in entries:
            d = rec.as_dict()
            h = f"program_{rec.dispatch_key}_dispatch_seconds"
            for q in ("p50", "p95", "p99", "count", "sum"):
                d[f"dispatch_seconds_{q}"] = hists.get(f"{h}_{q}")
            d["dispatches_total"] = hists.get(
                f"program_{rec.dispatch_key}_dispatches_total"
            )
            programs.append(d)
        return {
            "schema": CENSUS_SCHEMA,
            "created_unix": time.time(),
            "enabled": self.enabled,
            "programs": programs,
            "totals": {
                "programs": len(entries),
                "traces": sum(rec.traces for rec in entries),
                "compile_seconds": self.compile_seconds_total(),
                "flops": sum(
                    rec.facts.get("flops", 0.0) for rec in entries
                ),
                "watermark_bytes": (
                    self._watermark_bytes
                    if self._watermark_samples
                    else None
                ),
            },
        }

    def write_census(self, path: "str | Path") -> Path:
        """Atomic census dump (``logs/{name}/program_ledger.json`` —
        the file the census diff gate and program_report read)."""
        target = Path(path)
        target.parent.mkdir(parents=True, exist_ok=True)
        tmp = target.with_name("." + target.name + ".tmp")
        tmp.write_text(json.dumps(self.census(), indent=2, sort_keys=True))
        tmp.replace(target)
        return target


# ----------------------------------------------------------------------
# Process-global ledger
# ----------------------------------------------------------------------

_default_ledger = ProgramLedger()


def get_ledger() -> ProgramLedger:
    """The process-global ledger every compile seam resolves at call
    time."""
    return _default_ledger


def set_ledger(ledger: ProgramLedger) -> ProgramLedger:
    """Swap the process-global ledger (tests); returns the previous
    one."""
    global _default_ledger
    previous = _default_ledger
    _default_ledger = ledger
    return previous


def configure_ledger(
    enabled: Optional[bool] = None, reservoir: Optional[int] = None
) -> ProgramLedger:
    """Re-shape the process-global ledger in place (the entry points'
    ``ledger`` / ``ledger_reservoir`` knobs)."""
    ledger = get_ledger()
    if enabled is not None:
        ledger.enabled = bool(enabled)
    if reservoir is not None:
        ledger._metrics.reservoir = max(1, int(reservoir))
    return ledger


def merge_ledger_snapshot(base: Dict[str, Any]) -> Dict[str, Any]:
    """Overlay the process-global ledger's families onto ``base``, in
    place — THE one merge point the TelemetryServer, the fleet's
    ``/v1/metrics``, and the sentinel's default snapshot all share, so
    their views of the ledger namespace can never diverge. Failure-
    isolated: observability never breaks the scrape that reads it."""
    try:
        base.update(get_ledger().snapshot())
    except Exception:  # noqa: BLE001
        pass
    return base


def load_census(path: "str | Path") -> Dict[str, Any]:
    """Read a census file back, validating the schema envelope."""
    data = json.loads(Path(path).read_text())
    if not isinstance(data, dict) or "programs" not in data:
        raise ValueError(f"{path}: not a program-ledger census")
    schema = data.get("schema")
    if schema != CENSUS_SCHEMA:
        raise ValueError(
            f"{path}: census schema {schema!r} (this reader speaks "
            f"{CENSUS_SCHEMA})"
        )
    return data
