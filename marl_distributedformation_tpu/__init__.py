"""TPU-native multi-agent RL formation-control framework.

A brand-new JAX/XLA framework with the capabilities of
asanati/MARL-DistributedFormation (reference mounted at /root/reference):
decentralized 2D formation control where each agent acts on local
observations (itself, its two ring neighbors, the goal) under
neighbor-shared rewards, trained with an in-repo PPO.

Design: functional core, imperative shell.

- ``env``      — pure-functional formation environment (jit+vmap over formations)
- ``models``   — policy/value networks (MLP, GNN) in flax
- ``algo``     — PPO: GAE via ``lax.scan``, clipped surrogate, minibatch epochs
- ``parallel`` — device-mesh sharding (dp over formations, ring halo exchange
                 over the agent axis via ``shard_map`` + ``ppermute``)
- ``train``    — jitted end-to-end trainer, checkpointing, metrics
- ``ops``      — Pallas TPU kernels and fused ops
- ``scenarios``— compile-once disturbance & scenario engine (perturbation
                 layers, ScenarioSpec registry, robustness eval matrix)
- ``serving``  — compiled micro-batching policy inference
- ``analysis`` — graftlint static rules + runtime tracing guards
- ``compat``   — reference-workflow-compatible host-side adapters/frontends

Reference layer map and parity contract: see SURVEY.md at the repo root.
"""

__version__ = "0.1.0"

from marl_distributedformation_tpu.env import (  # noqa: F401
    EnvParams,
    FormationState,
    Transition,
    reset,
    step,
    make_vec_env,
)
