"""EnvSpec registry: named environments + params-type dispatch.

Mirrors the scenario registry's discipline (scenarios/registry.py): every
lookup fails fast on unknown names with a did-you-mean and the full
registry listing — a typo must never silently train/evaluate the default
environment.

The second lookup axis is the important one: ``spec_for_params(params)``
resolves the spec from the *type* of an ``EnvParams`` pytree. Downstream
code (eval.py, scenarios/engine.py, train/trainer.py, the gate's matrix
program) already threads env params everywhere, so dispatching on the
params type makes the whole stack env-generic with ZERO signature churn —
and the formation env resolves to the very same ``env/formation.py``
functions it always called, keeping that path bitwise identical.
"""

from __future__ import annotations

import difflib
from typing import Dict, Tuple

from marl_distributedformation_tpu.envs.spec import EnvSpec

_REGISTRY: Dict[str, EnvSpec] = {}
_BY_PARAMS_CLS: Dict[type, EnvSpec] = {}


def registered_envs() -> Tuple[str, ...]:
    """Registered environment names, registration order."""
    return tuple(_REGISTRY)


def register_env(spec: EnvSpec, overwrite: bool = False) -> None:
    """Add an environment (how-to: docs/environments.md).

    Overwriting a name is opt-in, and each env must bring its own
    ``params_cls`` — two envs sharing one params type would make
    ``spec_for_params`` ambiguous (subclass the params instead, as
    ``PursuitParams(EnvParams)`` does).
    """
    if spec.name in _REGISTRY and not overwrite:
        raise ValueError(
            f"environment {spec.name!r} is already registered; pass "
            "overwrite=True to replace it"
        )
    claimed = _BY_PARAMS_CLS.get(spec.params_cls)
    if claimed is not None and claimed.name != spec.name and not overwrite:
        raise ValueError(
            f"params class {spec.params_cls.__name__!r} is already claimed "
            f"by environment {claimed.name!r}; give {spec.name!r} its own "
            "params subclass so spec_for_params stays unambiguous"
        )
    if overwrite and spec.name in _REGISTRY:
        # Drop the old params-class claim so a replacement spec with a new
        # params type doesn't leave a stale dispatch entry behind.
        _BY_PARAMS_CLS.pop(_REGISTRY[spec.name].params_cls, None)
    _REGISTRY[spec.name] = spec
    _BY_PARAMS_CLS[spec.params_cls] = spec


def get_env(name: str) -> EnvSpec:
    """Lookup that fails fast: unknown names raise with the valid registry
    entries (and a did-you-mean) — never a silent formation fallback."""
    spec = _REGISTRY.get(name)
    if spec is None:
        close = difflib.get_close_matches(str(name), _REGISTRY, n=1)
        hint = f" (did you mean {close[0]!r}?)" if close else ""
        raise ValueError(
            f"unknown environment {name!r}{hint}; registered environments: "
            f"{', '.join(registered_envs())}"
        )
    return spec


def spec_for_params(params) -> EnvSpec:
    """Resolve the spec from a params instance's type (module doc).

    Walks the MRO so a params *subclass* resolves to the most-derived
    registered env (``PursuitParams`` -> pursuit_evasion, its ``EnvParams``
    base -> formation), and an unregistered type fails fast naming the
    registered (env, params-class) pairs.
    """
    for cls in type(params).__mro__:
        spec = _BY_PARAMS_CLS.get(cls)
        if spec is not None:
            return spec
    pairs = ", ".join(
        f"{s.name} ({s.params_cls.__name__})" for s in _REGISTRY.values()
    )
    raise ValueError(
        f"no registered environment for params type "
        f"{type(params).__name__!r}; registered: {pairs} — register the "
        "env with envs.register_env (docs/environments.md)"
    )
