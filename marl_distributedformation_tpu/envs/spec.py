"""The environment contract: ``EnvSpec`` + declared observation layout.

The JaxMARL / Jumanji idiom (PAPERS.md): many pure-JAX environments behind
ONE ``step``/``reset`` contract, so every downstream compiled program —
trainer, scenario engine, promotion gate, serving ladder — is env-generic.
An ``EnvSpec`` bundles an environment's pure functions (exactly the
signatures ``env/formation.py`` established, so the formation env rides
behind the contract **bitwise unchanged**) plus two pieces of metadata the
rest of the system keys on:

- ``params_cls``: the env's frozen params dataclass. Downstream code never
  takes an env name — it resolves the spec from the params it already
  holds (``registry.spec_for_params``), so every existing call site stays
  signature-compatible and the formation path stays the legacy path.
- ``obs_layout(params) -> ObsLayout``: the declared per-agent observation
  layout — named column blocks (``self`` / ``neighbor`` / ``goal`` / ...)
  and the neighbor topology (``ring`` | ``knn``). Scenario layers that
  index observation columns (comm dropout, obstacle occlusion) read block
  slices from here and **fail fast** when an env doesn't declare the block
  they need, instead of silently perturbing the wrong columns
  (scenarios/layers.py).

Contract semantics (shared by every registered env):

- ``reset(key, params) -> state`` — pure; all randomness from ``key``.
- ``step(state, velocity, params, with_obs=True) -> (state, Transition)``
  — one formation, raw per-agent velocities (the L0 contract), auto-reset
  on done with the episode key carried in ``state.key``.
- ``obs(state, params) -> obs`` — recompute observations from a state
  (shape-generic over a leading batch axis; the knn path batches the
  neighbor search, ops/knn.py).
- ``reset_batch(key, params, M)`` / ``step_batch(state, velocity, params)``
  — the vmapped forms every compiled program consumes.

``reset_env`` / ``step_env`` below expose the conventional gym-flavored
view (``(state, obs)`` / ``(state, obs, reward, done, info)``) on top of
the same primitives for new code and docs/environments.md examples.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Tuple

import numpy as np

Ranges = Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class ObsLayout:
    """Declared per-agent observation layout (static, hashable).

    ``blocks`` maps a block name to a tuple of half-open column ranges —
    a tuple because one logical block may occupy disjoint ranges (the knn
    ``neighbor`` block is offsets+distances early in the row plus the
    trailing neighbor-index block). Stored as a tuple of pairs so the
    layout can ride as static jit closure state.
    """

    dim: int
    topology: str  # "ring" | "knn" — how the neighbor block is built
    blocks: Tuple[Tuple[str, Ranges], ...]

    def __post_init__(self) -> None:
        assert self.topology in ("ring", "knn"), self.topology
        for name, ranges in self.blocks:
            for start, stop in ranges:
                assert 0 <= start <= stop <= self.dim, (
                    f"block {name!r} range ({start}, {stop}) outside "
                    f"obs dim {self.dim}"
                )

    def names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.blocks)

    def block(self, name: str) -> Ranges | None:
        for block_name, ranges in self.blocks:
            if block_name == name:
                return ranges
        return None

    def require(self, name: str, needed_by: str = "caller") -> Ranges:
        """Fail fast when a needed block isn't declared — the cure for the
        silent-mismasking hazard (a layer blanking the wrong columns)."""
        ranges = self.block(name)
        if ranges is None:
            raise ValueError(
                f"{needed_by} needs obs block {name!r}, but this env's "
                f"declared layout only has: {', '.join(self.names())} — "
                "declare the block in the env's obs_layout or don't apply "
                "this layer to it"
            )
        return ranges

    def columns(self, *names: str, needed_by: str = "caller") -> np.ndarray:
        """Static ``(dim,)`` bool mask of the named blocks' columns (every
        name must be declared — see ``require``)."""
        cols = np.zeros((self.dim,), dtype=bool)
        for name in names:
            for start, stop in self.require(name, needed_by=needed_by):
                cols[start:stop] = True
        return cols


@dataclasses.dataclass(frozen=True)
class EnvSpec:
    """A registered environment: pure functions + metadata (module doc).

    Frozen (hashable) so a spec can ride as static jit closure state, like
    the env params it dispatches on.
    """

    name: str
    description: str
    params_cls: type
    # Pure functions, exactly the env/formation.py signatures (module doc).
    reset: Callable[..., Any]  # (key, params) -> state
    step: Callable[..., Any]  # (state, velocity, params, with_obs) -> (state, tr)
    obs: Callable[..., Any]  # (state, params) -> obs
    reset_batch: Callable[..., Any]  # (key, params, M) -> state
    step_batch: Callable[..., Any]  # (state, velocity, params) -> (state, tr)
    obs_layout: Callable[..., ObsLayout]  # (params) -> ObsLayout

    # -- conventional protocol view (gym-flavored; docs/environments.md) --

    def reset_env(self, key, params):
        """``(state, obs)`` — reset plus the first observation."""
        state = self.reset(key, params)
        return state, self.obs(state, params)

    def step_env(self, state, velocity, params):
        """``(state, obs, reward, done, info)`` — the flat contract tuple
        (``info`` is the transition's metrics dict)."""
        next_state, tr = self.step(state, velocity, params)
        return next_state, tr.obs, tr.reward, tr.done, tr.metrics

    def default_params(self, **overrides):
        """A fresh ``params_cls`` instance (keyword overrides applied)."""
        return self.params_cls(**overrides)
