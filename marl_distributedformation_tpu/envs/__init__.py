"""Typed environment subsystem: one contract, many pure-JAX envs.

The JaxMARL / Jumanji idiom (docs/environments.md): every environment is
a set of pure functions behind one ``EnvSpec`` contract with declared
observation-layout metadata, named in a fail-fast registry. Downstream
code resolves the spec from the env params it already holds
(``spec_for_params``), so the trainer, scenario engine, promotion gate,
and serving ladder are env-generic with zero signature churn — and the
formation env resolves to the legacy ``env/formation.py`` functions
verbatim (bitwise-identical trajectories, pinned in tests/test_envs.py).

    from marl_distributedformation_tpu import envs

    spec = envs.get("formation")           # fail-fast, did-you-mean
    spec = envs.spec_for_params(params)    # dispatch on params type
    state, obs = spec.reset_env(key, spec.default_params())
"""

from marl_distributedformation_tpu.envs.spec import (  # noqa: F401
    EnvSpec,
    ObsLayout,
)
from marl_distributedformation_tpu.envs.registry import (  # noqa: F401
    get_env,
    register_env,
    registered_envs,
    spec_for_params,
)
from marl_distributedformation_tpu.envs.formation import (  # noqa: F401
    FORMATION_SPEC,
    formation_obs_layout,
)
from marl_distributedformation_tpu.envs.pursuit import (  # noqa: F401
    PURSUIT_SPEC,
    PursuitParams,
)

# ``envs.get("formation")`` — the registry's canonical spelling.
get = get_env

register_env(FORMATION_SPEC)
register_env(PURSUIT_SPEC)
