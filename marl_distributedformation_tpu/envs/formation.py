"""The formation env behind the contract — the legacy functions, verbatim.

This module creates NO new step/reset code: the spec's fields ARE the
``env/formation.py`` functions (asserted identical in tests/test_envs.py),
so resolving formation through the registry is bitwise identical to the
legacy direct-import path by construction. The only new code is the
declared observation layout, which makes explicit what ``compute_obs`` /
``_assemble_knn_obs`` lay out implicitly (and what
``scenarios/layers.py`` used to hard-code).
"""

from __future__ import annotations

from marl_distributedformation_tpu.env.formation import (
    compute_obs,
    reset,
    reset_batch,
    step,
    step_batch,
)
from marl_distributedformation_tpu.env.types import EnvParams
from marl_distributedformation_tpu.envs.spec import EnvSpec, ObsLayout


def formation_obs(state, params: EnvParams):
    """Recompute observations from a (possibly batched) state."""
    return compute_obs(state.agents, state.goal, params)


def formation_obs_layout(params: EnvParams) -> ObsLayout:
    """The layout ``compute_obs`` (ring) / ``_assemble_knn_obs`` (knn)
    produce, as declared block metadata.

    ring: ``[self (2) | neighbor: prev+next offsets (4) | goal (2)?]``.
    knn:  ``[self (2) | neighbor: offsets (2k) + dists (k) | goal (2)? |
    neighbor: indices (k)]`` — the neighbor block is two disjoint ranges.
    """
    dim = params.obs_dim
    if params.obs_mode == "knn":
        k = params.knn_k
        blocks = [
            ("self", ((0, 2),)),
            ("neighbor", ((2, 2 + 3 * k), (dim - k, dim))),
        ]
        if params.goal_in_obs:
            blocks.append(("goal", ((2 + 3 * k, 2 + 3 * k + 2),)))
    else:
        blocks = [("self", ((0, 2),)), ("neighbor", ((2, 6),))]
        if params.goal_in_obs:
            blocks.append(("goal", ((6, 8),)))
    return ObsLayout(
        dim=dim, topology=params.obs_mode, blocks=tuple(blocks)
    )


FORMATION_SPEC = EnvSpec(
    name="formation",
    description=(
        "ring-formation control (the reference env): N agents form a "
        "regular polygon around a static goal — env/formation.py, "
        "reference simulate.py"
    ),
    params_cls=EnvParams,
    reset=reset,
    step=step,
    obs=formation_obs,
    reset_batch=reset_batch,
    step_batch=step_batch,
    obs_layout=formation_obs_layout,
)
