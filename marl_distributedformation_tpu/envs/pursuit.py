"""Pursuit-evasion: the second pure-JAX environment behind the contract.

N evaders (the learning agents) flee ONE scripted pursuer while holding
ring cohesion. The design deliberately reuses the formation env's
machinery nearly unchanged (ROADMAP item 3c: "pursuit-evasion ...
reuse the formation obs/knn structure almost unchanged"):

- **State** is ``FormationState`` with ``goal`` reinterpreted as the
  pursuer's position — so resets, auto-reset ``tree_select``, the PRNG
  stream discipline, and every pytree-shaped downstream program (fused
  scan, sebulba queues, checkpoints) work structurally unchanged.
- **Observations** are ``compute_obs`` verbatim: the relative-"goal"
  block becomes the relative-pursuer block (declared as ``pursuer`` in
  the obs layout — a layer that needs a ``goal`` block fails fast here
  instead of silently masking pursuer columns). ``obs_mode="knn"`` and
  the Pallas neighbor search work as-is.
- **Physics, metrics, episode accounting** are the formation functions
  (``integrate``, ``_in_obstacle``, ``compute_metrics``, the Q1 parity
  done rule), so ``eval.episode_length`` and the metric keys the gate,
  sweeps, and bench consume (``avg_dist_to_goal`` = distance to the
  pursuer here, ``ave_dist_to_neighbor``) hold for both envs.

The pursuer is scripted pure-JAX: each step it moves ``pursuer_speed``
toward the nearest evader (no overshoot), clipped to the world box. The
reward flips the goal-shaping sign — evaders are paid to be FAR from the
pursuer, penalized hard within ``capture_radius`` — and keeps the
neighbor-spacing / out-of-bounds / obstacle terms and ring reward mixing,
so the task is "flee together in formation", not "scatter".

Scenario layers compose unchanged (scenarios/ resolves step/obs through
the registry): ``moving_goal`` drifts the pursuer, ``comm_dropout`` and
the obstacle layers read this env's declared layout.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.env.formation import (
    _in_obstacle,
    compute_metrics,
    compute_obs,
    integrate,
    reset,
    ring_neighbors,
)
from marl_distributedformation_tpu.env.types import (
    EnvParams,
    FormationState,
    Transition,
    tree_select,
)
from marl_distributedformation_tpu.envs.spec import EnvSpec, ObsLayout

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PursuitParams(EnvParams):
    """Formation params + the pursuit knobs.

    Subclassing ``EnvParams`` (rather than a fresh dataclass) is what
    makes the whole stack env-generic for free: every call site that
    threads ``EnvParams`` duck-types these, and ``envs.spec_for_params``
    dispatches on the most-derived registered type.
    """

    pursuer_speed: float = 7.0  # px/step, < max_speed so evasion is possible
    capture_radius: float = 30.0  # px — within this the evader is "caught"
    capture_penalty: float = 50.0  # per-step penalty while caught
    evade_reward_scale: float = 0.05  # reward per px of pursuer distance

    def __post_init__(self) -> None:
        super().__post_init__()
        assert self.pursuer_speed >= 0.0
        assert self.capture_radius >= 0.0


def pursuer_update(
    agents: Array, pursuer: Array, params: PursuitParams
) -> Array:
    """Scripted pursuer policy: move ``pursuer_speed`` toward the nearest
    evader (no overshoot), clipped to the world box. Pure JAX — argmin +
    normalized direction, no host branching."""
    dists = jnp.linalg.norm(agents - pursuer[None, :], axis=-1)
    nearest = agents[jnp.argmin(dists)]
    delta = nearest - pursuer
    gap = jnp.linalg.norm(delta)
    direction = delta / jnp.maximum(gap, 1e-6)
    moved = pursuer + jnp.minimum(params.pursuer_speed, gap) * direction
    wh = jnp.array([params.width, params.height], jnp.float32)
    return jnp.clip(moved, 0.0, wh)


def pursuit_reward(
    agents: Array,
    pursuer: Array,
    out_of_bounds: Array,
    in_obstacle: Array,
    params: PursuitParams,
):
    """Per-agent evade reward with the formation env's cohesion terms.

    Mirrors ``compute_reward``'s structure: individual terms, then the
    ring reward mixing ``(1-2p)*r_i + p*(r_prev + r_next)`` — fleeing is
    a team sport here, exactly like formation-holding.
    """
    dist_to_pursuer = jnp.linalg.norm(agents - pursuer[..., None, :], axis=-1)
    evade_reward = params.evade_reward_scale * dist_to_pursuer
    caught = dist_to_pursuer < params.capture_radius
    capture_penalty = -params.capture_penalty * caught

    # Asymmetric neighbor-spacing penalty, verbatim formation semantics:
    # quadratic when too close, linear when too far.
    prev_pos, next_pos = ring_neighbors(agents, -2)
    target = params.desired_neighbor_dist
    right_diff = jnp.linalg.norm(agents - next_pos, axis=-1) - target
    left_diff = jnp.linalg.norm(agents - prev_pos, axis=-1) - target
    reward_right = -params.neighbor_penalty_scale * jnp.where(
        right_diff < 0, right_diff**2, right_diff
    )
    reward_left = -params.neighbor_penalty_scale * jnp.where(
        left_diff < 0, left_diff**2, left_diff
    )

    individual = (
        evade_reward
        + capture_penalty
        + reward_right
        + reward_left
        - params.oob_penalty * out_of_bounds
        - params.obstacle_penalty * in_obstacle
    )

    rho = params.share_reward_ratio
    prev_r, next_r = ring_neighbors(individual, -1)
    mixed = (1.0 - 2.0 * rho) * individual + rho * (prev_r + next_r)

    terms = {
        "evade_reward": evade_reward,
        "capture_penalty": capture_penalty,
        "reward_right_neighbor": reward_right,
        "reward_left_neighbor": reward_left,
    }
    return mixed, terms


def pursuit_step(
    state: FormationState,
    velocity: Array,
    params: PursuitParams,
    with_obs: bool = True,
) -> Tuple[FormationState, Transition]:
    """One formation of evaders, one step (contract: envs/spec.py).

    Same skeleton and ordering as ``formation.step``: integrate → flag
    bounds/obstacles → scripted pursuer moves (reacting to the evaders'
    NEW positions) → reward on the pre-reset state → parity done rule →
    auto-reset → obs/metrics on the (possibly reset) state.
    """
    agents, out_of_bounds = integrate(state.agents, velocity, params)
    in_obstacle = _in_obstacle(agents, state.obstacles, params)
    pursuer = pursuer_update(agents, state.goal, params)

    reward, reward_terms = pursuit_reward(
        agents, pursuer, out_of_bounds, in_obstacle, params
    )

    if params.strict_parity:
        done = state.steps > params.max_steps
    else:
        done = state.steps + 1 >= params.max_steps

    stepped = FormationState(
        agents=agents,
        goal=pursuer,
        obstacles=state.obstacles,
        steps=state.steps + 1,
        key=state.key,
    )
    fresh = reset(state.key, params)
    next_state = tree_select(done, fresh, stepped)

    if with_obs:
        obs = compute_obs(next_state.agents, next_state.goal, params)
    else:
        obs = jnp.zeros((state.agents.shape[-2], 0), jnp.float32)
    metrics = compute_metrics(next_state.agents, next_state.goal, params)
    metrics.update({k: v.mean() for k, v in reward_terms.items()})
    metrics["reward"] = reward.mean()

    return next_state, Transition(
        obs=obs, reward=reward, done=done, metrics=metrics
    )


def pursuit_reset_batch(
    key: Array, params: PursuitParams, num_formations: int
) -> FormationState:
    keys = jax.random.split(key, num_formations)
    return jax.vmap(reset, in_axes=(0, None))(keys, params)


def pursuit_step_batch(
    state: FormationState, velocity: Array, params: PursuitParams
) -> Tuple[FormationState, Transition]:
    """Batched pursuit step, mirroring ``formation.step_batch``'s knn
    routing (the batched neighbor search sees ``(M, N, 2)`` at once)."""
    if params.obs_mode == "knn":
        next_state, tr = jax.vmap(
            functools.partial(pursuit_step, with_obs=False),
            in_axes=(0, 0, None),
        )(state, velocity, params)
        obs = compute_obs(next_state.agents, next_state.goal, params)
        return next_state, tr.replace(obs=obs)
    return jax.vmap(pursuit_step, in_axes=(0, 0, None))(
        state, velocity, params
    )


def pursuit_obs(state: FormationState, params: PursuitParams) -> Array:
    return compute_obs(state.agents, state.goal, params)


def pursuit_obs_layout(params: PursuitParams) -> ObsLayout:
    """Formation's column geometry with the relative-goal block renamed
    ``pursuer`` — layers needing a ``goal`` block fail fast here rather
    than silently masking pursuer columns (spec.ObsLayout.require)."""
    dim = params.obs_dim
    if params.obs_mode == "knn":
        k = params.knn_k
        blocks = [
            ("self", ((0, 2),)),
            ("neighbor", ((2, 2 + 3 * k), (dim - k, dim))),
        ]
        if params.goal_in_obs:
            blocks.append(("pursuer", ((2 + 3 * k, 2 + 3 * k + 2),)))
    else:
        blocks = [("self", ((0, 2),)), ("neighbor", ((2, 6),))]
        if params.goal_in_obs:
            blocks.append(("pursuer", ((6, 8),)))
    return ObsLayout(
        dim=dim, topology=params.obs_mode, blocks=tuple(blocks)
    )


PURSUIT_SPEC = EnvSpec(
    name="pursuit_evasion",
    description=(
        "pursuit-evasion: N evaders flee one scripted pursuer (moves "
        "pursuer_speed toward the nearest evader each step) while "
        "holding ring cohesion — formation machinery reused, goal slot "
        "carries the pursuer"
    ),
    params_cls=PursuitParams,
    reset=reset,
    step=pursuit_step,
    obs=pursuit_obs,
    reset_batch=pursuit_reset_batch,
    step_batch=pursuit_step_batch,
    obs_layout=pursuit_obs_layout,
)
