"""State and parameter types for the formation environment.

The reference keeps environment state as mutable attributes on a
``FormationSimulator`` object (reference ``simulate.py:11-61``). Here state is
an immutable pytree (``FormationState``) and all static configuration lives in
a hashable frozen dataclass (``EnvParams``) so every step function can be
traced once by XLA and ``vmap``-ed over thousands of formations.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@dataclasses.dataclass(frozen=True)
class EnvParams:
    """Static environment configuration (compile-time constants).

    Defaults mirror the reference simulator's hardcoded values
    (``simulate.py:13-31``): a 400x600 world, desired formation radius 60,
    1000-step episode budget, reward-sharing ratio 0.25.
    """

    num_agents: int = 5
    num_obstacles: int = 0
    width: float = 400.0
    height: float = 600.0
    obstacle_size: float = 10.0
    max_steps: int = 1000
    desired_radius: float = 60.0
    share_reward_ratio: float = 0.25  # rho in [0, 0.5]; cfg key wired for real
    #   (the reference's cfg value is dead — see SURVEY.md Q6)
    goal_in_obs: bool = True
    max_speed: float = 10.0  # action scaling, reference vectorized_env.py:69

    # Reward constants (reference simulate.py:183-215).
    close_goal_dist: float = 100.0
    close_goal_bonus: float = 10.0
    reward_dist_scale: float = 0.1
    neighbor_penalty_scale: float = 0.01
    oob_penalty: float = 100.0
    obstacle_penalty: float = 100.0

    # Reset distribution constants (reference simulate.py:124-143).
    agent_spawn_band: float = 100.0  # agents spawn in the bottom 100 px
    obstacle_margin_band: float = 100.0  # no obstacles in top/bottom 100 px

    # Behavior flags.
    strict_parity: bool = True
    """Reproduce the reference's quirks exactly (SURVEY.md §8):
    Q1 — episodes last ``max_steps + 2`` steps (done when the pre-increment
    step counter exceeds ``max_steps``, reference simulate.py:111,231);
    Q3 — termination on timeout only (goal-reached termination is commented
    out in the reference, simulate.py:233-234).
    When False: episodes last exactly ``max_steps`` steps and
    ``goal_termination`` may end them early."""

    goal_termination: bool = False
    """End the episode when every agent is within ``close_goal_dist`` of the
    goal. Only honored when ``strict_parity`` is False (the reference ships
    with this disabled)."""

    obs_mode: str = "ring"
    """``"ring"``: the reference's local view — self + two ring neighbors
    (+ goal), simulate.py:150-174. ``"knn"``: large-swarm view (BASELINE.json
    config 4) — self (+ goal) plus offsets/distances/indices of the
    ``knn_k`` nearest neighbors, recomputed every step (ops/knn.py). Rewards
    keep ring semantics in both modes (the task definition is the ring
    formation; only what agents *observe* changes)."""

    knn_k: int = 4
    """Neighbor count for ``obs_mode="knn"``; must be < num_agents."""

    knn_impl: str = "auto"
    """Neighbor-search implementation for batched knn observations:
    ``"auto"`` (on TPU: fused Pallas kernel for N <= 640, chunked-streaming
    kernel beyond; XLA elsewhere), ``"xla"``, ``"pallas"``,
    ``"pallas_big"``, or ``"pallas_interpret"``/``"pallas_big_interpret"``
    (CPU-debuggable kernels). See ops/knn.py ``knn_batch``."""

    obstacle_mode: str = "parity"
    """``"parity"``: the reference's inconsistent geometry (Q2) — the obstacle
    point is treated as the lower-left corner of an ``obstacle_size``-sided box
    for collision (simulate.py:96) while placement/rendering treat it as the
    center of a ``2*obstacle_size`` box (simulate.py:126-130).
    ``"fixed"``: consistent geometry — the point is the center of a
    ``2*obstacle_size``-sided box for placement, collision, and rendering."""

    def __post_init__(self) -> None:
        assert self.num_agents >= 2, "ring topology needs at least 2 agents"
        assert 0.0 <= self.share_reward_ratio <= 0.5, (
            "share_reward_ratio must be in [0, 0.5] (reference simulate.py:28)"
        )
        assert self.obstacle_mode in ("parity", "fixed")
        assert self.obs_mode in ("ring", "knn")
        if self.obs_mode == "knn":
            assert 1 <= self.knn_k < self.num_agents, (
                f"knn_k={self.knn_k} must be in [1, num_agents)"
            )
        assert self.knn_impl in (
            "auto",
            "xla",
            "pallas",
            "pallas_big",
            "pallas_interpret",
            "pallas_big_interpret",
        ), f"unknown knn_impl {self.knn_impl!r}"

    @property
    def desired_neighbor_dist(self) -> float:
        """Chord length of a regular ``num_agents``-gon of radius
        ``desired_radius`` (reference simulate.py:26)."""
        return float(
            2.0 * self.desired_radius * np.sin(np.pi / self.num_agents)
        )

    @property
    def obs_dim(self) -> int:
        """Per-agent observation width.

        ``ring``: 6, +2 when the relative goal is appended (reference
        vectorized_env.py:28-31). ``knn``: own pos (2) + k offsets (2k) +
        k distances (k) [+ rel goal (2)] + k neighbor indices (k) — indices
        ride along as exact-in-float32 values so graph models can gather
        neighbor embeddings without recomputing the search (models/gnn.py).
        """
        if self.obs_mode == "knn":
            base = 2 + 3 * self.knn_k + (2 if self.goal_in_obs else 0)
            return base + self.knn_k
        return 8 if self.goal_in_obs else 6

    @property
    def act_dim(self) -> int:
        return 2

    def replace(self, **changes: Any) -> "EnvParams":
        return dataclasses.replace(self, **changes)


@struct.dataclass
class FormationState:
    """Per-formation dynamic state.

    Shapes are for a single formation; batched code ``vmap``s over a leading
    formation axis M. ``key`` is a per-formation PRNG stream so resets are
    independent and deterministic (the reference has no seeding at all —
    SURVEY.md Q9).
    """

    agents: jax.Array  # (N, 2) float32 positions
    goal: jax.Array  # (2,) float32
    obstacles: jax.Array  # (K, 2) float32 (K may be 0)
    steps: jax.Array  # () int32 — steps completed since reset
    key: jax.Array  # PRNG key for this formation's reset stream


@struct.dataclass
class Transition:
    """Everything ``step`` returns besides the next state.

    ``done`` is scalar per formation (the reference broadcasts it to all
    agents in the vec adapter, vectorized_env.py:79). ``metrics`` holds the
    reference's observability contract scalars (simulate.py:238-254) plus the
    per-agent reward terms it logs (simulate.py:188-208), all computed
    on-device with no host callbacks.
    """

    obs: jax.Array  # (N, obs_dim) float32
    reward: jax.Array  # (N,) float32 — neighbor-mixed rewards
    done: jax.Array  # () bool
    metrics: Dict[str, jax.Array]


def tree_select(pred: jax.Array, on_true: Any, on_false: Any) -> Any:
    """``jnp.where`` over a pytree with a scalar predicate (broadcasts)."""
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), on_true, on_false
    )
