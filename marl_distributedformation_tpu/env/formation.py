"""Pure-functional formation-control environment.

Reimplements the semantics of the reference's ``FormationSimulator``
(``simulate.py:7-254``) as pure functions over a ``FormationState`` pytree:

- physics: single-integrator ``agents += velocity`` with clipping to the
  world box and an out-of-bounds flag (reference simulate.py:80-90);
- observations: per-agent local view — own normalized position, offsets to
  the two ring neighbors, optional normalized relative goal (simulate.py:150-174);
- rewards: goal shaping + proximity bonus + asymmetric neighbor-spacing
  penalty + boundary/obstacle penalties, then ring-neighbor reward mixing
  (simulate.py:176-229);
- auto-reset inside ``step`` following the SB3 VecEnv convention — the
  observation returned on ``done`` is the first observation of the next
  episode while the reward is the terminal reward (simulate.py:113-118).

Every Python-level loop in the reference (per-agent observation loop
simulate.py:162-167, reward-sharing loop simulate.py:223-229, per-formation
loop vectorized_env.py:71-81) becomes a ``jnp.roll``/``vmap`` so the whole
step compiles to one fused XLA program.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.env.types import (
    EnvParams,
    FormationState,
    Transition,
    tree_select,
)

Array = jax.Array


def ring_neighbors(x: Array, axis: int) -> Tuple[Array, Array]:
    """Default (single-device) ring-neighbor lookup: ``(prev, next)`` along
    ``axis`` via ``jnp.roll``. The sharded agent-axis variant in
    ``parallel/ring.py`` swaps this for a ppermute halo exchange; all env
    math below is parameterized over this function so both paths share one
    implementation."""
    return jnp.roll(x, 1, axis=axis), jnp.roll(x, -1, axis=axis)


def integrate(
    agents: Array, velocity: Array, params: EnvParams
) -> Tuple[Array, Array]:
    """Single-integrator physics + boundary handling (simulate.py:80-90):
    returns ``(clipped_agents, out_of_bounds)``. Shape-generic over leading
    batch axes."""
    agents = agents + velocity
    out_of_bounds = (
        (agents[..., 0] <= 0.0)
        | (agents[..., 1] <= 0.0)
        | (agents[..., 0] >= params.width)
        | (agents[..., 1] >= params.height)
    )
    agents = jnp.clip(
        agents,
        jnp.zeros((2,), jnp.float32),
        jnp.array([params.width, params.height], jnp.float32),
    )
    return agents, out_of_bounds


def reset(key: Array, params: EnvParams) -> FormationState:
    """Sample a fresh formation state.

    Matches the reference's initial-state distribution (simulate.py:120-147):
    agents uniform over the bottom ``agent_spawn_band`` strip, goal uniform
    with a ``desired_radius`` wall margin, obstacles uniform over the middle
    band. The reference draws from torch's unseeded global RNG (SURVEY.md
    Q9); here every formation carries its own PRNG stream, so rollouts are
    reproducible — distributions match, exact draws intentionally don't.
    """
    key, k_obstacles, k_agents, k_goal = jax.random.split(key, 4)

    # Obstacles: x in [s, W-s], y in [band+s, H-band-s] (simulate.py:125-127).
    obstacles = jax.random.uniform(
        k_obstacles, (params.num_obstacles, 2), dtype=jnp.float32
    )
    obstacles = obstacles * jnp.array(
        [
            params.width - 2.0 * params.obstacle_size,
            params.height
            - 2.0 * params.obstacle_margin_band
            - 2.0 * params.obstacle_size,
        ],
        dtype=jnp.float32,
    ) + jnp.array(
        [
            params.obstacle_size,
            params.obstacle_margin_band + params.obstacle_size,
        ],
        dtype=jnp.float32,
    )

    # Agents: x in [0, W], y in [0, band] (simulate.py:133-135).
    agents = jax.random.uniform(
        k_agents, (params.num_agents, 2), dtype=jnp.float32
    ) * jnp.array(
        [params.width, params.agent_spawn_band], dtype=jnp.float32
    )

    # Goal: uniform with desired_radius margin from every wall
    # (simulate.py:140-143).
    goal = jax.random.uniform(k_goal, (2,), dtype=jnp.float32) * jnp.array(
        [
            params.width - 2.0 * params.desired_radius,
            params.height - 2.0 * params.desired_radius,
        ],
        dtype=jnp.float32,
    ) + params.desired_radius

    return FormationState(
        agents=agents,
        goal=goal,
        obstacles=obstacles,
        steps=jnp.zeros((), jnp.int32),
        key=key,
    )


def compute_obs(
    agents: Array,
    goal: Array,
    params: EnvParams,
    pos_neighbors: Tuple[Array, Array] = None,
) -> Array:
    """Per-agent local observation.

    ``obs_mode="ring"`` (reference simulate.py:150-174) — layout per agent i:
    ``[own_pos/WH, prev_i - own, next_i - own, (goal - own_pos)/WH?]`` where
    positions are normalized by (width, height) and prev/next are the ring
    neighbors. The reference's per-agent Python loop becomes two
    ``jnp.roll``s (or, when ``pos_neighbors`` is supplied by the sharded
    path, a precomputed halo exchange). Shape-generic over leading batch
    axes (agent axis is -2).

    ``obs_mode="knn"`` (BASELINE.json config 4) — see ``compute_obs_knn``.
    """
    if params.obs_mode == "knn":
        assert pos_neighbors is None, (
            "knn obs does not take precomputed ring neighbors; the "
            "agent-axis-sharded knn path goes through "
            "compute_obs_knn_sharded (parallel/ring.py), not this argument"
        )
        return compute_obs_knn(agents, goal, params)
    wh = jnp.array([params.width, params.height], dtype=jnp.float32)
    if pos_neighbors is None:
        pos_neighbors = ring_neighbors(agents, -2)
    prev_pos, next_pos = pos_neighbors
    normalized = agents / wh
    parts = [
        normalized,
        prev_pos / wh - normalized,
        next_pos / wh - normalized,
    ]
    if params.goal_in_obs:
        parts.append((goal[..., None, :] - agents) / wh)  # simulate.py:172
    return jnp.concatenate(parts, axis=-1)


def compute_obs_knn(agents: Array, goal: Array, params: EnvParams) -> Array:
    """Large-swarm observation over the k-nearest-neighbor graph.

    Per agent i: ``[own_pos/WH (2), offsets to k nearest neighbors /WH (2k),
    distances /diag (k), (goal - own)/WH (2, if goal_in_obs),
    neighbor indices (k)]``. Indices are exact int values carried in float32
    (N < 2^24) so formation-level graph models (models/gnn.py) can gather
    neighbor embeddings for message passing; MLP policies simply learn to
    ignore them.

    Shape-generic: single formation ``agents (N, 2)``/``goal (2,)`` runs the
    per-formation XLA search (vmap-safe); batched ``(M, N, 2)``/``(M, 2)``
    dispatches through ``ops.knn_batch`` so the fused Pallas kernel
    (ops/knn_pallas.py, selected by ``EnvParams.knn_impl``) sees the whole
    batch at once and the ``(M, N, N)`` distance tensor never touches HBM.
    """
    from marl_distributedformation_tpu.ops import knn, knn_batch

    if agents.ndim > 2:
        idx, offsets, dists = knn_batch(
            agents, params.knn_k, impl=params.knn_impl
        )
    else:
        idx, offsets, dists = knn(agents, params.knn_k)
    return _assemble_knn_obs(agents, goal, idx, offsets, dists, params)


def _assemble_knn_obs(
    agents: Array,
    goal: Array,
    idx: Array,
    offsets: Array,
    dists: Array,
    params: EnvParams,
) -> Array:
    """The knn observation layout, given the search results — shared by the
    single-device path above and the agent-axis-sharded path
    (``compute_obs_knn_sharded``), so the two stay bit-identical."""
    wh = jnp.array([params.width, params.height], dtype=jnp.float32)
    diag = float(np.hypot(params.width, params.height))
    parts = [
        agents / wh,
        (offsets / wh).reshape(*agents.shape[:-1], 2 * params.knn_k),
        dists / diag,
    ]
    if params.goal_in_obs:
        parts.append((goal[..., None, :] - agents) / wh)
    parts.append(idx.astype(jnp.float32))
    return jnp.concatenate(parts, axis=-1)


def compute_obs_knn_sharded(
    local_agents: Array,
    all_agents: Array,
    goal: Array,
    params: EnvParams,
    agent_offset,
) -> Array:
    """knn observations for an agent-axis-sharded slab (parallel/ring.py
    swarm mode): ``local_agents (m, n_local, 2)`` is this device's slab of
    global rows ``agent_offset..agent_offset+n_local``, ``all_agents
    (m, N, 2)`` the all-gathered formation. Neighbor indices in the obs stay
    GLOBAL, so the observation rows equal the corresponding rows of
    ``compute_obs_knn`` on the unsharded formation exactly.
    """
    from marl_distributedformation_tpu.ops.knn import knn_local

    idx, offsets, dists = jax.vmap(
        knn_local, in_axes=(0, 0, None, None)
    )(local_agents, all_agents, params.knn_k, agent_offset)
    return _assemble_knn_obs(
        local_agents, goal, idx, offsets, dists, params
    )


def _in_obstacle(agents: Array, obstacles: Array, params: EnvParams) -> Array:
    """Per-agent obstacle containment flag.

    ``parity`` mode reproduces the reference's inconsistent geometry
    (SURVEY.md Q2): the obstacle point is the *lower-left corner* of an
    ``obstacle_size``-sided box (simulate.py:94-98). ``fixed`` mode treats
    the point as the box *center* with half-extent ``obstacle_size`` —
    consistent with how the reference places and renders obstacles
    (simulate.py:126-130).
    """
    if params.num_obstacles == 0:
        return jnp.zeros((agents.shape[0],), dtype=bool)
    if params.obstacle_mode == "parity":
        lo = obstacles[:, None, :]
        hi = lo + params.obstacle_size
    else:  # "fixed"
        lo = obstacles[:, None, :] - params.obstacle_size
        hi = obstacles[:, None, :] + params.obstacle_size
    inside = jnp.logical_and(lo <= agents[None], agents[None] <= hi)
    return inside.all(axis=-1).any(axis=0)


def compute_reward(
    agents: Array,
    goal: Array,
    out_of_bounds: Array,
    in_obstacle: Array,
    params: EnvParams,
    neighbors_fn=ring_neighbors,
    pos_neighbors: Tuple[Array, Array] = None,
    neighbor_dist_target: Array = None,
) -> Tuple[Array, Dict[str, Array]]:
    """Neighbor-mixed per-agent rewards (reference simulate.py:176-229).

    Returns the mixed rewards and a dict of *per-agent* reward-term arrays
    (the terms the reference streams to wandb, simulate.py:188-208 — callers
    reduce them: plain ``.mean()`` single-device, psum-mean when the agent
    axis is sharded). Shape-generic over leading batch axes; ``neighbors_fn``
    supplies ring neighbors (roll by default, halo exchange when sharded);
    ``neighbor_dist_target`` overrides the static regular-polygon chord
    target — the heterogeneous path (env/hetero.py) passes the per-formation
    ``2·R·sin(π/n)`` computed from the dynamic agent count.
    """
    dist_to_goal = jnp.linalg.norm(agents - goal[..., None, :], axis=-1)
    close_to_goal = dist_to_goal < params.close_goal_dist
    close_to_goal_reward = params.close_goal_bonus * close_to_goal
    reward_dist = -params.reward_dist_scale * dist_to_goal

    # Asymmetric spacing penalty: quadratic when too close, linear when too
    # far (simulate.py:197-205).
    if pos_neighbors is None:
        pos_neighbors = neighbors_fn(agents, -2)
    prev_pos, next_pos = pos_neighbors
    dist_right = jnp.linalg.norm(agents - next_pos, axis=-1)
    dist_left = jnp.linalg.norm(agents - prev_pos, axis=-1)
    target = (
        params.desired_neighbor_dist
        if neighbor_dist_target is None
        else neighbor_dist_target
    )
    right_diff = dist_right - target
    left_diff = dist_left - target
    reward_right = -params.neighbor_penalty_scale * jnp.where(
        right_diff < 0, right_diff**2, right_diff
    )
    reward_left = -params.neighbor_penalty_scale * jnp.where(
        left_diff < 0, left_diff**2, left_diff
    )

    individual = (
        reward_dist
        + close_to_goal_reward
        + reward_right
        + reward_left
        - params.oob_penalty * out_of_bounds
        - params.obstacle_penalty * in_obstacle
    )

    # Ring-neighbor reward mixing (1-2p)*r_i + p*(r_{i-1} + r_{i+1})
    # (simulate.py:222-229), as neighbor lookups instead of a Python loop.
    rho = params.share_reward_ratio
    prev_r, next_r = neighbors_fn(individual, -1)
    mixed = (1.0 - 2.0 * rho) * individual + rho * (prev_r + next_r)

    terms = {
        "close_to_goal_reward": close_to_goal_reward,
        "reward_dist": reward_dist,
        "reward_right_neighbor": reward_right,
        "reward_left_neighbor": reward_left,
    }
    return mixed, terms


def compute_metrics(
    agents: Array,
    goal: Array,
    params: EnvParams,
    pos_neighbors: Tuple[Array, Array] = None,
) -> Dict[str, Array]:
    """Side-effect-free progress metrics (reference simulate.py:238-254).

    ``std_dist_to_neighbor`` uses the unbiased (n-1) estimator to match
    ``torch.Tensor.std``.
    """
    if pos_neighbors is None:
        pos_neighbors = ring_neighbors(agents, -2)
    dist_to_goal = jnp.linalg.norm(agents - goal[..., None, :], axis=-1)
    dist_right = jnp.linalg.norm(agents - pos_neighbors[1], axis=-1)
    return {
        "avg_dist_to_goal": dist_to_goal.mean(),
        "ave_dist_to_neighbor": dist_right.mean(),
        "std_dist_to_neighbor": dist_right.std(ddof=1),
    }


def step(
    state: FormationState,
    velocity: Array,
    params: EnvParams,
    with_obs: bool = True,
) -> Tuple[FormationState, Transition]:
    """Advance one formation by one step.

    ``velocity`` is the raw per-agent velocity ``(N, 2)`` — the same contract
    as the reference's L0 API (``FormationSimulator.step``, simulate.py:70).
    Action scaling from policy space [-1, 1] lives in the vec adapter, as in
    the reference (vectorized_env.py:69-70, SURVEY.md Q8).

    Follows the reference step order exactly (simulate.py:70-118): integrate,
    flag + clip bounds, obstacle containment, reward (on pre-reset state),
    timeout check against the pre-increment counter (Q1), auto-reset, then
    metrics and observation on the (possibly reset) state.
    """
    agents, out_of_bounds = integrate(state.agents, velocity, params)

    in_obstacle = _in_obstacle(agents, state.obstacles, params)

    reward, reward_terms = compute_reward(
        agents, state.goal, out_of_bounds, in_obstacle, params
    )

    if params.strict_parity:
        # Q1: pre-increment check -> episodes run max_steps + 2 steps.
        done = state.steps > params.max_steps
    else:
        done = state.steps + 1 >= params.max_steps
        if params.goal_termination:
            dist_to_goal = jnp.linalg.norm(agents - state.goal, axis=-1)
            done = done | (dist_to_goal < params.close_goal_dist).all()

    stepped = FormationState(
        agents=agents,
        goal=state.goal,
        obstacles=state.obstacles,
        steps=state.steps + 1,
        key=state.key,
    )
    fresh = reset(state.key, params)
    next_state = tree_select(done, fresh, stepped)

    if with_obs:
        obs = compute_obs(next_state.agents, next_state.goal, params)
    else:
        # Zero-width placeholder for callers that compute obs once over the
        # whole batch after the vmap (step_batch's knn path) and then
        # ``replace`` it — costs nothing even if a caller keeps it live
        # (no reliance on XLA dead-code elimination).
        obs = jnp.zeros((state.agents.shape[-2], 0), jnp.float32)
    metrics = compute_metrics(next_state.agents, next_state.goal, params)
    metrics.update({k: v.mean() for k, v in reward_terms.items()})
    metrics["reward"] = reward.mean()

    return next_state, Transition(
        obs=obs, reward=reward, done=done, metrics=metrics
    )


# ---------------------------------------------------------------------------
# Batched (vmapped) wrappers — the TPU replacement for the reference's
# sequential formation loop (vectorized_env.py:71-81).
# ---------------------------------------------------------------------------


def reset_batch(
    key: Array, params: EnvParams, num_formations: int
) -> FormationState:
    """Reset ``num_formations`` independent formations (leading axis M)."""
    keys = jax.random.split(key, num_formations)
    return jax.vmap(reset, in_axes=(0, None))(keys, params)


def step_batch(
    state: FormationState, velocity: Array, params: EnvParams
) -> Tuple[FormationState, Transition]:
    """Step a batch of formations: state leaves and ``velocity`` carry a
    leading formation axis M; ``velocity`` is ``(M, N, 2)``.

    For ``obs_mode="knn"`` the per-formation step runs without obs and the
    neighbor-graph observation is computed once over the whole batch, so the
    fused Pallas search (ops/knn_pallas.py) sees ``(M, N, 2)`` directly.
    """
    if params.obs_mode == "knn":
        next_state, tr = jax.vmap(
            functools.partial(step, with_obs=False), in_axes=(0, 0, None)
        )(state, velocity, params)
        obs = compute_obs(next_state.agents, next_state.goal, params)
        return next_state, tr.replace(obs=obs)
    return jax.vmap(step, in_axes=(0, 0, None))(state, velocity, params)


def make_vec_env(
    params: EnvParams, num_formations: int
) -> Tuple[
    Callable[[Array], Tuple[FormationState, Array]],
    Callable[[FormationState, Array], Tuple[FormationState, Transition]],
]:
    """Build jitted ``(reset_fn, step_fn)`` closed over static params.

    ``reset_fn(key) -> (state, obs)`` with obs ``(M, N, obs_dim)``;
    ``step_fn(state, actions)`` takes policy actions in [-1, 1] shaped
    ``(M, N, 2)`` and applies the ``max_speed`` scaling, mirroring the
    reference's L1 adapter contract (vectorized_env.py:68-82).
    """

    @jax.jit
    def reset_fn(key: Array) -> Tuple[FormationState, Array]:
        state = reset_batch(key, params, num_formations)
        obs = compute_obs(state.agents, state.goal, params)
        return state, obs

    @jax.jit
    def step_fn(
        state: FormationState, actions: Array
    ) -> Tuple[FormationState, Transition]:
        velocity = params.max_speed * actions
        return step_batch(state, velocity, params)

    return reset_fn, step_fn
