"""Heterogeneous formations: mixed agent counts under XLA static shapes.

BASELINE.json config 5 ("Heterogeneous multi-formation (mixed 5/20-agent
groups) with obstacle field, curriculum over num_agents_per_formation") has no
reference implementation — the reference fixes one ``num_agents_per_formation``
for every formation in the batch (reference ``vectorized_env.py:39-43``) and
its obstacle system is disabled (``simulate.py:16``; SURVEY.md Q2). This
module supplies the capability TPU-first:

- Every formation is padded to a static ``params.num_agents`` (= N_max) so one
  XLA program serves the whole mixed batch; the *active* agent count ``n`` and
  obstacle count ``k`` are per-formation **data** (int32 scalars in the state
  pytree), so a curriculum can change the mix between rollouts with zero
  recompiles.
- Ring topology, neighbor-spacing targets, and reward mixing all follow the
  dynamic ``n``: neighbors are gathered with ``(i ± 1) mod n`` index arrays
  instead of ``jnp.roll``, and the regular-polygon chord target
  ``2·R·sin(π/n)`` (reference ``simulate.py:26``) is computed per formation.
- Padded agents are inert: zero observations, zero rewards, zero velocity;
  they carry zero loss weight in PPO (algo/ppo.py ``MinibatchData.weights``).
- Inactive obstacle slots are parked far outside the world box so the
  containment test (formation.py ``_in_obstacle``) can never fire on them.

Single-formation functions take scalars ``n``/``k``; batched wrappers ``vmap``
over a leading formation axis M exactly like env/formation.py.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from marl_distributedformation_tpu.env.formation import (
    _in_obstacle,
    compute_obs,
    compute_reward,
    integrate,
    reset,
)
from marl_distributedformation_tpu.env.types import (
    EnvParams,
    Transition,
    tree_select,
)

Array = jax.Array

FAR_AWAY = -1.0e6  # parking spot for inactive obstacle slots


@struct.dataclass
class HeteroState:
    """Per-formation state for a padded heterogeneous formation.

    Same layout as ``FormationState`` (env/types.py) plus the two dynamic
    counts. ``agents`` is always ``(N_max, 2)``; rows ``>= n_agents`` are
    padding.
    """

    agents: Array  # (N_max, 2) float32
    goal: Array  # (2,) float32
    obstacles: Array  # (K_max, 2) float32; slots >= n_obstacles parked far away
    steps: Array  # () int32
    key: Array  # per-formation PRNG stream
    n_agents: Array  # () int32 — active agents, 2 <= n <= N_max
    n_obstacles: Array  # () int32 — active obstacles, 0 <= k <= K_max


def agent_mask(n_agents: Array, n_max: int) -> Array:
    """``(N_max,)`` bool validity mask: True for the first ``n`` slots."""
    return jnp.arange(n_max) < n_agents


def ring_gather_indices(n_agents: Array, n_max: int) -> Tuple[Array, Array]:
    """Dynamic-ring neighbor indices ``(prev, next)``, each ``(N_max,)``.

    Active agent ``i < n`` has ring neighbors ``(i-1) mod n`` and
    ``(i+1) mod n`` — the padded replacement for the reference's
    ``torch.roll`` over a full-length ring (``simulate.py:181-182``).
    Padded slots produce in-range garbage indices; their outputs are
    masked by every consumer.
    """
    idx = jnp.arange(n_max)
    prev = (idx - 1 + n_agents) % n_agents
    nxt = (idx + 1) % n_agents
    return prev, nxt


def desired_neighbor_dist(n_agents: Array, params: EnvParams) -> Array:
    """Per-formation regular-polygon chord target ``2·R·sin(π/n)``
    (reference ``simulate.py:26`` with the formation's own ``n``)."""
    return (
        2.0
        * params.desired_radius
        * jnp.sin(jnp.pi / n_agents.astype(jnp.float32))
    )


def hetero_reset(
    key: Array, params: EnvParams, n_agents: Array, n_obstacles: Array
) -> HeteroState:
    """Sample a fresh padded formation.

    Reuses the homogeneous reset distribution (env/formation.py ``reset``,
    reference ``simulate.py:120-147``) at the padded sizes, then parks
    obstacle slots ``>= n_obstacles`` far outside the world so they can never
    contain an agent. Padded agent rows are sampled like real ones (they are
    simply never read).
    """
    base = reset(key, params)
    k = jnp.arange(params.num_obstacles) < n_obstacles
    obstacles = jnp.where(k[:, None], base.obstacles, FAR_AWAY)
    return HeteroState(
        agents=base.agents,
        goal=base.goal,
        obstacles=obstacles,
        steps=base.steps,
        key=base.key,
        n_agents=jnp.asarray(n_agents, jnp.int32),
        n_obstacles=jnp.asarray(n_obstacles, jnp.int32),
    )


def hetero_step(
    state: HeteroState, velocity: Array, params: EnvParams
) -> Tuple[HeteroState, Transition]:
    """Advance one padded formation by one step.

    Mirrors the homogeneous step order (env/formation.py ``step``, reference
    ``simulate.py:70-118``) with the ring re-expressed over the dynamic agent
    count: integrate → clip/flag bounds → obstacle containment → reward on the
    dynamic ring → timeout (Q1 semantics under ``strict_parity``) → auto-reset
    → obs/metrics on the possibly-reset state. Padded agents receive zero
    velocity, zero reward, zero observation.
    """
    assert params.obs_mode == "ring", (
        "heterogeneous formations use ring obs; knn swarms are homogeneous "
        "(BASELINE.json configs 4 vs 5)"
    )
    n_max = params.num_agents
    mask = agent_mask(state.n_agents, n_max)
    prev_idx, next_idx = ring_gather_indices(state.n_agents, n_max)

    def gather_neighbors(x: Array, axis: int) -> Tuple[Array, Array]:
        del axis  # single formation: agent axis is leading for every consumer
        return x[prev_idx], x[next_idx]

    velocity = jnp.where(mask[:, None], velocity, 0.0)
    agents, out_of_bounds = integrate(state.agents, velocity, params)
    in_obstacle = _in_obstacle(agents, state.obstacles, params)

    pos_neighbors = gather_neighbors(agents, -2)
    reward, reward_terms = compute_reward(
        agents,
        state.goal,
        out_of_bounds,
        in_obstacle,
        params,
        neighbors_fn=gather_neighbors,
        pos_neighbors=pos_neighbors,
        neighbor_dist_target=desired_neighbor_dist(state.n_agents, params),
    )
    reward = jnp.where(mask, reward, 0.0)

    if params.strict_parity:
        done = state.steps > params.max_steps  # Q1 pre-increment check
    else:
        done = state.steps + 1 >= params.max_steps
        if params.goal_termination:
            dist_to_goal = jnp.linalg.norm(agents - state.goal, axis=-1)
            close = dist_to_goal < params.close_goal_dist
            done = done | jnp.where(mask, close, True).all()

    stepped = HeteroState(
        agents=agents,
        goal=state.goal,
        obstacles=state.obstacles,
        steps=state.steps + 1,
        key=state.key,
        n_agents=state.n_agents,
        n_obstacles=state.n_obstacles,
    )
    fresh = hetero_reset(state.key, params, state.n_agents, state.n_obstacles)
    next_state = tree_select(done, fresh, stepped)

    next_mask = mask  # n_agents is preserved across auto-reset
    next_prev, next_next = ring_gather_indices(next_state.n_agents, n_max)
    obs = compute_obs(
        next_state.agents,
        next_state.goal,
        params,
        pos_neighbors=(
            next_state.agents[next_prev],
            next_state.agents[next_next],
        ),
    )
    obs = jnp.where(next_mask[:, None], obs, 0.0)

    fmask = mask.astype(jnp.float32)
    active = fmask.sum()
    metrics = hetero_metrics(
        next_state.agents,
        next_state.goal,
        (next_state.agents[next_prev], next_state.agents[next_next]),
        next_mask,
    )
    metrics.update(
        {k: (v * fmask).sum() / active for k, v in reward_terms.items()}
    )
    metrics["reward"] = (reward * fmask).sum() / active
    metrics["num_active_agents"] = active

    return next_state, Transition(
        obs=obs, reward=reward, done=done, metrics=metrics
    )


def hetero_metrics(
    agents: Array,
    goal: Array,
    pos_neighbors: Tuple[Array, Array],
    mask: Array,
) -> Dict[str, Array]:
    """Masked progress metrics matching the homogeneous observability
    contract (env/formation.py ``compute_metrics``, reference
    ``simulate.py:238-254``); means/std run over active agents only."""
    fmask = mask.astype(jnp.float32)
    active = fmask.sum()
    dist_to_goal = jnp.linalg.norm(agents - goal[None, :], axis=-1)
    dist_right = jnp.linalg.norm(agents - pos_neighbors[1], axis=-1)
    mean_right = (dist_right * fmask).sum() / active
    var_right = (((dist_right - mean_right) ** 2) * fmask).sum() / (
        active - 1.0
    )
    return {
        "avg_dist_to_goal": (dist_to_goal * fmask).sum() / active,
        "ave_dist_to_neighbor": mean_right,
        "std_dist_to_neighbor": jnp.sqrt(var_right),
    }


def hetero_compute_obs(state: HeteroState, params: EnvParams) -> Array:
    """Masked observation for the current state (reset-time counterpart of
    the obs computed inside ``hetero_step``)."""
    n_max = params.num_agents
    mask = agent_mask(state.n_agents, n_max)
    prev_idx, next_idx = ring_gather_indices(state.n_agents, n_max)
    obs = compute_obs(
        state.agents,
        state.goal,
        params,
        pos_neighbors=(state.agents[prev_idx], state.agents[next_idx]),
    )
    return jnp.where(mask[:, None], obs, 0.0)


# ---------------------------------------------------------------------------
# Batched (vmapped) wrappers
# ---------------------------------------------------------------------------


def hetero_reset_batch(
    key: Array, params: EnvParams, n_agents: Array, n_obstacles: Array
) -> HeteroState:
    """Reset M formations; ``n_agents``/``n_obstacles`` are ``(M,)`` int32
    arrays (typically sampled by a curriculum stage, train/curriculum.py)."""
    keys = jax.random.split(key, n_agents.shape[0])
    return jax.vmap(hetero_reset, in_axes=(0, None, 0, 0))(
        keys, params, n_agents, n_obstacles
    )


def hetero_step_batch(
    state: HeteroState, velocity: Array, params: EnvParams
) -> Tuple[HeteroState, Transition]:
    """Step M padded formations; ``velocity`` is ``(M, N_max, 2)``."""
    return jax.vmap(hetero_step, in_axes=(0, 0, None))(state, velocity, params)


def make_hetero_vec_env(
    params: EnvParams,
) -> Tuple[Callable, Callable]:
    """Jitted ``(reset_fn, step_fn)`` with the L1 adapter contract
    (policy actions in [-1, 1], ``max_speed`` scaling — reference
    ``vectorized_env.py:68-82``) over padded heterogeneous batches.

    ``reset_fn(key, n_agents, n_obstacles) -> (state, obs)``;
    ``step_fn(state, actions) -> (state, transition)``.
    """

    @jax.jit
    def reset_fn(
        key: Array, n_agents: Array, n_obstacles: Array
    ) -> Tuple[HeteroState, Array]:
        state = hetero_reset_batch(key, params, n_agents, n_obstacles)
        obs = jax.vmap(hetero_compute_obs, in_axes=(0, None))(state, params)
        return state, obs

    @jax.jit
    def step_fn(
        state: HeteroState, actions: Array
    ) -> Tuple[HeteroState, Transition]:
        return hetero_step_batch(state, params.max_speed * actions, params)

    return reset_fn, step_fn
