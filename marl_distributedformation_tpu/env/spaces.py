"""Space metadata for the formation environment.

The reference exposes gymnasium ``spaces.Box`` metadata on its VecEnv
adapter (vectorized_env.py:34-35): per-agent action ``(2,)`` in [-1, 1] and
observation ``(obs_dim,)`` nominally in [-1, 1] (bounds are declarative, not
enforced — SURVEY.md Q10). This module carries the same metadata without a
gym dependency in the compute path; the compat layer converts to gymnasium
spaces when a frontend needs them.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from marl_distributedformation_tpu.env.types import EnvParams


@dataclasses.dataclass(frozen=True)
class Box:
    low: float
    high: float
    shape: Tuple[int, ...]
    dtype: type = np.float32

    def sample(self, rng: np.random.Generator) -> np.ndarray:
        return rng.uniform(self.low, self.high, self.shape).astype(self.dtype)

    def to_gymnasium(self):
        from gymnasium import spaces  # local import: frontends only

        return spaces.Box(
            low=self.low, high=self.high, shape=self.shape, dtype=self.dtype
        )


def action_space(params: EnvParams) -> Box:
    """Per-agent action space (reference vectorized_env.py:34)."""
    return Box(low=-1.0, high=1.0, shape=(params.act_dim,))


def observation_space(params: EnvParams) -> Box:
    """Per-agent observation space (reference vectorized_env.py:35)."""
    return Box(low=-1.0, high=1.0, shape=(params.obs_dim,))
