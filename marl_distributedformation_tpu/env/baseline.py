"""Hand-crafted potential-field baseline controller, as a pure function.

Reimplements the reference's scripted ``control`` (simulate.py:256-319): a
spring force toward the desired spacing with both ring neighbors, a spring
toward the diametrically-opposite agent (diameter spacing), obstacle
repulsion, and goal attraction. It is the non-learned baseline used for
return-parity testing (BASELINE.json config 1).

Deviations from the reference, on purpose:
- distances are clamped to ``eps`` before normalizing directions (the
  reference divides by raw norms and would NaN on coincident agents);
- odd ``num_agents`` is supported by rolling ``N // 2`` positions (the
  reference asserts even N — SURVEY.md Q11); for even N this is identical.
Like the reference (Q11), the controller uses its own ``desired_radius=40``,
not the env reward's 60 — baseline and learned policy optimize different
formation sizes, and the parity gate compares against this exact controller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.env.types import EnvParams

Array = jax.Array

CONTROL_DESIRED_RADIUS = 40.0  # reference simulate.py:259
FORMATION_GAIN = 0.02  # simulate.py:290-292
OBSTACLE_GAIN = 0.3  # simulate.py:304
GOAL_GAIN = 0.01  # simulate.py:315


def _unit(vec: Array, eps: float = 1e-8) -> tuple[Array, Array]:
    dist = jnp.linalg.norm(vec, axis=-1)
    return vec / jnp.maximum(dist, eps)[..., None], dist


def control(
    agents: Array, goal: Array, obstacles: Array, params: EnvParams
) -> Array:
    """Per-agent velocity command ``(N, 2)`` for the formation controller.

    Pure function of positions — drive it through ``env.step`` with raw
    velocities (the L0 contract), exactly like the reference's
    ``control(i, env)`` does via ``env.step(f_formation + f_obstacle +
    f_goal)`` (simulate.py:319).
    """
    num_agents = agents.shape[0]

    # Ring neighbors (simulate.py:262-275): shift A = next, shift B = prev.
    shift_a = jnp.roll(agents, -1, axis=0)
    shift_b = jnp.roll(agents, 1, axis=0)
    dir_a, dist_a = _unit(shift_a - agents)
    dir_b, dist_b = _unit(shift_b - agents)

    # Diametrically opposite agent (simulate.py:278-284).
    opposite = jnp.roll(agents, num_agents // 2, axis=0)
    dir_opp, dist_opp = _unit(opposite - agents)

    desired_dist = np.pi * CONTROL_DESIRED_RADIUS / num_agents  # simulate.py:286

    f_formation = (
        FORMATION_GAIN * (dist_a - desired_dist)[:, None] * dir_a
        + FORMATION_GAIN * (dist_b - desired_dist)[:, None] * dir_b
        + FORMATION_GAIN
        * (dist_opp - 2.0 * CONTROL_DESIRED_RADIUS)[:, None]
        * dir_opp
    )
    f_formation = jnp.clip(f_formation, -1.0, 1.0)  # simulate.py:293

    # Obstacle repulsion (simulate.py:296-307), vectorized over obstacles.
    if obstacles.shape[0] > 0:
        offsets = agents[None, :, :] - obstacles[:, None, :]  # (K, N, 2)
        dists = jnp.linalg.norm(offsets, axis=-1)
        dirs = offsets / jnp.maximum(dists, 1e-8)[..., None]
        avoid_dist = params.obstacle_size * 2.0
        repel = jnp.maximum(-OBSTACLE_GAIN * (dists - avoid_dist), 0.0)
        f_obstacle = (repel[..., None] * dirs).sum(axis=0)
    else:
        f_obstacle = jnp.zeros_like(f_formation)

    # Goal attraction toward the controller's own radius (simulate.py:309-317).
    goal_dir, goal_dist = _unit(agents - goal)
    f_goal = -(GOAL_GAIN * (goal_dist - CONTROL_DESIRED_RADIUS))[:, None] * goal_dir
    f_goal = jnp.clip(f_goal, -1.0, 1.0)

    return f_formation + f_obstacle + f_goal
