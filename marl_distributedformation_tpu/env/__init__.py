"""Pure-functional formation environment (see ``formation.py``)."""

from marl_distributedformation_tpu.env.types import (  # noqa: F401
    EnvParams,
    FormationState,
    Transition,
    tree_select,
)
from marl_distributedformation_tpu.env.formation import (  # noqa: F401
    compute_metrics,
    compute_obs,
    compute_reward,
    make_vec_env,
    reset,
    reset_batch,
    step,
    step_batch,
)
from marl_distributedformation_tpu.env.spaces import (  # noqa: F401
    Box,
    action_space,
    observation_space,
)
from marl_distributedformation_tpu.env.baseline import control  # noqa: F401
from marl_distributedformation_tpu.env.hetero import (  # noqa: F401
    HeteroState,
    agent_mask,
    hetero_reset,
    hetero_reset_batch,
    hetero_step,
    hetero_step_batch,
    make_hetero_vec_env,
)
