"""Diagonal-Gaussian action distribution with state-independent log-std.

Functional equivalent of the action distribution the reference gets from SB3
(``'MlpPolicy'`` builds a ``DiagGaussianDistribution`` with one learned
``log_std`` vector shared across states; reference vectorized_env.py:126).
All ops are shape-polymorphic over leading batch axes.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

Array = jax.Array

_LOG_2PI = math.log(2.0 * math.pi)


def sample(key: Array, mean: Array, log_std: Array) -> Array:
    """Reparameterized draw: ``mean + exp(log_std) * eps``."""
    eps = jax.random.normal(key, mean.shape, mean.dtype)
    return mean + jnp.exp(log_std) * eps


def log_prob(actions: Array, mean: Array, log_std: Array) -> Array:
    """Log density summed over the action dimension (independent dims)."""
    z = (actions - mean) * jnp.exp(-log_std)
    per_dim = -0.5 * (z**2 + _LOG_2PI) - log_std
    return per_dim.sum(axis=-1)


def entropy(log_std: Array) -> Array:
    """Differential entropy; state-independent, shape ``()``."""
    return (log_std + 0.5 * (1.0 + _LOG_2PI)).sum()


def mode(mean: Array) -> Array:
    """Deterministic action (used by ``predict(deterministic=True)``
    playback, reference visualize_policy.py:16)."""
    return mean
