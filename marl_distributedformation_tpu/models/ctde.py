"""Centralized-training / decentralized-execution (CTDE) actor-critic.

BASELINE.json config 3: "20-agent formation, per-agent local obs, CTDE
centralized critic". The reference has no centralized critic — its SB3
``'MlpPolicy'`` value function sees only one agent's local observation
(vectorized_env.py:32,126: each agent is its own SB3 "environment") — so
value estimates cannot account for the other agents' positions even though
rewards are neighbor-mixed (simulate.py:222-229). This module adds that
capability the TPU-native way:

- **Actor** — identical per-agent tanh MLP over local observations with
  shared parameters (decentralized execution: deploying the policy still
  needs only local information).
- **Critic** — a permutation-invariant deep-set over the whole formation:
  per-agent embeddings are mean-pooled into a global formation summary that
  is concatenated back onto each agent's embedding before the value head.
  Every tensor op is a batched matmul or reduction along the agent axis, so
  the whole formation's critic evaluates as a handful of MXU calls — no
  per-agent loop, any N, one set of weights.

The pooled design (rather than concatenating all N observations into one
flat critic input, the classic MADDPG layout) keeps the parameter count
independent of N, stays permutation-equivariant (value_i is invariant to
re-labeling the *other* agents), and maps onto padding/masking for
heterogeneous formations.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from flax import linen as nn

from marl_distributedformation_tpu.models.common import (
    PolicyHead,
    PooledValueHead,
    hidden_init,
)

Array = jax.Array


class CTDEActorCritic(nn.Module):
    """Shared per-agent actor + centralized deep-set critic.

    ``__call__`` takes ``obs`` with the agent axis second-to-last —
    ``(..., N, obs_dim)`` — and returns per-agent ``(mean, log_std, value)``
    with ``value`` shaped ``(..., N)``. Unlike ``MLPActorCritic`` (which is
    agent-factored and can be applied to any flattening of agents), this
    module must see whole formations: the trainer detects ``per_formation``
    and minibatches over formations instead of agent-transitions.

    ``mask``: optional ``(..., N)`` float/bool validity mask for padded
    (heterogeneous) formations — masked agents are excluded from the pooled
    summary and get value 0.
    """

    act_dim: int = 2
    hidden: Sequence[int] = (64, 64)
    embed_dim: int = 64
    log_std_init: float = 0.0
    per_formation: bool = True  # trainer flag: minibatch whole formations

    @nn.compact
    def __call__(
        self, obs: Array, mask: Optional[Array] = None
    ) -> Tuple[Array, Array, Array]:
        # Actor: per-agent, local-obs only (matches MLPActorCritic's actor
        # tower so decentralized execution is unchanged).
        mean = PolicyHead(self.act_dim, self.hidden, name="actor")(obs)

        # Critic: embed each agent, pool over the agent axis (-2), broadcast
        # the formation summary back to every agent.
        emb = nn.tanh(
            nn.Dense(self.embed_dim, kernel_init=hidden_init, name="vf_embed")(
                obs
            )
        )
        value = PooledValueHead(self.hidden, name="critic")(emb, mask)

        log_std = self.param(
            "log_std",
            nn.initializers.constant(self.log_std_init),
            (self.act_dim,),
        )
        return mean, log_std, value
