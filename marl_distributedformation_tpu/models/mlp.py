"""MLP actor-critic matching the reference's SB3 ``'MlpPolicy'`` shape.

The reference trains ``PPO('MlpPolicy', ...)`` (vectorized_env.py:126): two
separate tanh MLPs of width [64, 64] for policy and value, orthogonal init
(gain sqrt(2) hidden, 0.01 action head, 1.0 value head), and a learned
state-independent ``log_std``.

``log_std_init`` is a *real* knob here: the reference sets
``model.policy.log_std_init = -2`` after construction, which is a no-op —
SB3 had already created the parameter at 0.0 (SURVEY.md Q5). Parity default
is therefore 0.0; pass -2.0 to get what the reference author intended.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

Array = jax.Array


class MLPActorCritic(nn.Module):
    """Per-agent actor-critic over local observations.

    Every agent in every formation shares these parameters — the central
    MARL trick the reference implements by flattening M formations x N
    agents into ``num_envs = M*N`` SB3 environments (vectorized_env.py:32,
    SURVEY.md §2.1 #10).
    """

    act_dim: int = 2
    hidden: Sequence[int] = (64, 64)
    log_std_init: float = 0.0

    @nn.compact
    def __call__(self, obs: Array) -> Tuple[Array, Array, Array]:
        """Returns ``(action_mean, log_std, value)``; ``obs`` may carry any
        leading batch axes."""
        hidden_init = nn.initializers.orthogonal(jnp.sqrt(2.0))

        pi = obs
        for i, width in enumerate(self.hidden):
            pi = nn.tanh(
                nn.Dense(width, kernel_init=hidden_init, name=f"pi_{i}")(pi)
            )
        mean = nn.Dense(
            self.act_dim,
            kernel_init=nn.initializers.orthogonal(0.01),
            name="pi_head",
        )(pi)

        vf = obs
        for i, width in enumerate(self.hidden):
            vf = nn.tanh(
                nn.Dense(width, kernel_init=hidden_init, name=f"vf_{i}")(vf)
            )
        value = nn.Dense(
            1, kernel_init=nn.initializers.orthogonal(1.0), name="vf_head"
        )(vf)

        log_std = self.param(
            "log_std",
            nn.initializers.constant(self.log_std_init),
            (self.act_dim,),
        )
        return mean, log_std, value.squeeze(-1)
