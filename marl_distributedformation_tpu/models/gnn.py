"""Graph-network actor-critic over the k-NN observation graph.

BASELINE.json config 4: "100-agent swarm with k-nearest-neighbor obs graph
+ GNN policy" — new capability beyond the reference (whose policy is a
per-agent MLP over a fixed ring view, vectorized_env.py:126; SURVEY.md §5
"long-context" note). Design:

- Nodes are agents; edges are each agent's ``k`` nearest neighbors, carried
  inside the observation produced by ``env.formation.compute_obs_knn``
  (offsets, distances, and neighbor indices — indices exact in float32).
- ``rounds`` of message passing: gather neighbor embeddings with one
  ``take_along_axis`` per round (a dense gather XLA lowers well), compute
  edge messages from [h_i, h_j, edge_feats] with a shared MLP (batched
  matmuls on the MXU — no per-edge loop), mean-aggregate, GRU-free residual
  update. An agent's action therefore depends on its ``rounds``-hop
  neighborhood — a learned communication radius, decentralized-executable
  by running the same stack on each agent's local subgraph.
- Critic is centralized CTDE-style: masked mean-pool of final node
  embeddings appended to each node before the value head.

Everything is static-shaped: (N, k) gathers, (N, k, F) edge batches —
``vmap`` over M formations turns the whole swarm forward pass into a few
large MXU matmuls.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from marl_distributedformation_tpu.models.common import (
    PolicyHead,
    PooledValueHead,
    hidden_init,
)

Array = jax.Array


def parse_knn_obs(
    obs: Array, k: int, goal_in_obs: bool = True
) -> Tuple[Array, Array, Array]:
    """Split a ``compute_obs_knn`` layout into (node_feats, edge_feats, idx).

    ``obs``: ``(..., N, 2 + 3k [+2] + k)``. Returns node features
    ``(..., N, 2 [+2])`` (own pos, rel goal), edge features ``(..., N, k, 3)``
    (offset, dist), and int32 neighbor indices ``(..., N, k)``.
    """
    own = obs[..., :2]
    offsets = obs[..., 2 : 2 + 2 * k]
    dists = obs[..., 2 + 2 * k : 2 + 3 * k]
    node_parts = [own]
    if goal_in_obs:
        node_parts.append(obs[..., 2 + 3 * k : 4 + 3 * k])
    idx = obs[..., -k:].astype(jnp.int32)
    edge = jnp.concatenate(
        [
            offsets.reshape(*offsets.shape[:-1], k, 2),
            dists[..., None],
        ],
        axis=-1,
    )
    return jnp.concatenate(node_parts, axis=-1), edge, idx


def gather_nodes(h: Array, idx: Array) -> Array:
    """``h (..., N, E)``, ``idx (..., N, k)`` -> neighbor embeddings
    ``(..., N, k, E)`` via one flat ``take_along_axis`` on the node axis."""
    n, k = idx.shape[-2], idx.shape[-1]
    flat = jnp.take_along_axis(
        h, idx.reshape(*idx.shape[:-2], n * k, 1), axis=-2
    )
    return flat.reshape(*idx.shape[:-2], n, k, h.shape[-1])


class GNNActorCritic(nn.Module):
    """Message-passing actor-critic for k-NN swarm observations.

    ``__call__(obs, mask=None)`` takes ``obs (..., N, obs_dim)`` in the
    ``compute_obs_knn`` layout and returns per-agent
    ``(action_mean, log_std, value)``. ``mask (..., N)`` marks valid agents
    in padded (heterogeneous) formations: messages from padded neighbors are
    zeroed, padded agents are excluded from the critic pool, and their
    values are 0.
    """

    k: int
    act_dim: int = 2
    embed_dim: int = 64
    msg_dim: int = 64
    rounds: int = 2
    hidden: Sequence[int] = (64,)
    goal_in_obs: bool = True
    log_std_init: float = 0.0
    per_formation: bool = True  # trainer flag: minibatch whole formations

    @nn.compact
    def __call__(
        self, obs: Array, mask: Optional[Array] = None
    ) -> Tuple[Array, Array, Array]:
        node, edge, idx = parse_knn_obs(obs, self.k, self.goal_in_obs)

        h = nn.tanh(
            nn.Dense(self.embed_dim, kernel_init=hidden_init, name="embed")(
                node
            )
        )
        for r in range(self.rounds):
            h_nb = gather_nodes(h, idx)  # (..., N, k, E)
            h_self = jnp.broadcast_to(
                h[..., :, None, :], h_nb.shape
            )
            msg_in = jnp.concatenate([h_self, h_nb, edge], axis=-1)
            msg = nn.tanh(
                nn.Dense(
                    self.msg_dim, kernel_init=hidden_init, name=f"msg_{r}"
                )(msg_in)
            )
            if mask is not None:
                nb_valid = gather_nodes(
                    mask.astype(msg.dtype)[..., None], idx
                )  # (..., N, k, 1)
                msg = msg * nb_valid
                agg = msg.sum(axis=-2) / jnp.maximum(
                    nb_valid.sum(axis=-2), 1.0
                )
            else:
                agg = msg.mean(axis=-2)
            upd = nn.tanh(
                nn.Dense(
                    self.embed_dim, kernel_init=hidden_init, name=f"upd_{r}"
                )(jnp.concatenate([h, agg, node], axis=-1))
            )
            h = h + upd  # residual: round r refines round r-1

        # Actor head: local (r-hop) information only.
        mean = PolicyHead(self.act_dim, self.hidden, name="actor")(h)

        # Critic: CTDE pooled global context.
        value = PooledValueHead(self.hidden, name="critic")(h, mask)

        log_std = self.param(
            "log_std",
            nn.initializers.constant(self.log_std_init),
            (self.act_dim,),
        )
        return mean, log_std, value
