"""Shared building blocks for actor-critic models.

The per-agent policy tower and the CTDE pooled value head are used by both
``CTDEActorCritic`` (raw local obs) and ``GNNActorCritic`` (message-passed
embeddings); keeping them here keeps the two in lockstep.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

Array = jax.Array

# Host-side sqrt: jnp.sqrt here would run a device computation at import
# time, initializing the JAX backend before entry points can pick a platform
# (utils/config.py setup_platform) — on this image that means a TPU-tunnel
# roundtrip just to import the package.
hidden_init = nn.initializers.orthogonal(2.0**0.5)


def masked_mean_pool(x: Array, mask: Optional[Array]) -> Array:
    """Mean over the agent axis (-2), ignoring masked agents; keepdims.
    ``x (..., N, E)``, ``mask (..., N)`` or None -> ``(..., 1, E)``."""
    if mask is None:
        return x.mean(axis=-2, keepdims=True)
    m = mask.astype(x.dtype)[..., None]
    return (x * m).sum(axis=-2, keepdims=True) / jnp.maximum(
        m.sum(axis=-2, keepdims=True), 1.0
    )


class PolicyHead(nn.Module):
    """Per-agent action-mean tower: tanh MLP + orthogonal(0.01) head, the
    SB3 ``'MlpPolicy'`` actor shape (reference vectorized_env.py:126)."""

    act_dim: int
    hidden: Sequence[int]

    @nn.compact
    def __call__(self, x: Array) -> Array:
        for i, width in enumerate(self.hidden):
            x = nn.tanh(
                nn.Dense(width, kernel_init=hidden_init, name=f"pi_{i}")(x)
            )
        return nn.Dense(
            self.act_dim,
            kernel_init=nn.initializers.orthogonal(0.01),
            name="pi_head",
        )(x)


class PooledValueHead(nn.Module):
    """Centralized (CTDE) per-agent value head: concat each agent's features
    with the masked formation-mean pool, run a tanh tower, and zero values of
    masked agents."""

    hidden: Sequence[int]

    @nn.compact
    def __call__(self, x: Array, mask: Optional[Array] = None) -> Array:
        pooled = masked_mean_pool(x, mask)
        vf = jnp.concatenate([x, jnp.broadcast_to(pooled, x.shape)], axis=-1)
        for i, width in enumerate(self.hidden):
            vf = nn.tanh(
                nn.Dense(width, kernel_init=hidden_init, name=f"vf_{i}")(vf)
            )
        value = nn.Dense(
            1, kernel_init=nn.initializers.orthogonal(1.0), name="vf_head"
        )(vf).squeeze(-1)
        if mask is not None:
            value = value * mask.astype(value.dtype)
        return value
