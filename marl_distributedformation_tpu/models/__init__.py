"""Policy/value networks (flax) and action distributions."""

from marl_distributedformation_tpu.models.mlp import MLPActorCritic  # noqa: F401
from marl_distributedformation_tpu.models.ctde import CTDEActorCritic  # noqa: F401
from marl_distributedformation_tpu.models.gnn import GNNActorCritic  # noqa: F401
from marl_distributedformation_tpu.models import distributions  # noqa: F401
