"""Multi-host distributed runtime: process wire-up, hybrid DCN x ICI meshes,
and host-local data placement.

The reference has no distributed communication backend at all — one OS
process, CPU tensors, a sequential formation loop (SURVEY.md §2.1, reference
vectorized_env.py:71-81). This module is the TPU-native equivalent designed
fresh: ``jax.distributed`` wires processes into one JAX runtime, meshes are
laid out so the heavy collectives (gradient psum over 'dp', ring halo
ppermute over 'sp') ride ICI *within* a slice while only the slice-level
gradient reduction crosses DCN, and every host materializes only its own
formation shard (``jax.make_array_from_process_local_data``) so no
full-batch array ever exists on one host.

Single-process (including the CPU test mesh and the single tunneled chip)
everything degrades to a no-op / plain single-slice mesh, so the same
training code runs unchanged from laptop CPU to multi-host pod.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from marl_distributedformation_tpu.parallel.mesh import make_mesh

_initialized = False


# Env markers of the cluster launchers jax.distributed's auto-detection
# understands (Cloud TPU pods/multislice, Slurm, Open MPI). When one is
# present and no explicit coordinator config was given,
# ``jax.distributed.initialize()`` is called with NO arguments so jax's
# cluster detection resolves coordinator/process info — merely *not* calling
# initialize() would silently run N independent single-host jobs (round-1
# ADVICE finding: jax only auto-detects when initialize() is actually
# called).
_CLUSTER_ENV_MARKERS = (
    "TPU_WORKER_HOSTNAMES",  # Cloud TPU pod slice
    "TPU_WORKER_ID",
    "MEGASCALE_COORDINATOR_ADDRESS",  # multislice
    "SLURM_JOB_NUM_NODES",
    "OMPI_MCA_orte_hnp_uri",
)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Idempotent ``jax.distributed.initialize`` wrapper.

    Arguments default to the standard env vars (``JAX_COORDINATOR_ADDRESS``
    / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``). Without explicit config,
    a recognized cluster launch environment (TPU pod, multislice, Slurm,
    OMPI — ``_CLUSTER_ENV_MARKERS``) triggers argument-free
    ``jax.distributed.initialize()`` so jax's own cluster detection wires
    the processes together. Returns True if a multi-process runtime was (or
    already is) up, False for plain single-process operation — callers never
    need to branch on the launch mode themselves.
    """
    global _initialized
    # Resolve the launch configuration BEFORE touching anything that could
    # initialize the XLA backend: jax.distributed.initialize() must run
    # first or it raises, and even jax.process_count() initializes backends.
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = (
        num_processes if num_processes is not None
        else (int(env_np) if env_np else None)
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = (
        process_id if process_id is not None
        else (int(env_pid) if env_pid else None)
    )
    if _initialized:
        return jax.process_count() > 1
    if coordinator_address is None or num_processes in (None, 1):
        if num_processes != 1 and any(
            os.environ.get(v) for v in _CLUSTER_ENV_MARKERS
        ):
            # Cluster launch without explicit wiring: let jax detect it.
            try:
                jax.distributed.initialize()
            except Exception as e:  # noqa: BLE001 — degrade to single-proc
                print(
                    "[distributed] cluster env detected but "
                    f"jax.distributed.initialize() failed ({e!r}); "
                    "continuing single-process"
                )
        # else: plain single-process launch — safe to query below.
        _initialized = True
        return jax.process_count() > 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def is_coordinator() -> bool:
    """True on the process that owns host-side side effects (checkpoint
    writes, metric emission). Always True single-process."""
    return jax.process_index() == 0


def make_hybrid_mesh(
    axis_sizes: Dict[str, int], dcn_axis: str = "dp"
) -> Mesh:
    """Build a mesh whose ``dcn_axis`` outer factor spans hosts over DCN
    while everything else stays on ICI.

    For a multi-slice/multi-host run the device array comes from
    ``mesh_utils.create_hybrid_device_mesh``: ``dcn_axis`` is factored into
    ``num_slices x per_slice`` so that neighboring mesh coordinates along
    every other axis (and within a slice along ``dcn_axis``) are ICI
    neighbors — the gradient psum then does a fast ICI reduce-scatter per
    slice and only the slice-partial crosses DCN. Single-slice runs fall
    back to :func:`parallel.mesh.make_mesh` unchanged.

    ``axis_sizes`` follows ``make_mesh``'s convention (-1 = remaining
    devices); ``dcn_axis`` must be present and divisible by the number of
    slices.
    """
    devs = jax.devices()
    slice_ids = {getattr(d, "slice_index", None) for d in devs}
    if None not in slice_ids and len(slice_ids) > 1:
        # Real multi-slice TPU: granule = slice (DCN between slices).
        num_slices = len(slice_ids)
        process_is_granule = False
    elif jax.process_count() > 1:
        # Multi-process without slice topology (single-slice pod, GPU/CPU
        # clusters): treat each process as the DCN granule.
        num_slices = jax.process_count()
        process_is_granule = True
    else:
        return make_mesh(axis_sizes)

    from marl_distributedformation_tpu.parallel.mesh import (
        resolve_axis_sizes,
    )

    n_devices = len(devs)
    names, sizes = resolve_axis_sizes(axis_sizes, n_devices)
    assert dcn_axis in names, f"dcn_axis {dcn_axis!r} not in {names}"
    sizes = list(sizes)
    total = int(np.prod(sizes))
    if total != n_devices:
        raise ValueError(
            f"multi-host mesh {dict(zip(names, sizes))} covers {total} of "
            f"{n_devices} global devices. Unlike single-process meshes, a "
            "multi-host mesh must span every device (each process needs "
            "addressable devices in the mesh) — use -1 for one axis to "
            "absorb the remainder, e.g. mesh={dp: -1}"
        )
    dcn_idx = names.index(dcn_axis)
    assert sizes[dcn_idx] % num_slices == 0, (
        f"{dcn_axis}={sizes[dcn_idx]} must be divisible by "
        f"num_slices={num_slices}"
    )
    per_slice = list(sizes)
    per_slice[dcn_idx] //= num_slices
    dcn_shape = [1] * len(sizes)
    dcn_shape[dcn_idx] = num_slices
    devices = mesh_utils.create_hybrid_device_mesh(
        tuple(per_slice),
        tuple(dcn_shape),
        devices=devs,
        process_is_granule=process_is_granule,
    )
    return Mesh(devices, names)


def local_formation_slice(
    num_formations: int, process_index: Optional[int] = None
) -> Tuple[int, int]:
    """``(start, count)`` of this host's contiguous formation shard.

    The formation axis is split evenly across processes (multi-host data
    parallelism); M must divide by the process count so every device gets
    identical static shapes.
    """
    n_proc = jax.process_count()
    assert num_formations % n_proc == 0, (
        f"num_formations={num_formations} must be divisible by "
        f"process_count={n_proc}"
    )
    count = num_formations // n_proc
    pid = jax.process_index() if process_index is None else process_index
    return pid * count, count


def global_from_local(tree: Any, mesh: Mesh, spec: P = P("dp")) -> Any:
    """Assemble a globally-sharded pytree from each host's LOCAL shard.

    Every leaf carries this host's rows of the leading (formation) axis;
    the returned leaves are global ``jax.Array``s sharded by ``spec`` over
    ``mesh`` whose addressable shards are exactly the local data — no
    host ever holds the full batch. Single-process this is equivalent to
    ``device_put`` with the same sharding.
    """
    sharding = NamedSharding(mesh, spec)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        tree,
    )


def reset_batch_sharded(
    key: Any, params: Any, num_formations: int, mesh: Mesh
) -> Any:
    """Multi-host-safe ``env.formation.reset_batch``: every host constructs
    ONLY its own formation shard and the result is a globally 'dp'-sharded
    ``FormationState``.

    The per-formation PRNG streams are identical to the single-host
    ``reset_batch`` (keys are split globally, then sliced), so scaling the
    host count never changes the sampled initial states.
    """
    from marl_distributedformation_tpu.env.formation import reset

    start, count = local_formation_slice(num_formations)
    keys = jax.random.split(key, num_formations)[start : start + count]
    local = jax.vmap(reset, in_axes=(0, None))(keys, params)
    return global_from_local(local, mesh)


def hetero_reset_batch_sharded(
    key: Any, params: Any, n_agents: Any, n_obstacles: Any, mesh: Mesh
) -> Any:
    """Multi-host-safe ``env.hetero.hetero_reset_batch``: the curriculum's
    per-formation counts are computed identically on every host (same PRNG
    key), but each host materializes only its formation slice of the padded
    state — mirroring :func:`reset_batch_sharded` for the hetero trainer's
    ``start_stage`` (round-1 ADVICE: building the full batch per host both
    crashed ``device_put`` across processes and violated the per-host-shard
    design). Single-process this equals ``hetero_reset_batch`` placed on the
    mesh.
    """
    from marl_distributedformation_tpu.env.hetero import hetero_reset

    num_formations = int(n_agents.shape[0])
    start, count = local_formation_slice(num_formations)
    keys = jax.random.split(key, num_formations)[start : start + count]
    local = jax.vmap(hetero_reset, in_axes=(0, None, 0, 0))(
        keys,
        params,
        n_agents[start : start + count],
        n_obstacles[start : start + count],
    )
    return global_from_local(local, mesh)
