"""Device-mesh parallelism: dp over formations, ring exchange over agents."""

from marl_distributedformation_tpu.parallel.mesh import (  # noqa: F401
    formation_sharding,
    make_mesh,
    make_shard_fn,
    replicate,
    replicated,
    shard_batch,
)
from marl_distributedformation_tpu.parallel.ring import (  # noqa: F401
    make_ring_step,
    place_ring_state,
)
