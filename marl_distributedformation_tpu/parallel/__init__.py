"""Device-mesh parallelism: dp over formations, ring exchange over agents,
multi-host wire-up and hybrid DCN x ICI meshes."""

from marl_distributedformation_tpu.parallel.distributed import (  # noqa: F401
    global_from_local,
    hetero_reset_batch_sharded,
    init_distributed,
    is_coordinator,
    local_formation_slice,
    make_hybrid_mesh,
    reset_batch_sharded,
)
from marl_distributedformation_tpu.parallel.mesh import (  # noqa: F401
    formation_sharding,
    make_dp_step,
    make_mesh,
    make_shard_fn,
    replicate,
    replicated,
    shard_batch,
)
from marl_distributedformation_tpu.parallel.ring import (  # noqa: F401
    make_ring_step,
    place_ring_state,
)
