"""Device-mesh construction and sharding placement.

The reference has no distributed machinery at all (SURVEY.md §2.1: no
NCCL/MPI/multi-process anything — "distributed" in its name means
*decentralized control*). The TPU-native scaling story is therefore designed
fresh: formations are the data axis, sharded over a ``jax.sharding.Mesh``
('dp'); parameters are replicated; XLA inserts the gradient ``psum`` over ICI
because the jitted update consumes dp-sharded minibatches with replicated
params. An optional 'sp' axis shards the *agent* ring dimension for very
large swarms (see ``parallel/ring.py``).

Works identically on real TPU meshes and on CPU test meshes created with
``--xla_force_host_platform_device_count``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from marl_distributedformation_tpu.jax_compat import shard_map


def resolve_axis_sizes(
    axis_sizes: Dict[str, int], n_devices: int
) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    """Resolve a ``{name: size}`` spec against the device count: a single
    -1 means "all remaining devices"; the total may not exceed
    ``n_devices``. Shared by :func:`make_mesh` and
    ``distributed.make_hybrid_mesh``."""
    names = tuple(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n_devices // known
    total = int(np.prod(sizes))
    if total > n_devices:
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices; "
            f"only {n_devices} available"
        )
    return names, tuple(sizes)


def make_mesh(axis_sizes: Dict[str, int]) -> Mesh:
    """Build a mesh with named axes, e.g. ``{"dp": 4}`` or
    ``{"dp": 4, "sp": 2}``. Total size must divide the device count; use
    size -1 for one axis to mean "all remaining devices"."""
    names, sizes = resolve_axis_sizes(axis_sizes, len(jax.devices()))
    total = int(np.prod(sizes))
    devices = mesh_utils.create_device_mesh(
        tuple(sizes), devices=jax.devices()[:total]
    )
    return Mesh(devices, names)


def formation_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading formation axis M over 'dp'; everything else
    (agents, coordinates) stays local to the chip."""
    return NamedSharding(mesh, P("dp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree whose leaves all carry a leading formation axis."""
    return jax.device_put(tree, formation_sharding(mesh))


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(tree, replicated(mesh))


def make_dp_step(params: Any, mesh: Mesh) -> Callable:
    """Batched env step explicitly shard_mapped over 'dp': each device steps
    only its local formation block (the step has no cross-formation
    communication, so no collectives are needed).

    Required for knn observations on a mesh: the fused neighbor kernel
    (ops/knn_pallas.py) is a Mosaic custom call the XLA SPMD partitioner
    cannot split, so under plain ``jit`` the ``impl="auto"`` dispatch falls
    back to the XLA search (ops/knn.py ``_spmd_partitioner_controlled``).
    Inside this shard_map the kernel sees a per-device local ``(m_local, N,
    2)`` block — Manual mesh axes — and "auto" selects Pallas again.
    """
    from marl_distributedformation_tpu.env.formation import step_batch

    spec = P("dp")

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(spec, spec),
        # pallas_call outputs carry no varying-across-mesh metadata, which
        # trips the vma checker; the step is collective-free so the check
        # buys nothing here.
        check_vma=False,
    )
    def dp_step(state, velocity):
        return step_batch(state, velocity, params)

    return dp_step


def make_shard_fn(
    axis_sizes: Optional[Dict[str, int]] = None,
    mesh: Optional[Mesh] = None,
) -> Callable[[Any, Any, Any], Tuple[Any, Any, Any]]:
    """Build the ``shard_fn`` hook ``Trainer`` applies after initialization:
    replicate the train state, shard env state + obs over 'dp'.

    The jitted train iteration then runs SPMD: rollouts and minibatch grads
    are computed on local formation shards and XLA all-reduces gradients
    (replicated params + sharded batch => psum over 'dp' on ICI).
    """
    the_mesh = mesh or make_mesh(axis_sizes or {"dp": len(jax.devices())})
    extra_axes = set(the_mesh.shape) - {"dp", "sp"}
    if extra_axes:
        raise ValueError(
            f"shard_fn places the 'dp' (formation) and 'sp' (agent) axes; "
            f"mesh has unknown axes {sorted(extra_axes)}"
        )
    has_sp = "sp" in the_mesh.shape

    def shard_fn(train_state, env_state, obs):
        dp = the_mesh.shape["dp"]
        m = obs.shape[0]
        if m % dp != 0:
            raise ValueError(
                f"num_formations={m} not divisible by dp={dp}"
            )
        if has_sp:
            # Agent-axis sharding: agents/obs P('dp','sp'), per-formation
            # leaves P('dp') — the layout parallel/ring.py's halo-exchange
            # step consumes. Trainer pairs this with make_ring_step.
            from marl_distributedformation_tpu.parallel.ring import (
                place_ring_state,
            )

            return (
                replicate(train_state, the_mesh),
                place_ring_state(env_state, the_mesh),
                jax.device_put(
                    obs, NamedSharding(the_mesh, P("dp", "sp"))
                ),
            )
        return (
            replicate(train_state, the_mesh),
            shard_batch(env_state, the_mesh),
            shard_batch(obs, the_mesh),
        )

    shard_fn.mesh = the_mesh
    return shard_fn
