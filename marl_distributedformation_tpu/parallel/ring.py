"""Agent-axis ('sp') sharding with ring halo exchange over ICI.

The environment's interaction graph is a ring: every agent reads only its two
ring neighbors, for observations (reference simulate.py:162-167) and reward
mixing (simulate.py:222-229). That locality maps exactly onto a ring of TPU
devices — the same communication shape as ring attention for long sequences:
shard the agent axis N across the 'sp' mesh axis and exchange a ONE-AGENT
halo with each ring-neighbor device via ``lax.ppermute``, instead of
all-gathering the formation. Per step each device exchanges three halos
(pre-reset positions, per-agent rewards, post-reset positions) of
``m_local`` rows each, independent of N — swarm size scales linearly with
devices at constant ICI traffic per device.

``obs_mode="knn"`` swarms shard on 'sp' too (round 3): reward mixing and
metrics keep the constant-traffic ring halos, while the observation's
global neighbor search all-gathers positions over 'sp' (the all-to-all
analog of sequence parallelism — positions are 8N bytes/formation, tiny
next to the O(N·k) obs the search produces, which stay local) and each
device runs the LOCAL-QUERY search ``ops.knn.knn_local`` for its slab.
Sharded and unsharded trajectories coincide bit-for-bit
(tests/test_parallel.py).

The env math itself is NOT reimplemented here: ``env.formation``'s
``compute_obs`` / ``compute_reward`` / ``integrate`` are shape-generic and
parameterized over a ``neighbors_fn``; this module supplies the halo-exchange
variant. Episode resets draw from the same per-formation key on every 'sp'
device (the full formation is sampled and the local slice taken), so sharded
and unsharded trajectories coincide exactly (tested in test_parallel.py).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from marl_distributedformation_tpu.env import EnvParams, FormationState, Transition
from marl_distributedformation_tpu.jax_compat import shard_map
from marl_distributedformation_tpu.env.formation import (
    _in_obstacle,
    compute_obs,
    compute_obs_knn_sharded,
    compute_reward,
    integrate,
    reset,
)

Array = jax.Array


def halo_neighbors(
    block: Array, axis: int, sp_size: int, axis_name: str = "sp"
) -> Tuple[Array, Array]:
    """Sharded equivalent of ``formation.ring_neighbors``: per-agent
    ``(prev, next)`` along the sharded agent axis of a local slab
    ``(m, n_local, ...)``, via one ppermute pair around the device ring.

    With ``sp_size == 1`` the ppermutes are self-sends and this reduces to
    plain wrap-around (``jnp.roll``) semantics.
    """
    axis = axis % block.ndim
    assert axis == 1, f"sharded agent axis must be axis 1, got {axis}"
    last = block[:, -1:]
    first = block[:, :1]
    to_next = [(d, (d + 1) % sp_size) for d in range(sp_size)]
    to_prev = [(d, (d - 1) % sp_size) for d in range(sp_size)]
    from_prev = lax.ppermute(last, axis_name, to_next)
    from_next = lax.ppermute(first, axis_name, to_prev)
    prev = jnp.concatenate([from_prev, block[:, :-1]], axis=1)
    nxt = jnp.concatenate([block[:, 1:], from_next], axis=1)
    return prev, nxt


def make_ring_step(params: EnvParams, mesh: Mesh):
    """Build a jitted batched env step with the agent axis sharded over 'sp'
    (and formations over 'dp').

    Input/output shardings: ``agents/velocity (M, N, 2)`` as P('dp','sp');
    ``goal/obstacles/steps/key`` P('dp') (replicated over 'sp'); per-agent
    outputs P('dp','sp'); per-formation outputs P('dp').
    """
    sp_size = mesh.shape["sp"]
    if params.obs_mode not in ("ring", "knn"):
        raise ValueError(
            f"agent-axis ('sp') sharding supports obs_mode 'ring' (halo "
            f"exchange) and 'knn' (all-gather + local-query search); got "
            f"{params.obs_mode!r}"
        )
    if params.num_agents % sp_size != 0:
        raise ValueError(
            f"num_agents={params.num_agents} not divisible by sp={sp_size}"
        )
    n_local = params.num_agents // sp_size
    n_agents = float(params.num_agents)

    def neighbors_fn(x: Array, axis: int) -> Tuple[Array, Array]:
        return halo_neighbors(x, axis, sp_size)

    def psum_mean(x: Array) -> Array:
        """Global mean over the sharded agent axis, per formation."""
        return lax.psum(x.sum(axis=-1), "sp") / n_agents

    def block_step(
        agents: Array,  # (m, n_local, 2)
        goal: Array,  # (m, 2)
        obstacles: Array,  # (m, K, 2)
        steps: Array,  # (m,)
        key: Array,  # (m, 2) uint32 — identical on every 'sp' device
        velocity: Array,  # (m, n_local, 2)
    ):
        sp_idx = lax.axis_index("sp")

        agents, out_of_bounds = integrate(agents, velocity, params)
        in_obstacle = jax.vmap(_in_obstacle, in_axes=(0, 0, None))(
            agents, obstacles, params
        )

        # Shared reward math with halo-exchange neighbors (exchange #1 on
        # positions, #2 on per-agent rewards for the mixing term).
        mixed, terms = compute_reward(
            agents, goal, out_of_bounds, in_obstacle, params,
            neighbors_fn=neighbors_fn,
        )

        if params.strict_parity:
            done = steps > params.max_steps  # Q1 pre-increment check
        else:
            done = steps + 1 >= params.max_steps
            if params.goal_termination:
                dist_to_goal = jnp.linalg.norm(
                    agents - goal[:, None, :], axis=-1
                )
                close = dist_to_goal < params.close_goal_dist
                done = done | (
                    lax.psum(close.sum(axis=-1), "sp") == params.num_agents
                )

        # Auto-reset: every 'sp' device redraws the FULL formation from the
        # shared per-formation key and slices its slab, so sharded and
        # unsharded trajectories are identical (simulate.py:113-116).
        fresh = jax.vmap(reset, in_axes=(0, None))(key, params)
        fresh_local = lax.dynamic_slice_in_dim(
            fresh.agents, sp_idx * n_local, n_local, axis=1
        )
        new_agents = jnp.where(done[:, None, None], fresh_local, agents)
        new_goal = jnp.where(done[:, None], fresh.goal, goal)
        new_obstacles = (
            jnp.where(done[:, None, None], fresh.obstacles, obstacles)
            if params.num_obstacles > 0
            else obstacles
        )
        new_steps = jnp.where(done, fresh.steps, steps + 1)
        new_key = jnp.where(done[:, None], fresh.key, key)

        # Exchange #3: post-reset positions, reused by both the observation
        # (ring mode) and the neighbor-distance metrics (both modes).
        post_neighbors = neighbors_fn(new_agents, 1)
        if params.obs_mode == "knn":
            # All-to-all analog: gather the full formation's positions over
            # the 'sp' ring (8N bytes/formation — the cheap side of the
            # problem), search locally for this device's slab. Indices in
            # the obs stay global, so rows match the unsharded obs exactly.
            all_pos = lax.all_gather(
                new_agents, "sp", axis=1, tiled=True
            )  # (m, N, 2)
            obs = compute_obs_knn_sharded(
                new_agents, all_pos, new_goal, params, sp_idx * n_local
            )
        else:
            obs = compute_obs(
                new_agents, new_goal, params, pos_neighbors=post_neighbors
            )

        # Metrics (simulate.py:238-254) with global psum reductions; the
        # variance uses the numerically-stable centered form (two passes)
        # to match the unsharded std(ddof=1).
        m_dist_goal = jnp.linalg.norm(new_agents - new_goal[:, None, :], axis=-1)
        m_dist_right = jnp.linalg.norm(new_agents - post_neighbors[1], axis=-1)
        mean_right = psum_mean(m_dist_right)
        centered_sq = (m_dist_right - mean_right[:, None]) ** 2
        var = lax.psum(centered_sq.sum(axis=-1), "sp") / (n_agents - 1.0)
        metrics = {
            "avg_dist_to_goal": psum_mean(m_dist_goal),
            "ave_dist_to_neighbor": mean_right,
            "std_dist_to_neighbor": jnp.sqrt(var),
            "reward": psum_mean(mixed),
        }
        metrics.update({k: psum_mean(v) for k, v in terms.items()})
        return (
            new_agents,
            new_goal,
            new_obstacles,
            new_steps,
            new_key,
            obs,
            mixed,
            done,
            metrics,
        )

    agent_spec = P("dp", "sp")
    formation_spec = P("dp")
    in_specs = (
        agent_spec,  # agents
        formation_spec,  # goal
        formation_spec,  # obstacles
        formation_spec,  # steps
        formation_spec,  # key
        agent_spec,  # velocity
    )
    out_specs = (
        agent_spec,  # agents
        formation_spec,  # goal
        formation_spec,  # obstacles
        formation_spec,  # steps
        formation_spec,  # key
        agent_spec,  # obs
        agent_spec,  # reward
        formation_spec,  # done
        formation_spec,  # metrics (dict of (m,) arrays)
    )
    sharded = shard_map(
        block_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs
    )

    @jax.jit
    def ring_step(
        state: FormationState, velocity: Array
    ) -> Tuple[FormationState, Transition]:
        (
            agents,
            goal,
            obstacles,
            steps,
            key,
            obs,
            reward,
            done,
            metrics,
        ) = sharded(
            state.agents,
            state.goal,
            state.obstacles,
            state.steps,
            state.key,
            velocity,
        )
        next_state = FormationState(
            agents=agents,
            goal=goal,
            obstacles=obstacles,
            steps=steps,
            key=key,
        )
        return next_state, Transition(
            obs=obs, reward=reward, done=done, metrics=metrics
        )

    return ring_step


def place_ring_state(
    state: FormationState, mesh: Mesh
) -> FormationState:
    """Place a batched ``FormationState`` for ring stepping: agents sharded
    ('dp','sp'), per-formation leaves sharded ('dp') and replicated over 'sp'."""
    agent_sharding = NamedSharding(mesh, P("dp", "sp"))
    formation_sharding = NamedSharding(mesh, P("dp"))
    return FormationState(
        agents=jax.device_put(state.agents, agent_sharding),
        goal=jax.device_put(state.goal, formation_sharding),
        obstacles=jax.device_put(state.obstacles, formation_sharding),
        steps=jax.device_put(state.steps, formation_sharding),
        key=jax.device_put(state.key, formation_sharding),
    )
