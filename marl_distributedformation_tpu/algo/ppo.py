"""Proximal Policy Optimization: clipped surrogate, minibatch epochs.

In-repo replacement for the SB3 ``PPO`` the reference imports
(vectorized_env.py:115,126-131; SURVEY.md §2.2). Hyperparameter defaults are
the SB3 defaults overridden exactly as the reference overrides them
(``n_steps=10``, ``learning_rate=1e-3``, ``ent_coef=0.01``); everything else
(gamma, lambda, clip, epochs, batch size, vf coef, grad clip, Adam eps)
matches SB3's defaults so the ≤1% return-parity gate is meaningful.

Known deliberate deviation: when the rollout size is not divisible by
``batch_size``, the remainder transitions are dropped from each epoch's
shuffled pass (SB3 runs a final smaller minibatch). Static shapes keep the
whole update one XLA program; with default sizes the remainder is zero.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.training.train_state import TrainState

from marl_distributedformation_tpu.models import distributions

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PPOConfig:
    """Static PPO hyperparameters (hashable; safe to close over in jit)."""

    n_steps: int = 10  # reference vectorized_env.py:128
    learning_rate: float = 1e-3  # vectorized_env.py:130
    ent_coef: float = 0.01  # vectorized_env.py:131
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_range: float = 0.2
    clip_range_vf: Optional[float] = None  # SB3 default: no value clipping
    n_epochs: int = 10
    batch_size: int = 64
    vf_coef: float = 0.5
    max_grad_norm: float = 0.5
    adam_eps: float = 1e-5  # SB3 ActorCriticPolicy optimizer default
    normalize_advantage: bool = True
    log_std_init: float = 0.0  # parity: the reference's -2 is a no-op (Q5)
    # Entropy-coefficient decay (beyond SB3, which only schedules lr/clip):
    # when ``ent_coef_final`` is set, the effective coefficient interpolates
    # linearly from ``ent_coef`` to ``ent_coef_final`` over the run, keyed
    # on the optimizer step already carried in ``TrainState.step`` — so it
    # threads through vmapped populations, scan-fused dispatch, and
    # checkpoint resume with zero extra state. Motivation: a constant
    # entropy bonus can leave a policy RELYING on its action noise (the
    # hetero5 artifact holds ring spacing only through noise — its mode
    # action collapses, docs/acceptance/hetero5/). NB measured caveat:
    # annealing removes the pressure to KEEP noise, but adds none to
    # move its function into the mean — in the hetero5 budget the noise
    # equilibrium was self-sustaining (entropy barely moved with the
    # bonus at 5e-4), so evaluate as-trained (eval_deterministic=false)
    # remains the honest measure for such policies. ``total_iterations``
    # (the decay horizon, in iterations) is filled by the trainer shell.
    ent_coef_final: Optional[float] = None
    # Scheduled action-noise decay (the round-4 lesson, VERDICT r4
    # next-#1): annealing the entropy BONUS alone removes the pressure to
    # keep noise but adds none to move its function into the mean — the
    # hetero5 policy's noise-as-spacing equilibrium was self-sustaining.
    # ``log_std_final`` adds that missing pressure as a PROJECTION: after
    # every optimizer step the learned ``log_std`` parameter is clamped
    # to a ceiling that decays linearly from ``log_std_init`` to
    # ``log_std_final`` over the run (same optimizer-step progress as the
    # entropy schedule). A projection rather than a loss term because the
    # clipped-Adam optimizer takes ~unit-scaled steps: any pull term
    # moves log_std at most ``learning_rate`` per minibatch step, far too
    # slow to traverse nats within a normal run's horizon. Clamping the
    # PARAMETER (not the effective value) keeps rollout, loss,
    # checkpoint, and eval consistent: the saved policy actually IS the
    # narrow-noise policy, so ``deterministic=True`` eval stops
    # misrepresenting it. The policy may still learn a log_std BELOW the
    # ceiling; the schedule only forbids hiding behavior in noise.
    # ``log_std_decay_start`` holds the ceiling at ``log_std_init`` until
    # that fraction of the run, then decays linearly to ``log_std_final``
    # over the remainder — full exploration while behavior is learned,
    # noise squeezed out in the home stretch (measured: an all-run decay
    # starves late curriculum stages of exploration).
    log_std_final: Optional[float] = None
    log_std_decay_start: float = 0.0
    total_iterations: int = 0

    def make_optimizer(
        self, inject_lr: bool = False
    ) -> optax.GradientTransformation:
        """The training optimizer (SB3's clipped Adam). ``inject_lr=True``
        wraps adam in ``optax.inject_hyperparams`` so the learning rate
        lives in the OPTIMIZER STATE — one shared transform can then serve
        a vmapped population with per-member rates (train/sweep.py).
        Single source of truth for the chain: both variants must stay
        structurally identical apart from the inject wrapper."""
        adam = (
            optax.inject_hyperparams(optax.adam)(
                learning_rate=self.learning_rate, eps=self.adam_eps
            )
            if inject_lr
            else optax.adam(self.learning_rate, eps=self.adam_eps)
        )
        return optax.chain(
            optax.clip_by_global_norm(self.max_grad_norm), adam
        )


@struct.dataclass
class MinibatchData:
    obs: Array  # (b, obs_dim)
    actions: Array  # (b, act_dim)
    old_log_probs: Array  # (b,)
    advantages: Array  # (b,)
    returns: Array  # (b,)
    weights: Array = None  # (b,) optional per-transition loss weights —
    #   heterogeneous (padded) formations put weight 0 on padded agents
    #   (env/hetero.py); None means uniform weights (homogeneous path).
    mask: Array = None  # (b, N) optional agent-validity mask forwarded to
    #   per-formation models (CTDE/GNN) so padded agents are excluded from
    #   the pooled critic; None for agent-factored models or homogeneous
    #   batches. Distinct from ``weights``: the mask shapes the MODEL's
    #   forward pass, weights shape the LOSS reduction.


def _leaf_name(entry) -> Optional[str]:
    """Name of a tree-path entry (DictKey .key / GetAttrKey .name) — the
    single definition shared by the log_std structure check and the
    projection clamp so the two can't drift."""
    return getattr(entry, "key", getattr(entry, "name", None))


def _wmean(x: Array, weights: Array) -> Array:
    """Weighted mean; with ``weights=None`` falls back to a plain mean."""
    if weights is None:
        return x.mean()
    w = weights.reshape(x.shape if x.ndim else ())
    return (x * w).sum() / jnp.maximum(w.sum(), 1e-8)


def ppo_loss(
    nn_params: Any,
    apply_fn,
    mb: MinibatchData,
    config: PPOConfig,
    ent_coef: Optional[Array] = None,
) -> Tuple[Array, Dict[str, Array]]:
    """Clipped-surrogate PPO loss on one minibatch (SB3 semantics).

    ``ent_coef`` overrides ``config.ent_coef`` with a traced scalar when
    the entropy coefficient is scheduled (``config.ent_coef_final``)."""
    if mb.mask is not None:
        mean, log_std, values = apply_fn(nn_params, mb.obs, mb.mask)
    else:
        mean, log_std, values = apply_fn(nn_params, mb.obs)
    log_probs = distributions.log_prob(mb.actions, mean, log_std)
    ent = distributions.entropy(log_std)

    w = mb.weights
    advantages = mb.advantages
    if config.normalize_advantage:
        # SB3 normalizes per minibatch with torch's unbiased std. With
        # weights, moments run over the weighted (active) transitions only.
        if w is None:
            advantages = (advantages - advantages.mean()) / (
                advantages.std(ddof=1) + 1e-8
            )
        else:
            wa = w.reshape(advantages.shape)
            n_active = jnp.maximum(wa.sum(), 2.0)
            adv_mean = (advantages * wa).sum() / n_active
            adv_var = (((advantages - adv_mean) ** 2) * wa).sum() / (
                n_active - 1.0
            )
            advantages = (advantages - adv_mean) / (jnp.sqrt(adv_var) + 1e-8)

    ratio = jnp.exp(log_probs - mb.old_log_probs)
    unclipped = advantages * ratio
    clipped = advantages * jnp.clip(
        ratio, 1.0 - config.clip_range, 1.0 + config.clip_range
    )
    policy_loss = -_wmean(jnp.minimum(unclipped, clipped), w)

    if config.clip_range_vf is not None:
        # SB3's value clipping: predictions move at most clip_range_vf
        # from the rollout-time values. Those old values need no extra
        # plumbing — GAE's identity returns = advantages + values means
        # old_values = returns - advantages (both raw in the minibatch;
        # normalization above works on a local copy).
        old_values = mb.returns - mb.advantages
        values = old_values + jnp.clip(
            values - old_values,
            -config.clip_range_vf,
            config.clip_range_vf,
        )
    value_loss = _wmean((mb.returns - values) ** 2, w)
    entropy_loss = -ent  # state-independent Gaussian: scalar

    effective_ent_coef = (
        config.ent_coef if ent_coef is None else ent_coef
    )
    loss = (
        policy_loss
        + effective_ent_coef * entropy_loss
        + config.vf_coef * value_loss
    )
    metrics = {
        "loss": loss,
        "policy_loss": policy_loss,
        "value_loss": value_loss,
        "entropy": ent,
        "approx_kl": _wmean(mb.old_log_probs - log_probs, w),
        "clip_fraction": _wmean(
            (jnp.abs(ratio - 1.0) > config.clip_range).astype(jnp.float32), w
        ),
    }
    return loss, metrics


def ppo_update(
    train_state: TrainState,
    data: MinibatchData,
    key: Array,
    config: PPOConfig,
) -> Tuple[TrainState, Dict[str, Array]]:
    """Run ``n_epochs`` of shuffled minibatch SGD over flattened rollout data.

    ``data`` leaves are flat ``(total, ...)`` with ``total = T * M * N``
    agent-transitions — each agent is its own "environment", the reference's
    parameter-sharing trick (vectorized_env.py:32).
    """
    total = data.obs.shape[0]
    # Clamp for rollouts smaller than batch_size (e.g. num_formation=1):
    # train on one full-rollout minibatch instead of crashing.
    batch_size = min(config.batch_size, total)
    num_minibatches = total // batch_size
    used = num_minibatches * batch_size

    ent_decay = config.ent_coef_final is not None
    std_decay = config.log_std_final is not None
    decay = ent_decay or std_decay
    if decay:
        assert config.total_iterations > 0, (
            "ent_coef_final/log_std_final require total_iterations > 0 "
            "(the trainer shell fills it; constructing PPOConfig by "
            "hand, pass the planned iteration count)"
        )
    if std_decay:
        # Structure check up front: the projection below is path-keyed on
        # the leaf name, so a model without a "log_std" parameter would
        # silently make the schedule a no-op.
        leaf_names = {
            _leaf_name(p[-1])
            for p, _ in jax.tree_util.tree_flatten_with_path(
                train_state.params
            )[0]
        }
        assert "log_std" in leaf_names, (
            "log_std_final requires a 'log_std' parameter leaf; "
            f"model params have {sorted(map(str, leaf_names))}"
        )
        assert 0.0 <= config.log_std_decay_start < 1.0, (
            "log_std_decay_start is the fraction of the run to hold the "
            "ceiling before decaying; it must be in [0, 1) — at >= 1 the "
            f"decay would silently never run (got "
            f"{config.log_std_decay_start})"
        )
    if decay:
        # Linear schedule on the optimizer step the TrainState already
        # carries — resumes, vmapped populations, and fused dispatch all
        # inherit the right position for free.
        # ASSUMES a constant rollout size across the run: the horizon is
        # derived from THIS call's num_minibatches, while ts.step
        # accumulated under every earlier call's count. All trainer
        # shells keep rollout shape fixed (hetero pads to N_max), so the
        # two agree; a variable-shape caller would miscalibrate the
        # anneal and must fill total_iterations in minibatch-steps
        # itself.
        expected_total = (
            config.total_iterations * config.n_epochs * num_minibatches
        )

    grad_fn = jax.value_and_grad(ppo_loss, has_aux=True)

    def minibatch_step(ts: TrainState, idx: Array):
        mb = jax.tree_util.tree_map(lambda x: x[idx], data)
        ent_coef = None
        if decay:
            # Two-limb float split of the integer step: a straight
            # float32(step) collapses consecutive steps past 2^24 (#
            # reachable at parity batch_size=64 with large M), stalling
            # the anneal near the horizon. hi < 2^24 for any int32 step
            # and lo < 4096 are both exact in float32, so progress stays
            # strictly monotone in step.
            hi = jnp.asarray(ts.step // 4096, jnp.float32)
            lo = jnp.asarray(ts.step % 4096, jnp.float32)
            progress = jnp.clip(
                hi * (4096.0 / expected_total) + lo / expected_total,
                0.0,
                1.0,
            )
            if ent_decay:
                ent_coef = config.ent_coef + progress * (
                    config.ent_coef_final - config.ent_coef
                )
            if std_decay:
                start = config.log_std_decay_start
                sprog = jnp.clip(
                    (progress - start) / max(1.0 - start, 1e-8), 0.0, 1.0
                )
                log_std_ceiling = config.log_std_init + sprog * (
                    config.log_std_final - config.log_std_init
                )
        (_, metrics), grads = grad_fn(
            ts.params, ts.apply_fn, mb, config, ent_coef
        )
        # Raw (pre-clip) global gradient norm: the divergence diagnostic
        # the train lane's health word bounds (train/recovery.py) — the
        # optimizer chain clips at max_grad_norm, so the clipped norm
        # would saturate at 0.5 and hide every explosion.
        metrics["grad_norm"] = optax.global_norm(grads)
        if ent_decay:
            metrics["ent_coef"] = ent_coef
        ts = ts.apply_gradients(grads=grads)
        if std_decay:
            # Project the log_std parameter under the decayed ceiling —
            # every model family names its state-independent noise
            # parameter "log_std" (models/mlp.py, ctde.py, gnn.py); the
            # path-keyed clamp composes with vmapped populations (leaves
            # gain a member axis, the name does not change).
            metrics["log_std_ceiling"] = log_std_ceiling

            def clamp(path, leaf):
                if _leaf_name(path[-1]) == "log_std":
                    return jnp.minimum(leaf, log_std_ceiling)
                return leaf

            ts = ts.replace(
                params=jax.tree_util.tree_map_with_path(clamp, ts.params)
            )
        return ts, metrics

    def epoch_step(ts: TrainState, epoch_key: Array):
        perm = jax.random.permutation(epoch_key, total)[:used]
        idx = perm.reshape(num_minibatches, batch_size)
        ts, metrics = jax.lax.scan(minibatch_step, ts, idx)
        return ts, jax.tree_util.tree_map(lambda m: m.mean(), metrics)

    epoch_keys = jax.random.split(key, config.n_epochs)
    train_state, metrics = jax.lax.scan(epoch_step, train_state, epoch_keys)
    return train_state, jax.tree_util.tree_map(lambda m: m.mean(), metrics)
