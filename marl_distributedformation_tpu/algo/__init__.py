"""PPO training algorithm: GAE, rollouts, clipped-surrogate updates."""

from marl_distributedformation_tpu.algo.gae import compute_gae  # noqa: F401
from marl_distributedformation_tpu.algo.ppo import (  # noqa: F401
    MinibatchData,
    PPOConfig,
    ppo_loss,
    ppo_update,
)
from marl_distributedformation_tpu.algo.rollout import (  # noqa: F401
    RolloutBatch,
    collect_rollout,
)
