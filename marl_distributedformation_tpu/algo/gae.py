"""Generalized Advantage Estimation via ``lax.scan``.

Capability replacement for SB3's ``RolloutBuffer.compute_returns_and_advantage``
(consumed by the reference through ``PPO.learn``, vectorized_env.py:134;
SURVEY.md §2.2). Episodes that end inside the rollout are handled through the
``dones`` mask; because the reference's VecEnv supplies no
``terminal_observation`` (SURVEY.md Q4), terminal steps simply don't
bootstrap — matching SB3's behavior on this env exactly.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def compute_gae(
    rewards: Array,
    values: Array,
    dones: Array,
    last_value: Array,
    gamma: float,
    gae_lambda: float,
) -> Tuple[Array, Array]:
    """Compute advantages and returns.

    Args:
      rewards, values, dones: ``(T, ...)`` time-major rollout arrays;
        ``dones[t]`` is True when the transition at ``t`` ended an episode.
      last_value: ``(...)`` value of the observation after the final step.

    Returns:
      ``(advantages, returns)`` with ``returns = advantages + values``
      (TD(lambda) targets, as in SB3).
    """
    next_values = jnp.concatenate(
        [values[1:], last_value[None]], axis=0
    )
    non_terminal = 1.0 - dones.astype(values.dtype)
    deltas = rewards + gamma * next_values * non_terminal - values

    def body(next_adv, x):
        delta, nt = x
        adv = delta + gamma * gae_lambda * nt * next_adv
        return adv, adv

    _, advantages = jax.lax.scan(
        body,
        jnp.zeros_like(last_value),
        (deltas, non_terminal),
        reverse=True,
    )
    return advantages, advantages + values
