"""On-policy rollout collection as a ``lax.scan`` over environment steps.

The TPU-native replacement for SB3's ``collect_rollouts`` host loop (consumed
by the reference at vectorized_env.py:134; SURVEY.md §3.1): the policy
forward pass, action sampling, env step, and buffer write all live inside one
jitted scan — no host round-trips per step.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from marl_distributedformation_tpu.env import EnvParams, FormationState
from marl_distributedformation_tpu.envs import spec_for_params
from marl_distributedformation_tpu.models import distributions

Array = jax.Array


@struct.dataclass
class RolloutBatch:
    """Time-major rollout storage, shapes ``(T, M, N, ...)``.

    ``dones`` is broadcast from per-formation to per-agent, the same flattening
    the reference's adapter performs (vectorized_env.py:79).
    """

    obs: Array  # (T, M, N, obs_dim)
    actions: Array  # (T, M, N, act_dim) — unclipped samples, as SB3 stores
    log_probs: Array  # (T, M, N)
    values: Array  # (T, M, N)
    rewards: Array  # (T, M, N)
    dones: Array  # (T, M, N)
    metrics: Dict[str, Array]  # per-step env metrics, each (T, M)


def collect_rollout(
    apply_fn: Callable[..., Tuple[Array, Array, Array]],
    nn_params: Any,
    env_state: FormationState,
    obs: Array,
    key: Array,
    env_params: EnvParams,
    n_steps: int,
    env_step_fn: Optional[Callable] = None,
    mask: Optional[Array] = None,
) -> Tuple[FormationState, Array, RolloutBatch, Array]:
    """Roll ``n_steps`` vectorized env steps under the current policy.

    Actions are sampled from the Gaussian head, clipped to the [-1, 1] action
    space for the env (SB3's convention: the *unclipped* sample and its log
    prob go into the buffer), then scaled by ``max_speed`` exactly where the
    reference's adapter does it (vectorized_env.py:69-70).

    ``env_step_fn(state, velocity) -> (state, transition)`` defaults to the
    REGISTERED env's vmapped single-chip step, resolved from the params type
    (``envs.spec_for_params`` — formation params resolve to the legacy
    ``step_batch`` verbatim, so that path is bitwise unchanged); pass a ring
    step (``parallel.make_ring_step``) to roll with the agent axis sharded
    over 'sp'.

    ``mask`` is an optional ``(M, N)`` agent-validity mask forwarded to
    per-formation models (CTDE/GNN) for padded heterogeneous batches; it is
    constant across the rollout because ``n_agents`` is preserved through
    auto-resets (env/hetero.py).

    Returns ``(env_state, last_obs, batch, last_value)``.
    """
    if env_step_fn is None:
        env_spec = spec_for_params(env_params)

        def env_step_fn(state, velocity):
            return env_spec.step_batch(state, velocity, env_params)

    def policy(obs):
        if mask is not None:
            return apply_fn(nn_params, obs, mask)
        return apply_fn(nn_params, obs)

    def body(carry, step_key):
        env_state, obs = carry
        with jax.named_scope("policy"):
            mean, log_std, value = policy(obs)
            action = distributions.sample(step_key, mean, log_std)
            log_p = distributions.log_prob(action, mean, log_std)
        clipped = jnp.clip(action, -1.0, 1.0)
        with jax.named_scope("env_step"):
            env_state, tr = env_step_fn(
                env_state, env_params.max_speed * clipped
            )
        done_agents = jnp.broadcast_to(
            tr.done[:, None], tr.reward.shape
        ).astype(jnp.float32)
        out = RolloutBatch(
            obs=obs,
            actions=action,
            log_probs=log_p,
            values=value,
            rewards=tr.reward,
            dones=done_agents,
            metrics=tr.metrics,
        )
        return (env_state, tr.obs), out

    step_keys = jax.random.split(key, n_steps)
    (env_state, last_obs), batch = jax.lax.scan(
        body, (env_state, obs), step_keys
    )
    _, _, last_value = policy(last_obs)
    return env_state, last_obs, batch, last_value
