"""Robustness evaluation matrix: scenarios x severities x checkpoints.

One compiled program serves the whole grid: the episode runner takes the
*model parameters* and the *scenario parameters* as traced inputs (only
the architecture and env geometry are static), so sweeping 9 scenarios x
3 severities x K same-architecture checkpoints compiles exactly once —
pinned by a budget-1 ``analysis.guards.RetraceGuard``. Identical initial
states across every cell (the eval-seed convention of ``eval.py``), so
cells are directly comparable.

CLI: ``scripts/robustness_matrix.py`` (one JSON report per run).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.analysis.guards import RetraceGuard
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.eval import (
    policy_act_fn,
    run_episode_metrics,
)
from marl_distributedformation_tpu.scenarios.registry import get_scenario

Array = jax.Array


def make_matrix_runner(
    model,
    env_params: EnvParams,
    num_formations: int,
    deterministic: bool = True,
    max_traces: Optional[int] = 1,
) -> Tuple:
    """Build ``(run, guard)``: ``run(key, model_params, scenario_params)``
    -> episode metrics, jitted once for the whole matrix (``guard`` is the
    budget-``max_traces`` RetraceGuard wrapping it)."""
    guard = RetraceGuard("robustness_matrix_eval", max_traces=max_traces)

    def episode(key, model_params, scenario_params):
        act = policy_act_fn(model, model_params, env_params, deterministic)
        return run_episode_metrics(
            key, act, env_params, num_formations, scenario_params
        )

    return jax.jit(guard.wrap(episode)), guard


def run_matrix(
    checkpoint_paths: Sequence[str],
    env_params: EnvParams,
    scenarios: Sequence[str],
    severities: Sequence[float],
    num_formations: int = 256,
    seed: int = 1234,
    deterministic: bool = True,
) -> Dict:
    """Sweep every checkpoint over scenarios x severities.

    Checkpoints must share one architecture (one run's checkpoint series
    — validated, a mismatch names the offending file). Returns the report
    dict: ``matrix[checkpoint][scenario][severity] -> metrics`` plus the
    compile count (the zero-recompile receipt).
    """
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy

    if not checkpoint_paths:
        raise ValueError("run_matrix needs at least one checkpoint path")
    specs = [get_scenario(str(name)) for name in scenarios]  # fail fast

    policies = [
        LoadedPolicy.from_checkpoint(
            str(p), act_dim=env_params.act_dim, env_params=env_params
        )
        for p in checkpoint_paths
    ]
    def signature(params):
        # Structure AND leaf shapes/dtypes: same-structure checkpoints
        # with different widths would otherwise pass, then blow the
        # budget-1 guard mid-sweep with a confusing retrace error.
        return jax.tree_util.tree_structure(params), [
            (jnp.shape(leaf), jnp.asarray(leaf).dtype)
            for leaf in jax.tree_util.tree_leaves(params)
        ]

    reference = signature(policies[0].params)
    for path, pol in zip(checkpoint_paths, policies):
        if signature(pol.params) != reference:
            raise ValueError(
                f"checkpoint {path} has a different parameter "
                "structure/shape than the first checkpoint — the matrix "
                "shares one compiled program, so all checkpoints must be "
                "one architecture (run separate matrices per architecture)"
            )

    run, guard = make_matrix_runner(
        policies[0].model, env_params, num_formations, deterministic
    )
    key = jax.random.PRNGKey(seed)

    matrix: Dict[str, Dict[str, Dict[str, Dict[str, float]]]] = {}
    for path, pol in zip(checkpoint_paths, policies):
        per_scenario: Dict[str, Dict[str, Dict[str, float]]] = {}
        for spec in specs:
            per_severity: Dict[str, Dict[str, float]] = {}
            for severity in severities:
                sp = spec.build(jnp.float32(severity))
                out = run(key, pol.params, sp)
                per_severity[f"{float(severity):g}"] = {
                    k: float(v) for k, v in out.items()
                }
            per_scenario[spec.name] = per_severity
        matrix[str(path)] = per_scenario

    return {
        "scenarios": [spec.name for spec in specs],
        "severities": [float(s) for s in severities],
        "checkpoints": [str(p) for p in checkpoint_paths],
        "eval_formations": num_formations,
        "num_agents": env_params.num_agents,
        "seed": seed,
        "deterministic": deterministic,
        "matrix": matrix,
        "eval_compiles": guard.count,
    }
