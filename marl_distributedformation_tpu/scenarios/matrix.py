"""Robustness evaluation matrix: scenarios x severities x checkpoints.

One compiled program serves the whole grid: the episode runner takes the
*model parameters* and the *scenario parameters* as traced inputs (only
the architecture and env geometry are static), so sweeping 9 scenarios x
3 severities x K same-architecture checkpoints compiles exactly once —
pinned by a budget-1 ``analysis.guards.RetraceGuard``. Identical initial
states across every cell (the eval-seed convention of ``eval.py``), so
cells are directly comparable.

:class:`MatrixProgram` is the importable, long-lived form: it owns the
jitted runner + guard and evaluates arbitrarily many parameter
candidates over its life without re-jitting — the promotion gate of the
always-learning pipeline (``pipeline/gate.py``) holds ONE for an entire
run, so every trained candidate reuses the same compiled program (the
budget-1 receipt spans all of them). :func:`run_matrix` is the one-shot
checkpoint-list sweep built on top of it, and the CLI
(``scripts/robustness_matrix.py``) is a thin wrapper over that.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.analysis.guards import (
    RetraceGuard,
    ledgered_jit,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.eval import (
    policy_act_fn,
    run_episode_metrics,
)
from marl_distributedformation_tpu.scenarios.registry import get_scenario

Array = jax.Array


def make_matrix_runner(
    model,
    env_params: EnvParams,
    num_formations: int,
    deterministic: bool = True,
    max_traces: Optional[int] = 1,
) -> Tuple:
    """Build ``(run, guard)``: ``run(key, model_params, scenario_params)``
    -> episode metrics, jitted once for the whole matrix (``guard`` is the
    budget-``max_traces`` RetraceGuard wrapping it)."""
    guard = RetraceGuard("robustness_matrix_eval", max_traces=max_traces)

    def episode(key, model_params, scenario_params):
        act = policy_act_fn(model, model_params, env_params, deterministic)
        return run_episode_metrics(
            key, act, env_params, num_formations, scenario_params
        )

    run = ledgered_jit(
        episode,
        guard,
        subsystem="gate",
        program="robustness_matrix_eval",
    )
    return run, guard


def params_signature(params) -> Tuple:
    """Structure AND leaf shapes/dtypes of a parameter tree. The matrix
    shares ONE compiled program, so every candidate must match the first
    one's signature — same-structure checkpoints with different widths
    would otherwise pass construction, then blow the budget-1 guard
    mid-sweep with a confusing retrace error."""
    return jax.tree_util.tree_structure(params), tuple(
        (jnp.shape(leaf), jnp.asarray(leaf).dtype)
        for leaf in jax.tree_util.tree_leaves(params)
    )


class MatrixProgram:
    """The compiled scenario x severity eval program, reusable across
    candidates.

    Construction jits nothing; the single compile happens on the first
    evaluated cell and every later cell — any scenario, any severity,
    any same-architecture parameter tree — reuses it (``guard.count``
    is the receipt). ``check_params`` enforces the one-architecture
    contract against the first candidate seen.
    """

    def __init__(
        self,
        model,
        env_params: EnvParams,
        num_formations: int = 256,
        deterministic: bool = True,
        seed: int = 1234,
        max_traces: Optional[int] = 1,
        device=None,
    ) -> None:
        self.model = model
        self.env_params = env_params
        self.num_formations = num_formations
        self.deterministic = deterministic
        self.seed = seed
        self.run, self.guard = make_matrix_runner(
            model, env_params, num_formations, deterministic, max_traces
        )
        # Slice assignment (train/sebulba): a committed key pins the
        # compiled program to ``device`` — candidates are device_put
        # there per eval, so the gate never time-shares the learner's
        # silicon. None = follow jax's default placement (Anakin mode).
        self.device = device
        self.key = jax.random.PRNGKey(seed)
        if device is not None:
            self.key = jax.device_put(self.key, device)
        self._signature: Optional[Tuple] = None

    @property
    def compile_count(self) -> int:
        """Traces of the shared program so far (the compile-once
        receipt: stays 1 across every candidate and cell)."""
        return self.guard.count

    def check_params(self, params, origin: str = "<candidate>") -> None:
        """Fail fast on a parameter tree the compiled program cannot
        serve (different structure/shapes/dtypes than the first
        candidate)."""
        sig = params_signature(params)
        if self._signature is None:
            self._signature = sig
        elif sig != self._signature:
            raise ValueError(
                f"checkpoint {origin} has a different parameter "
                "structure/shape than the first candidate — the matrix "
                "shares one compiled program, so all candidates must be "
                "one architecture (run separate matrices per architecture)"
            )

    def evaluate_clean(
        self, params, origin: str = "<candidate>"
    ) -> Dict[str, float]:
        """The clean-env episode metrics via the registry's ``clean``
        scenario at severity 0 — bitwise identical to the raw env
        (pinned by tests/test_scenarios.py), through the SAME compiled
        program as every disturbed cell."""
        self.check_params(params, origin)
        if self.device is not None:
            params = jax.device_put(params, self.device)
        spec = get_scenario("clean")
        out = self.run(self.key, params, spec.build(jnp.float32(0.0)))
        return {k: float(v) for k, v in out.items()}

    def evaluate_cells(
        self,
        params,
        scenarios: Sequence[str],
        severities: Sequence[float],
        origin: str = "<candidate>",
    ) -> Dict[str, Dict[str, Dict[str, float]]]:
        """The full scenario x severity grid for one parameter tree:
        ``cells[scenario][f"{severity:g}"] -> metrics``."""
        self.check_params(params, origin)
        if self.device is not None:
            params = jax.device_put(params, self.device)
        specs = [get_scenario(str(name)) for name in scenarios]  # fail fast
        cells: Dict[str, Dict[str, Dict[str, float]]] = {}
        for spec in specs:
            per_severity: Dict[str, Dict[str, float]] = {}
            for severity in severities:
                sp = spec.build(jnp.float32(severity))
                out = self.run(self.key, params, sp)
                per_severity[f"{float(severity):g}"] = {
                    k: float(v) for k, v in out.items()
                }
            cells[spec.name] = per_severity
        return cells


def run_matrix(
    checkpoint_paths: Sequence[str],
    env_params: EnvParams,
    scenarios: Sequence[str],
    severities: Sequence[float],
    num_formations: int = 256,
    seed: int = 1234,
    deterministic: bool = True,
) -> Dict:
    """Sweep every checkpoint over scenarios x severities.

    Checkpoints must share one architecture (one run's checkpoint series
    — validated, a mismatch names the offending file). Returns the report
    dict: ``matrix[checkpoint][scenario][severity] -> metrics`` plus the
    compile count (the zero-recompile receipt).
    """
    from marl_distributedformation_tpu.compat.policy import LoadedPolicy

    if not checkpoint_paths:
        raise ValueError("run_matrix needs at least one checkpoint path")
    specs = [get_scenario(str(name)) for name in scenarios]  # fail fast

    policies = [
        LoadedPolicy.from_checkpoint(
            str(p), act_dim=env_params.act_dim, env_params=env_params
        )
        for p in checkpoint_paths
    ]
    program = MatrixProgram(
        policies[0].model,
        env_params,
        num_formations=num_formations,
        deterministic=deterministic,
        seed=seed,
    )
    # Validate EVERY architecture before the first (expensive) eval cell,
    # so a mismatched file fails the run up front, by name.
    for path, pol in zip(checkpoint_paths, policies):
        program.check_params(pol.params, origin=str(path))
    matrix: Dict[str, Dict[str, Dict[str, Dict[str, float]]]] = {}
    for path, pol in zip(checkpoint_paths, policies):
        matrix[str(path)] = program.evaluate_cells(
            pol.params,
            [spec.name for spec in specs],
            severities,
            origin=str(path),
        )

    return {
        "scenarios": [spec.name for spec in specs],
        "severities": [float(s) for s in severities],
        "checkpoints": [str(p) for p in checkpoint_paths],
        "eval_formations": num_formations,
        "num_agents": env_params.num_agents,
        "seed": seed,
        "deterministic": deterministic,
        "matrix": matrix,
        "eval_compiles": program.compile_count,
    }
