"""Compile-once disturbance & scenario engine (docs/scenarios.md).

Declarative, pure-JAX scenario variants of the formation env: composable
perturbation layers (``layers.py``) stack around ``env/formation.py``'s
step without forking it, every scenario/severity knob is a traced input
(``params.py``), and a ``ScenarioSpec`` registry (``registry.py``) names
the recipes — so ONE jitted train or eval step serves every registered
scenario at every severity with zero recompiles, and a batch can mix
scenarios per formation (``sample_scenario_batch``).
"""

from marl_distributedformation_tpu.scenarios.params import (  # noqa: F401
    ScenarioParams,
    broadcast_params,
)
from marl_distributedformation_tpu.scenarios.layers import (  # noqa: F401
    neighbor_obs_columns,
    occlude_obs,
    perturb_goal,
    perturb_obs,
    perturb_obstacles,
    perturb_velocity,
)
from marl_distributedformation_tpu.scenarios.engine import (  # noqa: F401
    make_scenario_step,
    scenario_step,
    scenario_step_batch,
)
from marl_distributedformation_tpu.scenarios.registry import (  # noqa: F401
    ScenarioSpec,
    get_scenario,
    register_scenario,
    registered_scenarios,
    sample_scenario_batch,
    scenario_params_for,
)
from marl_distributedformation_tpu.scenarios.schedule import (  # noqa: F401
    ADV_SCENARIO_PREFIX,
    ScenarioSchedule,
    ScenarioStage,
    from_falsifiers,
    schedule_from_cfg,
)
from marl_distributedformation_tpu.scenarios.matrix import (  # noqa: F401
    MatrixProgram,
    make_matrix_runner,
    run_matrix,
)
from marl_distributedformation_tpu.scenarios.adversary import (  # noqa: F401
    AdversaryConfig,
    AdversarySearch,
    ContinuousAdversary,
    Falsifier,
    make_population_runner,
)
