"""Composable disturbance layers: pure functions around the clean env step.

Each layer is a ``(ScenarioParams, state, value) -> value`` transform that
stacks around ``env/formation.py``'s ``step`` without forking it:

- ``perturb_goal`` (pre-step, state transform): moving formation targets
  (the goal drifts along a per-episode heading) and mid-episode target
  switching (at ``max_steps // 2`` the goal jumps toward a freshly
  sampled location by ``goal_jump``);
- ``perturb_velocity`` (pre-step, action transform): agent fault
  injection (per-episode frozen agents — actuator dropout), Gaussian +
  constant-bias actuator noise, and a constant + gusting wind field;
- ``perturb_obstacles`` (pre-step, state transform): moving obstacles —
  each obstacle drifts along its own per-episode heading, clipped to the
  world box (the avoidance capability the reference env had and the
  scenario engine dropped — ROADMAP item 3a);
- ``perturb_obs`` (post-step, observation transform): Gaussian +
  constant-bias sensor noise, comm dropout that masks the
  neighbor-derived observation blocks per agent per step, and obstacle
  occlusion — agents within ``obstacle_occlusion`` px of an obstacle
  lose the same neighbor blocks (obstacles as a sensing hazard).

Layers that index observation columns do NOT hard-code any layout: they
read the block slices from the env's **declared** obs layout
(``envs.spec_for_params(params).obs_layout(params)``) and fail fast when
an env doesn't declare the block they need (``ObsLayout.require``) —
masking the wrong columns silently is the one failure mode this design
exists to prevent.

Randomness derives from the formation's own PRNG stream via ``fold_in``
with per-layer salts — the env's key is read, never consumed, so the
underlying clean trajectory (resets included) is untouched. Every layer
is guarded with ``jnp.where(magnitude > 0, perturbed, clean)``: at zero
magnitude the output is the clean value **bitwise** (not just within
epsilon — ``x + 0.0`` would already flip ``-0.0`` signs), which is what
lets severity-0 scenarios reproduce the clean env trajectory exactly
(tests/test_scenarios.py) while the disturbance math stays inside one
compiled program for every scenario.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from marl_distributedformation_tpu.env.types import EnvParams, FormationState
from marl_distributedformation_tpu.scenarios.params import ScenarioParams

Array = jax.Array

# Per-layer fold_in salts (arbitrary, distinct; stable across versions so
# recorded robustness numbers stay reproducible).
_SALT_FAULT = 0x5C01
_SALT_ACT_NOISE = 0x5C02
_SALT_ACT_BIAS = 0x5C03
_SALT_GUST = 0x5C04
_SALT_GOAL_DIR = 0x5C05
_SALT_GOAL_SWITCH = 0x5C06
_SALT_OBS_NOISE = 0x5C07
_SALT_OBS_BIAS = 0x5C08
_SALT_COMM = 0x5C09
_SALT_OBSTACLE_DIR = 0x5C0A


def _episode_key(state: FormationState, salt: int) -> Array:
    """Constant within an episode (``state.key`` only changes at reset)."""
    return jax.random.fold_in(state.key, salt)


def _step_key(state: FormationState, salt: int) -> Array:
    """Fresh every step (folds the step counter on top of the salt)."""
    return jax.random.fold_in(_episode_key(state, salt), state.steps)


def _unit_heading(key: Array) -> Array:
    theta = jax.random.uniform(key, (), minval=0.0, maxval=2.0 * jnp.pi)
    return jnp.stack([jnp.cos(theta), jnp.sin(theta)])


def perturb_goal(
    state: FormationState, sp: ScenarioParams, params: EnvParams
) -> FormationState:
    """Pre-step goal transforms: drift + mid-episode switch (module doc)."""
    wh = jnp.array([params.width, params.height], jnp.float32)

    # Moving target: constant per-episode heading, clipped to the world.
    k_dir = _episode_key(state, _SALT_GOAL_DIR)
    moved = jnp.clip(state.goal + sp.goal_speed * _unit_heading(k_dir), 0.0, wh)
    goal = jnp.where(sp.goal_speed > 0, moved, state.goal)

    # Mid-episode switch: at max_steps // 2 the goal jumps ``goal_jump``
    # of the way to a fresh uniformly sampled target (1.0 = full resample,
    # continuous in severity so severity-0 is the identity).
    k_switch = _episode_key(state, _SALT_GOAL_SWITCH)
    margin = params.desired_radius
    fresh = (
        jax.random.uniform(k_switch, (2,), dtype=jnp.float32)
        * (wh - 2.0 * margin)
        + margin
    )
    at_switch = state.steps == params.max_steps // 2
    switched = goal + sp.goal_jump * (fresh - goal)
    goal = jnp.where(at_switch & (sp.goal_jump > 0), switched, goal)
    return state.replace(goal=goal)


def perturb_obstacles(
    state: FormationState, sp: ScenarioParams, params: EnvParams
) -> FormationState:
    """Pre-step obstacle transform: moving obstacles.

    Each obstacle drifts ``obstacle_speed`` px/step along its own
    per-episode heading, clipped to the world box. The drift is applied
    to the state the env step consumes, so the perturbed positions carry
    forward through the episode (accumulating motion) and reset with the
    formation — the env's collision penalty and the occlusion layer both
    see the moved obstacles. Identity (bitwise, and shape-trivially) when
    the env has no obstacles or ``obstacle_speed`` is 0.
    """
    if params.num_obstacles == 0:
        return state  # static shape property — nothing to move
    k_dir = _episode_key(state, _SALT_OBSTACLE_DIR)
    theta = jax.random.uniform(
        k_dir, (params.num_obstacles,), minval=0.0, maxval=2.0 * jnp.pi
    )
    headings = jnp.stack([jnp.cos(theta), jnp.sin(theta)], axis=-1)
    wh = jnp.array([params.width, params.height], jnp.float32)
    moved = jnp.clip(
        state.obstacles + sp.obstacle_speed * headings, 0.0, wh
    )
    obstacles = jnp.where(sp.obstacle_speed > 0, moved, state.obstacles)
    return state.replace(obstacles=obstacles)


def perturb_velocity(
    velocity: Array, state: FormationState, sp: ScenarioParams, params: EnvParams
) -> Array:
    """Pre-step action transforms: fault -> actuator noise -> wind."""
    del params  # layers are world-unit-native; kept for signature symmetry
    n = velocity.shape[-2]

    # Agent fault injection: a per-episode frozen set (actuator dropout —
    # the locality stress: neighbors of a dead agent must absorb it).
    k_fault = _episode_key(state, _SALT_FAULT)
    frozen = jax.random.bernoulli(
        k_fault, jnp.clip(sp.fault_prob, 0.0, 1.0), (n,)
    )
    faulted = jnp.where(frozen[..., None], 0.0, velocity)
    velocity = jnp.where(sp.fault_prob > 0, faulted, velocity)

    # Gaussian actuator noise + constant per-episode bias (miscalibrated
    # thrusters: zero-mean jitter plus a systematic drift direction).
    k_act = _step_key(state, _SALT_ACT_NOISE)
    k_bias = _episode_key(state, _SALT_ACT_BIAS)
    noisy = (
        velocity
        + sp.act_noise_sigma * jax.random.normal(k_act, velocity.shape)
        + sp.act_bias * _unit_heading(k_bias)
    )
    velocity = jnp.where(
        (sp.act_noise_sigma > 0) | (sp.act_bias > 0), noisy, velocity
    )

    # Wind field: constant vector + per-step formation-wide gust.
    k_gust = _step_key(state, _SALT_GUST)
    blown = velocity + sp.wind + sp.gust_sigma * jax.random.normal(k_gust, (2,))
    windy = (jnp.abs(sp.wind).sum() > 0) | (sp.gust_sigma > 0)
    return jnp.where(windy, blown, velocity)


def neighbor_obs_columns(
    params: EnvParams, needed_by: str = "comm dropout"
) -> np.ndarray:
    """Static ``(obs_dim,)`` mask of the env's DECLARED neighbor
    observation block — what comm dropout and obstacle occlusion blank.
    Read from the registered env's obs-layout metadata (never hard-coded
    column numbers: the formation layout baked in here once was a silent
    mismasking hazard for any other env). An env that doesn't declare a
    ``neighbor`` block fails fast naming the blocks it does declare
    (``envs.ObsLayout.require``). Own position and the goal/pursuer block
    stay visible — dropped comm, not a dead sensor."""
    from marl_distributedformation_tpu.envs import spec_for_params

    layout = spec_for_params(params).obs_layout(params)
    return layout.columns("neighbor", needed_by=needed_by)


def occlude_obs(
    obs: Array, state: FormationState, sp: ScenarioParams, params: EnvParams
) -> Array:
    """Obstacle occlusion: agents within ``obstacle_occlusion`` px of any
    obstacle lose their neighbor observation blocks — obstacles as a
    sensing hazard (the static obstacle-field layer), deterministic
    geometry with no RNG. The masked columns come from the env's declared
    layout, same discipline as comm dropout."""
    if params.num_obstacles == 0:
        return obs  # static shape property — nothing to occlude behind
    dists = jnp.linalg.norm(
        state.agents[..., :, None, :] - state.obstacles[..., None, :, :],
        axis=-1,
    )
    occluded = dists.min(axis=-1) < sp.obstacle_occlusion
    cols = jnp.asarray(
        neighbor_obs_columns(params, needed_by="obstacle occlusion")
    )
    masked = jnp.where(occluded[..., None] & cols, 0.0, obs)
    return jnp.where(sp.obstacle_occlusion > 0, masked, obs)


def perturb_obs(
    obs: Array, state: FormationState, sp: ScenarioParams, params: EnvParams
) -> Array:
    """Post-step observation transforms: sensor noise -> comm dropout ->
    obstacle occlusion.

    ``state`` is the post-step state the observation belongs to; only the
    *observed* values change — rewards, metrics, and the physical state
    stay exact (sensors lie, the world doesn't)."""
    # Gaussian sensor noise + constant per-episode per-column bias.
    k_obs = _step_key(state, _SALT_OBS_NOISE)
    k_bias = _episode_key(state, _SALT_OBS_BIAS)
    noisy = (
        obs
        + sp.obs_noise_sigma * jax.random.normal(k_obs, obs.shape)
        + sp.obs_bias * jax.random.normal(k_bias, (obs.shape[-1],))
    )
    obs = jnp.where((sp.obs_noise_sigma > 0) | (sp.obs_bias > 0), noisy, obs)

    # Comm dropout: per agent per step, blank the neighbor blocks.
    cols = jnp.asarray(neighbor_obs_columns(params))
    k_drop = _step_key(state, _SALT_COMM)
    dropped = jax.random.bernoulli(
        k_drop, jnp.clip(sp.comm_drop_prob, 0.0, 1.0), (obs.shape[-2],)
    )
    masked = jnp.where(dropped[..., None] & cols, 0.0, obs)
    obs = jnp.where(sp.comm_drop_prob > 0, masked, obs)

    return occlude_obs(obs, state, sp, params)
