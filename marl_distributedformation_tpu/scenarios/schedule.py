"""Severity schedules + domain-randomization stages for scenario training.

The shape mirrors ``train/curriculum.py``'s ``Curriculum``/``CurriculumStage``
(the repo's existing staged-training idiom): an ordered tuple of stages,
each naming the scenario subset to randomize over and a severity ramp.
Unlike the hetero curriculum — whose stage boundaries rebuild env state —
a scenario stage transition is pure data (a new probs vector + severity
scalar into the SAME compiled program), so schedules never recompile and
compose with ``iters_per_dispatch`` bursts.

Config forms accepted by ``schedule_from_cfg`` (cfg key ``scenarios``):

- a list of names: one flat stage at ``scenario_severity``
  (``scenarios=[wind,sensor_noise] scenario_severity=0.6``);
- a list of stage dicts (YAML string or parsed), each
  ``{rollouts, scenarios, severity, severity_start?}`` — severity ramps
  linearly from ``severity_start`` (default: previous stage's end, 0 for
  the first) to ``severity`` over the stage's rollouts.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from marl_distributedformation_tpu.scenarios.registry import (
    ScenarioSpec,
    get_scenario,
    register_scenario,
)


@dataclasses.dataclass(frozen=True)
class ScenarioStage:
    """One schedule phase: randomize over ``scenarios`` while severity
    ramps ``severity_start -> severity`` across ``rollouts``."""

    rollouts: int
    scenarios: Tuple[str, ...]
    severity: float = 0.5
    severity_start: Optional[float] = None

    def __post_init__(self) -> None:
        # User config reaches here — real raises, not asserts (asserts
        # vanish under -O and name neither the stage nor the key).
        if self.rollouts <= 0:
            raise ValueError(
                f"scenario stage {self.scenarios!r}: rollouts must be "
                f"positive, got {self.rollouts}"
            )
        if not self.scenarios:
            raise ValueError("a scenario stage needs at least one scenario")
        for name in self.scenarios:
            get_scenario(name)  # fail fast at construction, naming entries
        if self.severity < 0.0:
            raise ValueError(
                f"scenario stage {self.scenarios!r}: severity must be "
                f"non-negative, got {self.severity}"
            )


@dataclasses.dataclass(frozen=True)
class ScenarioSchedule:
    """An ordered sequence of stages; indexing past the end holds the
    last stage at its end severity (runs whose budget outlives the
    schedule keep training at the final difficulty)."""

    stages: Tuple[ScenarioStage, ...]

    def __post_init__(self) -> None:
        if not self.stages:
            raise ValueError("a scenario schedule needs at least one stage")

    @property
    def names(self) -> Tuple[str, ...]:
        """Union of every stage's scenarios, first-seen order — the fixed
        spec axis the jitted sampler is built over."""
        seen: List[str] = []
        for stage in self.stages:
            for name in stage.scenarios:
                if name not in seen:
                    seen.append(name)
        return tuple(seen)

    @property
    def total_rollouts(self) -> int:
        return sum(s.rollouts for s in self.stages)

    def stage_at(self, rollout: int) -> Tuple[ScenarioStage, int]:
        """(stage, rollout-within-stage) for a global rollout index."""
        done = 0
        for stage in self.stages:
            if rollout < done + stage.rollouts:
                return stage, rollout - done
            done += stage.rollouts
        last = self.stages[-1]
        return last, last.rollouts - 1

    def severity_at(self, rollout: int) -> float:
        """Host-side severity for a global rollout index (linear ramp
        within the stage; stage starts default to the previous end)."""
        start = 0.0
        done = 0
        for stage in self.stages:
            lo = stage.severity_start if stage.severity_start is not None else start
            if rollout < done + stage.rollouts:
                frac = (
                    (rollout - done) / (stage.rollouts - 1)
                    if stage.rollouts > 1
                    else 1.0
                )
                return float(lo + (stage.severity - lo) * frac)
            start = stage.severity
            done += stage.rollouts
        return float(self.stages[-1].severity)

    def probs_at(self, rollout: int) -> np.ndarray:
        """Uniform distribution over the active stage's scenarios, laid
        out on the schedule's union ``names`` axis (zeros elsewhere)."""
        stage, _ = self.stage_at(rollout)
        names = self.names
        probs = np.zeros((len(names),), np.float32)
        for name in stage.scenarios:
            probs[names.index(name)] = 1.0
        return probs / probs.sum()

    @functools.cached_property
    def _stage_table(self):
        """Vectorized twin of the per-rollout walk — one numpy row per
        stage: ``(starts, rollouts, lo, hi, probs_matrix)``. Chunked
        sampling at population scale calls the chunk methods once per
        fused dispatch with ``k`` up to the chunk size; an O(k · stages)
        Python loop there is measurable host work on the dispatch lane,
        while this table turns both chunk methods into a handful of
        vectorized ops. (``cached_property`` stores via the instance
        ``__dict__``, bypassing the frozen-dataclass ``__setattr__``.)"""
        starts, rollouts, lo, hi = [], [], [], []
        probs = []
        done = 0
        prev_end = 0.0
        names = self.names
        for stage in self.stages:
            starts.append(done)
            rollouts.append(stage.rollouts)
            lo.append(
                stage.severity_start
                if stage.severity_start is not None
                else prev_end
            )
            hi.append(stage.severity)
            row = np.zeros((len(names),), np.float32)
            for name in stage.scenarios:
                row[names.index(name)] = 1.0
            probs.append(row / row.sum())
            prev_end = stage.severity
            done += stage.rollouts
        return (
            np.asarray(starts, np.int64),
            np.asarray(rollouts, np.int64),
            np.asarray(lo, np.float64),
            np.asarray(hi, np.float64),
            np.stack(probs, axis=0),
        )

    def _stage_indices(self, rollout: int, k: int) -> np.ndarray:
        starts, rollouts, _, _, _ = self._stage_table
        r = np.arange(rollout, rollout + k)
        # Past-the-end rollouts hold the last stage (stage_at's clamp).
        return np.minimum(
            np.searchsorted(starts + rollouts, r, side="right"),
            len(starts) - 1,
        )

    def severity_chunk(self, rollout: int, k: int) -> np.ndarray:
        """``(k,)`` float32 severities for rollouts ``[rollout, rollout+k)``
        — the per-iteration schedule points a fused-scan chunk trains at
        (stage transitions and ramp steps land INSIDE the chunk, exactly
        where ``k`` host-loop dispatches would put them). Vectorized over
        the chunk, element-for-element identical to :meth:`severity_at`
        (same float64 ramp arithmetic, rounded to f32 at the end)."""
        starts, rollouts, lo, hi, _ = self._stage_table
        idx = self._stage_indices(rollout, k)
        r = np.arange(rollout, rollout + k)
        # Rollouts past the schedule clamp to the final severity
        # (frac=1); single-rollout stages ramp straight to `hi`.
        within = np.minimum(r - starts[idx], rollouts[idx] - 1)
        frac = np.where(
            rollouts[idx] > 1,
            within / np.maximum(rollouts[idx] - 1, 1),
            1.0,
        )
        return (lo[idx] + (hi[idx] - lo[idx]) * frac).astype(np.float32)

    def probs_chunk(self, rollout: int, k: int) -> np.ndarray:
        """``(k, len(names))`` scenario-mix distributions for rollouts
        ``[rollout, rollout+k)`` on the union ``names`` axis — the scanned
        twin of :meth:`probs_at`, one table gather instead of a per-index
        stage walk."""
        _, _, _, _, probs = self._stage_table
        return probs[self._stage_indices(rollout, k)]


# Derived adversarial-spec naming: one STABLE name per attacked family,
# so repeated falsifier feedback for the same scenario overwrites the
# spec in place (the schedule's name union — and with it the trainer's
# jitted sampler axis — never grows across feedback rounds).
ADV_SCENARIO_PREFIX = "adv:"


def from_falsifiers(
    falsifiers: Sequence[Any],
    rollouts: int = 100,
    include_clean: bool = True,
    severity_scale: float = 1.0,
) -> ScenarioSchedule:
    """Turn discovered worst cases into an auto-curriculum stage.

    ``falsifiers`` are ``adversary.Falsifier`` objects or their
    ``record()`` dicts (anything with ``scenario`` + ``severity`` — the
    gate's verdict payload round-trips). Each one registers a derived
    spec ``adv:{scenario}`` whose severity-1 magnitudes are the base
    family's scaled to the falsifier severity (times
    ``severity_scale``), so the returned single-stage schedule trains a
    uniform mix of every falsifier AT its discovered break point
    (severity 1.0, flat — each family at its own magnitudes, which one
    shared stage severity could not express). ``include_clean`` keeps
    the identity scenario in the mix: pure worst-case training forgets
    the clean task (the auto-curriculum retention trade, JaxMARL /
    Jumanji idiom — docs/adversarial.md).

    Consumed by the existing trainer via
    ``Trainer.update_scenario_schedule`` /
    ``request_scenario_schedule``: stage data and spec magnitudes are
    values, so the compiled train step never recompiles — see there.
    """
    if not falsifiers:
        raise ValueError("from_falsifiers needs at least one falsifier")
    names: List[str] = []
    magnitude_fields = [
        f.name
        for f in dataclasses.fields(ScenarioSpec)
        if f.name not in ("name", "description")
    ]
    for falsifier in falsifiers:
        if isinstance(falsifier, dict):
            scenario = str(falsifier["scenario"])
            severity = falsifier["severity"]
        else:
            scenario = str(falsifier.scenario)
            severity = falsifier.severity
        severity = float(severity) * float(severity_scale)
        if not math.isfinite(severity) or severity <= 0.0:
            raise ValueError(
                f"falsifier for scenario {scenario!r} has severity "
                f"{severity!r}; a training stage needs a finite positive "
                "severity (severity 0 is the clean env by construction)"
            )
        base = get_scenario(scenario)  # fail fast on unknown families
        derived = ScenarioSpec(
            name=f"{ADV_SCENARIO_PREFIX}{scenario}",
            description=(
                f"adversarial curriculum: {scenario} at discovered "
                f"falsifier severity {severity:g}"
            ),
            **{
                field: getattr(base, field) * severity
                for field in magnitude_fields
            },
        )
        register_scenario(derived, overwrite=True)
        if derived.name not in names:
            names.append(derived.name)
    if include_clean:
        names.append("clean")
    return ScenarioSchedule(
        stages=(
            ScenarioStage(
                rollouts=int(rollouts),
                scenarios=tuple(names),
                severity=1.0,
                severity_start=1.0,
            ),
        )
    )


def schedule_from_cfg(
    cfg: Any, default_severity: float = 0.5
) -> ScenarioSchedule:
    """Build a schedule from the ``scenarios`` config value (module doc).
    A YAML string (quoted CLI override) is parsed first."""
    if isinstance(cfg, str):
        import yaml

        cfg = yaml.safe_load(cfg)
    if not isinstance(cfg, (list, tuple)) or not cfg:
        raise ValueError(
            "scenarios must be a non-empty list of scenario names or "
            f"stage dicts, got {cfg!r}"
        )
    if all(isinstance(entry, str) for entry in cfg):
        return ScenarioSchedule(
            stages=(
                ScenarioStage(
                    rollouts=1,
                    scenarios=tuple(cfg),
                    severity=float(default_severity),
                    severity_start=float(default_severity),
                ),
            )
        )
    stages = []
    for entry in cfg:
        if not isinstance(entry, dict):
            raise ValueError(
                "scenario stages must all be dicts (or all names), got "
                f"{entry!r}"
            )
        unknown = set(entry) - {
            "rollouts", "scenarios", "severity", "severity_start",
        }
        if unknown:
            raise ValueError(
                f"unknown scenario-stage keys {sorted(unknown)}; valid: "
                "rollouts, scenarios, severity, severity_start"
            )
        stages.append(
            ScenarioStage(
                rollouts=int(entry.get("rollouts", 1)),
                scenarios=tuple(str(n) for n in entry["scenarios"]),
                severity=float(entry.get("severity", default_severity)),
                severity_start=(
                    float(entry["severity_start"])
                    if entry.get("severity_start") is not None
                    else None
                ),
            )
        )
    return ScenarioSchedule(stages=tuple(stages))
