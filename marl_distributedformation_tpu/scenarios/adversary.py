"""Worst-case severity search: find the minimal falsifier of a checkpoint.

The robustness matrix (``matrix.py``) answers "how does this policy do at
severities someone chose ahead of time?". This module answers the harder
question the gate actually cares about: **what is the smallest severity
at which each scenario family breaks this policy?** — the minimal-severity
*falsifier*. Because every scenario knob is a traced input
(``params.py``), a whole candidate *population* of ``ScenarioParams``
evaluates in ONE vmapped compiled program: each search generation is a
single device dispatch over ``P = 1 + families x grid`` candidates on
identical initial states, with the model parameters traced too, so the
program compiles exactly once for the life of the search — across every
generation AND every same-architecture checkpoint it ever judges
(budget-1 ``RetraceGuard`` receipt, the ``matrix.MatrixProgram``
discipline).

The search itself is **grid-refine bracketing** (deterministic — the
auto-curriculum and the promotion gate both need reproducible
falsifiers): generation 0 lays a coarse severity grid over ``(0,
max_severity]`` per family; each later generation subdivides the bracket
``(lo, hi)`` between the highest severity observed SAFE below the break
and the lowest severity observed FALSIFIED, until the bracket is tighter
than ``resolution`` or the generation budget runs out. "Falsified" means
the candidate's metric drops more than ``drop_tolerance`` (relative)
below the *clean* cell — which rides as row 0 of every generation, so
the comparison point comes through the same compiled program as every
disturbed cell. Severity 0 can never be a falsifier: the disturbance
stack is bitwise-clean at zero (pinned in tests/test_scenarios.py), so
its relative drop is exactly 0.

Downstream: ``schedule.from_falsifiers`` turns a search report into an
auto-curriculum training stage, and ``pipeline.gate.PromotionGate``
(``adversarial=True``) runs this search as an extra promotion rung —
docs/adversarial.md has the full loop.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.analysis.guards import (
    RetraceGuard,
    ledgered_jit,
)
from marl_distributedformation_tpu.env import EnvParams
from marl_distributedformation_tpu.eval import (
    policy_act_fn,
    run_episode_metrics,
)
from marl_distributedformation_tpu.scenarios.matrix import params_signature
from marl_distributedformation_tpu.scenarios.params import ScenarioParams
from marl_distributedformation_tpu.scenarios.registry import (
    ScenarioSpec,
    get_scenario,
    registered_scenarios,
)

Array = jax.Array

# Bump when the falsifier record / report shape changes
# (scripts/adversarial_search.py writes it, schedule.from_falsifiers and
# the gate verdicts consume it).
FALSIFIERS_SCHEMA = 1


@dataclasses.dataclass(frozen=True)
class AdversaryConfig:
    """What the search attacks and how hard it refines.

    ``scenarios=()`` attacks every registered family except ``clean``
    (attacking the identity stack is a no-op by construction). A family
    that survives ``max_severity`` is reported *robust*, not falsified —
    widen ``max_severity`` to keep pushing.
    """

    scenarios: Tuple[str, ...] = ()
    metric: str = "episode_return_per_agent"
    drop_tolerance: float = 0.2  # relative drop vs clean that "breaks"
    max_severity: float = 1.5
    grid: int = 6  # candidates per family per generation
    generations: int = 4
    resolution: float = 0.02  # stop refining below this bracket width
    num_formations: int = 64
    seed: int = 1234
    deterministic: bool = True

    def __post_init__(self) -> None:
        if self.grid < 1:
            raise ValueError(f"grid must be >= 1, got {self.grid}")
        if self.generations < 1:
            raise ValueError(
                f"generations must be >= 1, got {self.generations}"
            )
        if not (self.max_severity > 0.0):
            raise ValueError(
                f"max_severity must be positive, got {self.max_severity}"
            )


@dataclasses.dataclass(frozen=True)
class Falsifier:
    """One family's minimal discovered break point.

    ``params`` is the concrete knob dict at the falsifier severity
    (``ScenarioParams`` fields as host floats) — everything a training
    stage or an audit log needs to reproduce the disturbance without the
    registry.
    """

    scenario: str
    severity: float
    value: float  # the metric at the falsifier severity
    clean: float  # the same checkpoint's clean-cell metric
    drop: float  # relative drop vs clean (> drop_tolerance)
    params: Dict[str, object]

    def record(self) -> dict:
        return {
            "scenario": self.scenario,
            "severity": round(self.severity, 6),
            "value": self.value,
            "clean": self.clean,
            "drop": round(self.drop, 6),
            "params": self.params,
        }


def _relative_drop(candidate: float, baseline: float) -> float:
    """Scale-free drop of ``candidate`` below ``baseline`` (positive =
    worse) — same denomination as the promotion gate's regression checks
    (|baseline| floored at 1 so a near-zero clean return cannot turn
    noise into infinity)."""
    return (baseline - candidate) / max(abs(baseline), 1.0)


def scenario_knobs(spec: ScenarioSpec, severity: float) -> Dict[str, object]:
    """The concrete ``ScenarioParams`` knob dict of ``spec`` at
    ``severity`` (host floats; ``wind`` as a 2-list) — the portable
    falsifier payload."""
    built = spec.build(jnp.float32(severity))
    out: Dict[str, object] = {}
    for field in dataclasses.fields(ScenarioParams):
        leaf = np.asarray(getattr(built, field.name))
        out[field.name] = (
            float(leaf) if leaf.ndim == 0 else [float(v) for v in leaf]
        )
    return out


def make_population_runner(
    model,
    env_params: EnvParams,
    num_formations: int,
    deterministic: bool = True,
    max_traces: Optional[int] = 1,
) -> Tuple:
    """Build ``(run, guard)``: ``run(key, model_params, stacked_params)``
    -> per-candidate episode metrics, vmapped over a ``(P,)``-stacked
    ``ScenarioParams`` population. The key and model params broadcast, so
    every candidate rolls the SAME initial states and action-noise stream
    — cells differ only by their disturbance, exactly like the matrix.
    One jit for the whole search (``guard`` is the budget receipt)."""
    guard = RetraceGuard("adversary_population_eval", max_traces=max_traces)

    def population(key, model_params, stacked_params):
        act = policy_act_fn(model, model_params, env_params, deterministic)

        def one(sp):
            return run_episode_metrics(
                key, act, env_params, num_formations, sp
            )

        return jax.vmap(one)(stacked_params)

    run = ledgered_jit(
        population,
        guard,
        subsystem="adversary",
        program="adversary_population_eval",
    )
    return run, guard


def _stack_rows(rows: Sequence[Tuple[ScenarioSpec, float]]) -> ScenarioParams:
    """Stack per-candidate ``spec.build(severity)`` params to a leading
    ``(P,)`` axis (the vmapped program's population input). Severities
    stay host floats until ``build`` (validation without device syncs)."""
    built = [spec.build(float(sev)) for spec, sev in rows]
    return jax.tree_util.tree_map(lambda *leaves: jnp.stack(leaves), *built)


class AdversarySearch:
    """The reusable falsifier-search program (``MatrixProgram``'s
    contract): construction jits nothing, the single compile happens on
    the first generation, and every later generation — for THIS
    checkpoint or any later same-architecture one — reuses it.
    ``guard.count`` is the receipt the gate and the bench record.
    """

    def __init__(
        self,
        model,
        env_params: EnvParams,
        config: AdversaryConfig = AdversaryConfig(),
        max_traces: Optional[int] = 1,
        device=None,
    ) -> None:
        self.env_params = env_params
        self.config = config
        # Slice assignment (train/sebulba): committed inputs pin the
        # population program to ``device`` so the search runs beside —
        # not on — the learner slice. None = default placement.
        self.device = device
        names = config.scenarios or tuple(
            n for n in registered_scenarios() if n != "clean"
        )
        self.specs: Tuple[ScenarioSpec, ...] = tuple(
            get_scenario(str(n)) for n in names  # fail fast, by name
        )
        if not self.specs:
            raise ValueError("adversary search needs at least one scenario")
        self._clean_spec = get_scenario("clean")
        # Fixed population: 1 clean anchor row + grid rows per family —
        # shapes never change, so neither does the compiled program.
        self.population = 1 + len(self.specs) * config.grid
        self.run, self.guard = make_population_runner(
            model,
            env_params,
            config.num_formations,
            config.deterministic,
            max_traces,
        )
        self.key = jax.random.PRNGKey(config.seed)
        if device is not None:
            self.key = jax.device_put(self.key, device)
        self._signature: Optional[Tuple] = None
        self.candidates_evaluated = 0
        self.search_seconds_total = 0.0

    @property
    def compile_count(self) -> int:
        """Traces of the shared population program so far (stays 1
        across every generation and checkpoint)."""
        return self.guard.count

    def check_params(self, params, origin: str = "<candidate>") -> None:
        """One-architecture contract, the matrix's rule: a different
        structure/shape would blow the budget-1 guard mid-search with a
        confusing retrace error — fail by name instead."""
        sig = params_signature(params)
        if self._signature is None:
            self._signature = sig
        elif sig != self._signature:
            raise ValueError(
                f"checkpoint {origin} has a different parameter "
                "structure/shape than the first candidate — the search "
                "shares one compiled population program, so all "
                "candidates must be one architecture"
            )

    # -- evaluation ------------------------------------------------------

    def _evaluate(
        self, params, rows: List[Tuple[ScenarioSpec, float]]
    ) -> np.ndarray:
        """One generation: pad ``rows`` to the fixed population with
        clean anchors, dispatch the compiled program once, return the
        config metric per row (host floats)."""
        padded = list(rows) + [
            (self._clean_spec, 0.0) for _ in range(self.population - len(rows))
        ]
        if self.device is not None:
            params = jax.device_put(params, self.device)
        out = self.run(self.key, params, _stack_rows(padded))
        metric = out.get(self.config.metric)
        if metric is None:
            raise ValueError(
                f"metric {self.config.metric!r} absent from the episode "
                f"eval output (emitted: {', '.join(sorted(out))})"
            )
        return np.asarray(jax.device_get(metric), np.float64)[: len(rows)]

    def evaluate_cells(
        self,
        params,
        cells: Sequence[Tuple[str, float]],
        origin: str = "<candidate>",
    ) -> List[float]:
        """The config metric at explicit ``(scenario, severity)`` cells —
        through the SAME compiled program (the bench's worst-case
        comparison hook). ``len(cells)`` must fit the population."""
        self.check_params(params, origin)
        if len(cells) > self.population:
            raise ValueError(
                f"{len(cells)} cells exceed the population "
                f"({self.population}) — split into multiple calls"
            )
        rows = [
            (get_scenario(str(name)), float(sev)) for name, sev in cells
        ]
        return [float(v) for v in self._evaluate(params, rows)]

    # -- the search ------------------------------------------------------

    def _candidate_severities(
        self,
        lo: float,
        hi: Optional[float],
        done: bool,
    ) -> List[float]:
        """The next generation's probes for one family. Fresh families
        grid ``(0, max_severity]``; bracketed families subdivide
        ``(lo, hi)``; finished families re-probe their break point
        (population shape is fixed — repeats are the cheap filler)."""
        cfg = self.config
        if done:
            return [hi if hi is not None else cfg.max_severity] * cfg.grid
        if hi is None:
            return [
                cfg.max_severity * (i + 1) / cfg.grid
                for i in range(cfg.grid)
            ]
        return [
            lo + (hi - lo) * (i + 1) / (cfg.grid + 1)
            for i in range(cfg.grid)
        ]

    def search(self, params, origin: str = "<candidate>") -> dict:
        """Find the minimal-severity falsifier per scenario family.

        Host-side control flow only — the fitness values are drained to
        numpy before ANY Python comparison touches them (graftlint rule
        17's subject: a traced comparison in this loop would concretize),
        and every device round trip is one compiled population dispatch.
        Deterministic at fixed config+params. Returns the report dict
        (``falsifiers`` carry ``Falsifier.record()`` payloads).
        """
        self.check_params(params, origin)
        cfg = self.config
        t0 = time.perf_counter()
        lo: Dict[str, float] = {s.name: 0.0 for s in self.specs}
        hi: Dict[str, Optional[float]] = {s.name: None for s in self.specs}
        hi_value: Dict[str, float] = {}
        # A family is done when its bracket converged, or when a full
        # fresh grid up to max_severity found nothing to refine toward.
        done: Dict[str, bool] = {s.name: False for s in self.specs}
        clean: Optional[float] = None
        generations_run = 0
        for _ in range(cfg.generations):
            if all(done.values()):
                break
            rows: List[Tuple[ScenarioSpec, float]] = [(self._clean_spec, 0.0)]
            placements: List[Tuple[str, float]] = []
            for spec in self.specs:
                sevs = self._candidate_severities(
                    lo[spec.name], hi[spec.name], done[spec.name]
                )
                rows.extend((spec, s) for s in sevs)
                placements.extend((spec.name, s) for s in sevs)
            values = self._evaluate(params, rows)
            generations_run += 1
            self.candidates_evaluated += self.population
            if clean is None:
                clean = float(values[0])
            results: Dict[str, List[Tuple[float, float]]] = {}
            for (name, sev), value in zip(placements, values[1:]):
                results.setdefault(name, []).append((sev, float(value)))
            for spec in self.specs:
                name = spec.name
                if done[name]:
                    continue
                had_break = hi[name] is not None
                for sev, value in results[name]:
                    if _relative_drop(value, clean) > cfg.drop_tolerance:
                        if hi[name] is None or sev < hi[name]:
                            hi[name] = sev
                            hi_value[name] = value
                # Safe probes only raise the floor BELOW the break point
                # (returns are not guaranteed monotone in severity — a
                # safe pocket above the first break is not the bracket).
                for sev, value in results[name]:
                    if (
                        _relative_drop(value, clean) <= cfg.drop_tolerance
                        and sev > lo[name]
                        and (hi[name] is None or sev < hi[name])
                    ):
                        lo[name] = sev
                if hi[name] is None:
                    # A full grid up to max_severity stayed safe: the
                    # family is robust in range; re-gridding finds the
                    # same answer, so stop probing it.
                    done[name] = not had_break
                elif hi[name] - lo[name] <= cfg.resolution:
                    done[name] = True
        seconds = time.perf_counter() - t0
        self.search_seconds_total += seconds

        falsifiers: List[Falsifier] = []
        robust: List[str] = []
        for spec in self.specs:
            severity = hi[spec.name]
            if severity is None:
                robust.append(spec.name)
                continue
            value = hi_value[spec.name]
            falsifiers.append(
                Falsifier(
                    scenario=spec.name,
                    severity=float(severity),
                    value=value,
                    clean=float(clean),
                    drop=_relative_drop(value, float(clean)),
                    params=scenario_knobs(spec, float(severity)),
                )
            )
        return {
            "schema": FALSIFIERS_SCHEMA,
            "origin": str(origin),
            "metric": cfg.metric,
            "drop_tolerance": cfg.drop_tolerance,
            "max_severity": cfg.max_severity,
            "resolution": cfg.resolution,
            "scenarios": [s.name for s in self.specs],
            "clean": float(clean) if clean is not None else None,
            "falsifiers": [f.record() for f in falsifiers],
            "robust": robust,
            "generations": generations_run,
            "population": self.population,
            "candidates": generations_run * self.population,
            "num_formations": cfg.num_formations,
            "seed": cfg.seed,
            "deterministic": cfg.deterministic,
            "eval_compiles": self.compile_count,
            "search_seconds": round(seconds, 4),
        }

    # -- observability ---------------------------------------------------

    def candidates_per_sec(self) -> float:
        """Search throughput in scenario candidates evaluated per second
        (the bench's ``adversarial_candidates_per_sec``)."""
        if self.search_seconds_total <= 0:
            return 0.0
        return self.candidates_evaluated / self.search_seconds_total


class ContinuousAdversary:
    """Falsifier search as a CONTINUOUS lane over the live checkpoint
    stream — outside the promotion gate's latency budget.

    The gate's adversarial rung (``GateConfig.adversarial``) runs the
    search inline per candidate, which puts generations x population
    eval dispatches on the promotion critical path. This wrapper moves
    the same search off that path: it tails a trainer's checkpoint
    directory (``utils.checkpoint.latest_checkpoint`` — always the
    newest, skipping intermediates; worst-case coverage matters more
    than per-checkpoint coverage), attacks each new checkpoint with ONE
    long-lived :class:`AdversarySearch` (budget-1 compile receipt across
    every checkpoint it ever judges), and feeds discovered falsifiers
    back through ``on_schedule`` as a ``from_falsifiers`` curriculum
    stage — the train -> falsify -> train loop, decoupled from
    promotion. With a sebulba trainer the scenario seam applies the new
    schedule at the next actor dispatch with ZERO train-program
    recompiles (severity and knobs are traced inputs).

    ``device`` pins the search's compiled program to its own slice
    (train/sebulba's gate/adversary assignment) so continuous attacking
    never contends with the learner. Drive it deterministically with
    :meth:`poll_once` (tests, campaigns) or as a daemon via
    :meth:`run`/:meth:`stop`.
    """

    def __init__(
        self,
        log_dir,
        env_params: EnvParams,
        config: AdversaryConfig = AdversaryConfig(),
        device=None,
        on_schedule=None,
        feedback_rollouts: int = 50,
    ) -> None:
        from pathlib import Path

        self.log_dir = Path(log_dir)
        self.env_params = env_params
        self.config = config
        self.device = device
        self.on_schedule = on_schedule
        self.feedback_rollouts = int(feedback_rollouts)
        self.search: Optional[AdversarySearch] = None  # lazy, budget-1
        self.last_step = -1
        self.reports: List[dict] = []
        self.schedules_pushed = 0
        self.errors: List[str] = []
        self._stop = None  # threading.Event, created by run()
        self._thread = None

    def poll_once(self) -> Optional[dict]:
        """Attack the newest unseen checkpoint; None when there is
        nothing new. A bad candidate (corrupt file, architecture drift)
        is a recorded error, never a dead lane. On discovered
        falsifiers, pushes the feedback schedule through
        ``on_schedule`` (advisory: a failing callback is recorded,
        the lane keeps attacking)."""
        from marl_distributedformation_tpu.compat.policy import LoadedPolicy
        from marl_distributedformation_tpu.obs import get_registry
        from marl_distributedformation_tpu.utils.checkpoint import (
            checkpoint_step,
            latest_checkpoint,
        )

        path = latest_checkpoint(self.log_dir)
        if path is None:
            return None
        try:
            step = checkpoint_step(path)
        except ValueError:
            return None
        if step <= self.last_step:
            return None
        try:
            pol = LoadedPolicy.from_checkpoint(
                path,
                act_dim=self.env_params.act_dim,
                env_params=self.env_params,
            )
            if self.search is None:
                self.search = AdversarySearch(
                    pol.model,
                    self.env_params,
                    self.config,
                    device=self.device,
                )
            report = self.search.search(pol.params, origin=str(path))
        except Exception as e:  # noqa: BLE001 — a bad checkpoint must
            # not kill the lane; the next one may be fine.
            self.errors.append(f"{path.name}: {e!r}"[:300])
            del self.errors[:-32]
            self.last_step = step  # never re-attack a broken file
            return None
        self.last_step = step
        report["step"] = step
        self.reports.append(report)
        registry = get_registry()
        registry.counter("adversary_continuous_searches_total").inc()
        registry.gauge("adversary_continuous_falsifiers").set(
            float(len(report["falsifiers"]))
        )
        if report["falsifiers"] and self.on_schedule is not None:
            from marl_distributedformation_tpu.scenarios.schedule import (
                from_falsifiers,
            )

            try:
                self.on_schedule(
                    from_falsifiers(
                        report["falsifiers"],
                        rollouts=self.feedback_rollouts,
                    )
                )
                self.schedules_pushed += 1
            except Exception as e:  # noqa: BLE001 — feedback is advisory
                self.errors.append(f"on_schedule: {e!r}"[:300])
                del self.errors[:-32]
        return report

    # -- background lane -------------------------------------------------

    def run(self, interval_s: float = 1.0) -> "ContinuousAdversary":
        """Poll as a daemon thread every ``interval_s`` (the continuous
        mode scripts/always_learning.py wires next to a sebulba run)."""
        import threading

        if self._thread is not None:
            return self
        self._stop = threading.Event()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — keep the lane up
                    self.errors.append(repr(e)[:300])
                    del self.errors[:-32]
                self._stop.wait(interval_s)

        self._thread = threading.Thread(
            target=loop, name="continuous-adversary", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=30.0)
        self._thread = None

    def summary(self) -> dict:
        """Flat lane report (always_learning's JSON line picks it up)."""
        return {
            "adversary_searches": len(self.reports),
            "adversary_last_step": self.last_step,
            "adversary_schedules_pushed": self.schedules_pushed,
            "adversary_falsifiers_last": (
                len(self.reports[-1]["falsifiers"]) if self.reports else 0
            ),
            "adversary_compiles": (
                self.search.compile_count if self.search is not None else 0
            ),
            "adversary_errors": list(self.errors),
        }
