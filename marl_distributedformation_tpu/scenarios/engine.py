"""Scenario engine: the clean env step wrapped in the disturbance stack.

``scenario_step`` composes the layers (``layers.py``) around the
REGISTERED env's ``step`` in a fixed order — goal transforms, obstacle
transforms, actuator transforms, clean step, observation transforms —
without forking any env. The env is resolved from the params type
(``envs.spec_for_params``), a trace-time static dispatch: for formation
params the resolved functions ARE ``env/formation.py``'s, so that path
is bitwise identical to the pre-registry engine; any registered env
(pursuit-evasion, tomorrow's) gets the whole disturbance stack for free.
``scenario_step_batch`` is the vmapped form and accepts the
scenario parameters either unbatched (every formation runs the same
scenario — the eval shape) or with a leading ``(M,)`` axis (a mixed batch
— the domain-randomization training shape); which one is a static
property of the pytree's shapes, so both share the same code path.

Everything scenario-specific is *data* (``ScenarioParams``), so a jitted
caller that takes the params as an argument compiles exactly once for
every registered scenario at every severity (pinned with budget-1
``analysis.guards.RetraceGuard`` in tests/test_scenarios.py).
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax

from marl_distributedformation_tpu.env.types import (
    EnvParams,
    FormationState,
    Transition,
)
from marl_distributedformation_tpu.envs import spec_for_params
from marl_distributedformation_tpu.scenarios.layers import (
    perturb_goal,
    perturb_obs,
    perturb_obstacles,
    perturb_velocity,
)
from marl_distributedformation_tpu.scenarios.params import ScenarioParams

Array = jax.Array


def scenario_step(
    state: FormationState,
    velocity: Array,
    sp: ScenarioParams,
    params: EnvParams,
    with_obs: bool = True,
) -> Tuple[FormationState, Transition]:
    """One formation, one step, through the disturbance stack."""
    spec = spec_for_params(params)
    state = perturb_goal(state, sp, params)
    state = perturb_obstacles(state, sp, params)
    velocity = perturb_velocity(velocity, state, sp, params)
    next_state, tr = spec.step(state, velocity, params, with_obs=with_obs)
    if with_obs:
        tr = tr.replace(obs=perturb_obs(tr.obs, next_state, sp, params))
    return next_state, tr


def _params_axis(sp: ScenarioParams) -> int | None:
    """0 when the params carry a per-formation batch axis, else None —
    a static (shape-level) property, safe to branch on at trace time."""
    return 0 if sp.fault_prob.ndim else None


def scenario_step_batch(
    state: FormationState,
    velocity: Array,
    sp: ScenarioParams,
    params: EnvParams,
) -> Tuple[FormationState, Transition]:
    """Batched scenario step — the disturbance-stacked ``step_batch``.

    Mirrors ``step_batch``'s knn routing: the per-formation step runs
    without obs and the neighbor-graph observation is computed once over
    the whole batch (so the fused Pallas search sees ``(M, N, 2)``), then
    the observation layers run on the batch.
    """
    axis = _params_axis(sp)
    if params.obs_mode == "knn":
        next_state, tr = jax.vmap(
            functools.partial(scenario_step, with_obs=False),
            in_axes=(0, 0, axis, None),
        )(state, velocity, sp, params)
        obs = spec_for_params(params).obs(next_state, params)
        obs = jax.vmap(perturb_obs, in_axes=(0, 0, axis, None))(
            obs, next_state, sp, params
        )
        return next_state, tr.replace(obs=obs)
    return jax.vmap(scenario_step, in_axes=(0, 0, axis, None))(
        state, velocity, sp, params
    )


def make_scenario_step(
    params: EnvParams,
) -> Callable[[FormationState, Array, ScenarioParams], Tuple[FormationState, Transition]]:
    """``(state, velocity, scenario_params) -> (state, transition)`` closed
    over the static env params — the trainer's scenario ``env_step_fn``
    (the scenario params stay a traced argument, never a closure
    constant, so severity schedules never recompile)."""

    def step_fn(state, velocity, sp):
        return scenario_step_batch(state, velocity, sp, params)

    return step_fn
