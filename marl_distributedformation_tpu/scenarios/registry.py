"""ScenarioSpec registry: named, severity-parameterized disturbance recipes.

A ``ScenarioSpec`` records the layer magnitudes *at severity 1.0* as plain
Python floats (static, hashable); ``spec.build(severity)`` scales them by a
**traced** severity into a ``ScenarioParams`` pytree. The registry is the
single source of scenario names for training (domain randomization over a
stage's scenario set), evaluation (``evaluate.py scenario=...``), and the
robustness matrix (``scripts/robustness_matrix.py``) — and every lookup
fails fast on unknown names, listing the valid entries, instead of
silently falling back to the clean env.
"""

from __future__ import annotations

import dataclasses
import difflib
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from marl_distributedformation_tpu.scenarios.params import ScenarioParams

Array = jax.Array


def _validate_severity(severity, where: str) -> None:
    """Fail fast on a concrete severity that is negative or non-finite —
    a negative severity would silently FLIP every perturbation sign
    through the linear magnitude scaling (wind blowing backwards is a
    different scenario, not a milder one), and NaN/inf poisons every
    downstream cell. Traced severities (inside a jitted sampler/step)
    skip the check: values are unknowable at trace time, and every
    host-side entry into the traced path runs through here first."""
    try:
        value = np.asarray(severity)
    except Exception:  # noqa: BLE001 — a tracer: jit-time, concrete
        return  # values validated at the host-side call sites
    if not np.all(np.isfinite(value)):
        raise ValueError(
            f"{where}: severity must be finite, got {value!r}"
        )
    if np.any(value < 0.0):
        raise ValueError(
            f"{where}: severity must be >= 0, got {value!r} — a negative "
            "severity flips perturbation signs via the linear magnitude "
            "scaling instead of weakening them"
        )


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """Layer magnitudes at severity 1.0 (see ``ScenarioParams`` for units).

    Frozen + hashable so specs can ride as static jit closure state; the
    traced half only appears when ``build`` scales them by severity.
    """

    name: str
    description: str = ""
    fault_prob: float = 0.0
    act_noise_sigma: float = 0.0
    act_bias: float = 0.0
    wind_x: float = 0.0
    wind_y: float = 0.0
    gust_sigma: float = 0.0
    goal_speed: float = 0.0
    goal_jump: float = 0.0
    obs_noise_sigma: float = 0.0
    obs_bias: float = 0.0
    comm_drop_prob: float = 0.0
    obstacle_speed: float = 0.0
    obstacle_occlusion: float = 0.0

    def build(self, severity) -> ScenarioParams:
        """Scale the severity-1 magnitudes by a traced ``severity``
        (probabilities clipped to [0, 1]). A concrete severity that is
        negative or non-finite raises a clean ValueError naming the
        scenario (traced severities are validated at their host-side
        entry points instead)."""
        _validate_severity(severity, f"scenario {self.name!r}")
        s = jnp.asarray(severity, jnp.float32)

        def scaled(base: float) -> Array:
            return jnp.float32(base) * s

        return ScenarioParams(
            fault_prob=jnp.clip(scaled(self.fault_prob), 0.0, 1.0),
            act_noise_sigma=scaled(self.act_noise_sigma),
            act_bias=scaled(self.act_bias),
            wind=jnp.stack([scaled(self.wind_x), scaled(self.wind_y)]),
            gust_sigma=scaled(self.gust_sigma),
            goal_speed=scaled(self.goal_speed),
            goal_jump=jnp.clip(scaled(self.goal_jump), 0.0, 1.0),
            obs_noise_sigma=scaled(self.obs_noise_sigma),
            obs_bias=scaled(self.obs_bias),
            comm_drop_prob=jnp.clip(scaled(self.comm_drop_prob), 0.0, 1.0),
            obstacle_speed=scaled(self.obstacle_speed),
            obstacle_occlusion=scaled(self.obstacle_occlusion),
        )


# Magnitudes are sized against the env's own scale (400x600 world,
# max_speed 10 px/step, observations normalized to ~[-1, 1]): severity 1.0
# is "hard but not hopeless" for the trained north-star policy.
_DEFAULT_SPECS: Tuple[ScenarioSpec, ...] = (
    ScenarioSpec("clean", "the unperturbed environment (identity stack)"),
    ScenarioSpec(
        "actuator_fault",
        "per-episode frozen agents (actuator dropout): each agent dead "
        "with prob 0.4*severity — neighbors must absorb the gap",
        fault_prob=0.4,
    ),
    ScenarioSpec(
        "actuator_noise",
        "miscalibrated thrusters: Gaussian velocity jitter + a constant "
        "per-episode drift direction",
        act_noise_sigma=5.0,
        act_bias=2.0,
    ),
    ScenarioSpec(
        "sensor_noise",
        "noisy observations: Gaussian jitter + a constant per-episode "
        "per-column bias on everything each agent sees",
        obs_noise_sigma=0.1,
        obs_bias=0.05,
    ),
    ScenarioSpec(
        "wind",
        "constant wind field plus per-step formation-wide gusts",
        wind_x=4.0,
        wind_y=2.0,
        gust_sigma=3.0,
    ),
    ScenarioSpec(
        "moving_goal",
        "the formation target drifts along a per-episode heading",
        goal_speed=5.0,
    ),
    ScenarioSpec(
        "goal_switch",
        "mid-episode target switch: at max_steps/2 the goal jumps "
        "severity of the way to a fresh target",
        goal_jump=1.0,
    ),
    ScenarioSpec(
        "comm_dropout",
        "lossy comms: each agent's neighbor observation blocks blank "
        "with prob 0.5*severity per step",
        comm_drop_prob=0.5,
    ),
    # Obstacle-field layers (ROADMAP item 3a). Both are identity when the
    # env has no obstacles (num_obstacles is a static shape property) —
    # train/evaluate with num_obstacles > 0 to give them teeth.
    ScenarioSpec(
        "obstacle_field",
        "static obstacle field as a sensing hazard: agents within "
        "80*severity px of an obstacle lose their neighbor obs blocks "
        "(avoidance pressure comes from the env's obstacle penalty; "
        "needs num_obstacles > 0)",
        obstacle_occlusion=80.0,
    ),
    ScenarioSpec(
        "moving_obstacles",
        "obstacles drift 3*severity px/step along per-episode headings "
        "(clipped to the world) — moving obstacle avoidance; needs "
        "num_obstacles > 0",
        obstacle_speed=3.0,
    ),
    ScenarioSpec(
        "storm",
        "3-layer stress stack: wind + actuator noise + sensor noise",
        wind_x=3.0,
        wind_y=1.5,
        gust_sigma=2.0,
        act_noise_sigma=2.0,
        obs_noise_sigma=0.05,
    ),
)

_REGISTRY: Dict[str, ScenarioSpec] = {s.name: s for s in _DEFAULT_SPECS}


def registered_scenarios() -> Tuple[str, ...]:
    """Registered scenario names, registration order."""
    return tuple(_REGISTRY)


def register_scenario(spec: ScenarioSpec, overwrite: bool = False) -> None:
    """Add a scenario (how-to: docs/scenarios.md). Overwriting a name is
    opt-in so a typo'd registration cannot shadow a stock scenario."""
    if spec.name in _REGISTRY and not overwrite:
        raise ValueError(
            f"scenario {spec.name!r} is already registered; pass "
            "overwrite=True to replace it"
        )
    _REGISTRY[spec.name] = spec


def get_scenario(name: str) -> ScenarioSpec:
    """Lookup that fails fast: unknown names raise with the valid registry
    entries (and a did-you-mean) — never a silent clean-env fallback."""
    spec = _REGISTRY.get(name)
    if spec is None:
        close = difflib.get_close_matches(str(name), _REGISTRY, n=1)
        hint = f" (did you mean {close[0]!r}?)" if close else ""
        raise ValueError(
            f"unknown scenario {name!r}{hint}; registered scenarios: "
            f"{', '.join(registered_scenarios())}"
        )
    return spec


def scenario_params_for(name: str, severity) -> ScenarioParams:
    """``get_scenario(name).build(severity)`` — the one-liner eval entry."""
    return get_scenario(name).build(severity)


def sample_scenario_batch(
    key: Array,
    severity,
    probs: Array,
    specs: Sequence[ScenarioSpec],
    num_formations: int,
) -> ScenarioParams:
    """Domain randomization: draw one scenario per formation.

    ``probs`` is a traced ``(len(specs),)`` distribution (a stage's active
    subset is zeros elsewhere), ``severity`` a traced scalar — so a jitted
    sampler over a fixed spec union never retraces across stages or
    severity schedules. Returns ``ScenarioParams`` with a leading ``(M,)``
    axis on every leaf. A concrete negative / non-finite severity fails
    fast naming the spec set (the traced path validates at its host-side
    entry instead).
    """
    _validate_severity(
        severity,
        f"scenario batch over [{', '.join(s.name for s in specs)}]",
    )
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves),
        *[spec.build(severity) for spec in specs],
    )
    idx = jax.random.choice(
        key, len(specs), (num_formations,), p=probs
    )
    return jax.tree_util.tree_map(lambda leaf: leaf[idx], stacked)


def _all_scenarios_doc() -> str:  # pragma: no cover — docs helper
    return "\n".join(
        f"- `{s.name}`: {s.description}" for s in _REGISTRY.values()
    )
