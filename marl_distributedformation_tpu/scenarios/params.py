"""Traced scenario parameters: the data that *is* the scenario.

Every disturbance layer (``layers.py``) reads its magnitudes from this
pytree, and every field is a jnp array — a **traced input** to the jitted
step, never a Python constant baked into the program. That inversion is
the whole design: one compiled train/eval step covers every registered
scenario at every severity, because switching scenario or severity only
changes *values*, never shapes, dtypes, or program structure (the
JaxMARL/Jumanji recipe for scenario suites — parameterized variants in
one program, not a zoo of env subclasses).

Shapes: scalars are ``()`` per formation; a batch of formations carries a
leading ``(M,)`` axis on every leaf (``(M, 2)`` for ``wind``) so one
vmapped step can mix scenarios across the batch. ``ScenarioParams.zeros``
is the identity element: every layer is a bitwise no-op at all-zero
parameters (pinned by tests/test_scenarios.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class ScenarioParams:
    """Per-formation disturbance magnitudes (all traced, see module doc).

    Layer order of application is fixed (docs/scenarios.md): goal
    transforms -> actuator transforms -> env step -> observation
    transforms.
    """

    fault_prob: jax.Array  # () in [0,1] — per-agent per-episode freeze prob
    act_noise_sigma: jax.Array  # () px/step — Gaussian actuator noise
    act_bias: jax.Array  # () px/step — constant per-episode actuator bias
    wind: jax.Array  # (2,) px/step — constant wind velocity field
    gust_sigma: jax.Array  # () px/step — per-step formation-wide gust
    goal_speed: jax.Array  # () px/step — goal drift along an episode heading
    goal_jump: jax.Array  # () in [0,1] — mid-episode goal switch fraction
    obs_noise_sigma: jax.Array  # () obs units — Gaussian sensor noise
    obs_bias: jax.Array  # () obs units — constant per-episode sensor bias
    comm_drop_prob: jax.Array  # () in [0,1] — per-step neighbor-block dropout
    obstacle_speed: jax.Array  # () px/step — obstacle drift (moving obstacles)
    obstacle_occlusion: jax.Array  # () px — neighbor-obs blackout radius
    #   around obstacles (static obstacle field as a sensing hazard)

    @classmethod
    def zeros(cls) -> "ScenarioParams":
        """The identity scenario (clean env, bitwise)."""
        z = jnp.zeros((), jnp.float32)
        return cls(
            fault_prob=z,
            act_noise_sigma=z,
            act_bias=z,
            wind=jnp.zeros((2,), jnp.float32),
            gust_sigma=z,
            goal_speed=z,
            goal_jump=z,
            obs_noise_sigma=z,
            obs_bias=z,
            comm_drop_prob=z,
            obstacle_speed=z,
            obstacle_occlusion=z,
        )


def broadcast_params(sp: ScenarioParams, num_formations: int) -> ScenarioParams:
    """Tile one formation's params to a ``(M,)``-leading batch (every
    formation runs the same scenario — the eval-matrix shape)."""
    return jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(
            leaf, (num_formations, *jnp.shape(leaf))
        ),
        sp,
    )
