"""vmap-in-axes-arity: ``in_axes`` container length vs the mapped arity.

``jax.vmap(f, in_axes=(0, None))`` promises the mapped function exactly
two positional arguments. When the tuple's length disagrees with either
the function's signature or the immediate call site, JAX raises only at
*trace* time — which for a library helper can be arbitrarily far from
the mistake, inside someone else's jit, with the axes spec long out of
view. The classic authoring bug is editing a function's signature (or
the call) and forgetting the axes tuple.

Two checks, both purely static and deliberately conservative (only
top-level tuple/list ``in_axes`` literals; only ``Name``/``lambda``
targets resolvable in the same module; skipped entirely for wrapped
targets like ``functools.partial`` where the effective arity is not
syntactic):

1. signature: a resolvable target must be able to accept exactly
   ``len(in_axes)`` positional args (``required <= len <= total``,
   ``*args`` accepts anything);
2. call site: ``jax.vmap(f, in_axes=...)(a, b, c)`` must pass exactly
   ``len(in_axes)`` positional args (no starred/keyword args — those
   make the count non-syntactic and are skipped).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_VMAP_NAMES = frozenset({"jax.vmap", "vmap"})


def _in_axes_literal(node: ast.Call) -> Optional[ast.AST]:
    """The ``in_axes`` expression when given as a top-level tuple/list
    literal, else None (ints, Names, nested pytrees: out of scope)."""
    expr: Optional[ast.AST] = None
    if len(node.args) >= 2:
        expr = node.args[1]
    for kw in node.keywords:
        if kw.arg == "in_axes":
            expr = kw.value
    if isinstance(expr, (ast.Tuple, ast.List)):
        return expr
    return None


def _rebound_names(ctx: ModuleContext) -> frozenset:
    """Names that are assignment targets or function parameters anywhere
    in the module — a def with such a name may be shadowed or rebound
    (``f = functools.partial(f, ...)``), so its syntactic arity cannot
    be trusted. Computed once per module and cached on the context."""
    cached = getattr(ctx, "_vmap_rebound_names", None)
    if cached is not None:
        return cached
    names = set()
    for node in ast.walk(ctx.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
            targets = [node.target]
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            a = node.args
            names.update(
                arg.arg
                for arg in (
                    *a.posonlyargs, *a.args, *a.kwonlyargs,
                    *filter(None, (a.vararg, a.kwarg)),
                )
            )
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    names.add(n.id)
    result = frozenset(names)
    ctx._vmap_rebound_names = result
    return result


def _resolve_targets(ctx: ModuleContext, node: ast.AST) -> List[ast.AST]:
    """Same-module defs/lambdas the mapped callable certainly denotes;
    empty when the target is wrapped, imported, an attribute, or a name
    that is also rebound/shadowed somewhere in the module (no guessing —
    a partial changes the effective arity)."""
    if isinstance(node, ast.Lambda):
        return [node]
    if isinstance(node, ast.Name) and node.id not in _rebound_names(ctx):
        return list(ctx._defs_by_name.get(node.id, ()))
    return []


def _fits(fn: ast.AST, n: int) -> bool:
    """Can ``fn`` accept exactly ``n`` positional arguments?"""
    args = fn.args
    if args.vararg is not None:
        return True
    total = len(args.posonlyargs) + len(args.args)
    required = total - len(args.defaults)
    return required <= n <= total


class VmapInAxesArity(Rule):
    name = "vmap-in-axes-arity"
    default_severity = "error"
    description = (
        "vmap in_axes tuple length disagrees with the mapped function's "
        "arity or the immediate call — raises only at trace time, far "
        "from the mistake"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in _VMAP_NAMES or not node.args:
                continue
            axes = _in_axes_literal(node)
            if axes is None:
                continue
            n = len(axes.elts)

            targets = _resolve_targets(ctx, node.args[0])
            if targets and not any(_fits(t, n) for t in targets):
                names = getattr(node.args[0], "id", "<lambda>")
                yield (
                    axes.lineno,
                    axes.col_offset,
                    f"in_axes has {n} entr{'y' if n == 1 else 'ies'} but "
                    f"`{names}` cannot take {n} positional argument(s) — "
                    "the axes spec and the signature drifted apart",
                )
                continue  # one finding per call is enough

            parent = ctx.parents.get(node)
            if (
                isinstance(parent, ast.Call)
                and parent.func is node
                and not parent.keywords
                and not any(isinstance(a, ast.Starred) for a in parent.args)
                and len(parent.args) != n
            ):
                yield (
                    axes.lineno,
                    axes.col_offset,
                    f"in_axes has {n} entr{'y' if n == 1 else 'ies'} but "
                    f"the vmapped call passes {len(parent.args)} "
                    "argument(s) — every mapped argument needs its axis "
                    "(and vice versa)",
                )
