"""ledger-record-in-traced-scope: program-ledger recording smuggled
into compiled code.

The ProgramLedger (``marl_distributedformation_tpu/obs/ledger.py``) is
host-only by the same contract as the Tracer (rule 15), the
MetricsRegistry (rule 18), and the chaos plane (rule 19): programs
register at the compile seam AROUND the jitted call and dispatch
latencies are recorded at host dispatch seams — never inside the
program being measured. A ``get_ledger().dispatch(...)`` inside a
jit/vmap/scan traced scope is doubly wrong: at best it records once at
TRACE time (a census that silently measures nothing), at worst a tracer
leaks into the ledger's host dicts — and either way host mutation has
leaked into what must stay a pure compiled program, which is exactly
what would break the budget-1 compile receipts the ledger itself
attributes.

Detection surfaces (rule 15/18/19's reachability analysis extended to
the ledger API):

- record calls whose receiver chain names the ledger —
  ``ledger.dispatch(...)``, ``self._ledger.register(...)``,
  ``get_ledger().record_watermark(...)`` — with the method in the
  recording set (``dispatch``/``register``/``record_watermark``/
  ``write_census``);
- names imported from an ``obs``/``ledger`` module and called through
  (``from ...obs.ledger import get_ledger``), plus the guards-side
  sampling helper ``sample_device_watermark`` by name;
- one same-module call hop, like rules 12/15/18/19: a traced scope
  calling a local helper whose body records is the same hazard wearing
  a function name.

Receiver chains must look ledger-like before the method-name check
applies — ``atexit.register(...)`` and an argparse ``.register`` stay
clean.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Recording entry points on a ProgramLedger handle (obs/ledger.py).
_RECORD_METHODS = frozenset(
    {"dispatch", "register", "record_watermark", "write_census"}
)
# Bare helpers that record into the ledger when called (guards.py).
_RECORD_FUNCTIONS = frozenset({"sample_device_watermark"})
# Module-path fragments that mark an import as the ledger plane.
_LEDGER_MODULE_PARTS = frozenset({"obs", "ledger"})


def _is_ledger_module(module: str) -> bool:
    return any(part in _LEDGER_MODULE_PARTS for part in module.split("."))


class LedgerRecordInTracedScope(Rule):
    name = "ledger-record-in-traced-scope"
    default_severity = "error"
    description = (
        "obs.ProgramLedger registration/dispatch recording reachable "
        "inside a jit/scan/vmap traced scope — host work smuggled into "
        "the compiled program being measured; record at the dispatch "
        "seam instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        ledger_names = self._ledger_imports(ctx.tree)
        reported: Set[Tuple[int, int]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is None:
                continue
            hit = self._record_call(ctx, node, ledger_names)
            if hit and (node.lineno, node.col_offset) not in reported:
                reported.add((node.lineno, node.col_offset))
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{hit} inside a traced scope records at trace time "
                    "(once per COMPILE, not per dispatch) — the program "
                    "ledger is host-side only; record at the dispatch "
                    "seam around the jitted call",
                )

    # -- import surface ---------------------------------------------------

    @staticmethod
    def _ledger_imports(tree: ast.Module) -> Set[str]:
        """Local names bound from obs/ledger modules: both
        ``from ...obs.ledger import get_ledger`` targets and
        ``import ...obs.ledger as l`` aliases."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if _is_ledger_module(node.module or ""):
                    for alias in node.names:
                        if alias.name != "*":
                            names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_ledger_module(alias.name):
                        names.add(alias.asname or alias.name.split(".")[0])
        return names

    # -- call classification ----------------------------------------------

    def _record_call(
        self, ctx: ModuleContext, node: ast.Call, ledger_names: Set[str]
    ) -> Optional[str]:
        """A human-readable description when this call records to the
        ledger (directly or one same-module hop away); else None."""
        direct = self._direct_record(node, ledger_names)
        if direct:
            return direct
        # One call hop: a traced scope calling a same-module helper that
        # records (rule 12/15/18/19's reachability idiom).
        if isinstance(node.func, ast.Name):
            for definition in ctx._defs_by_name.get(node.func.id, ()):
                for inner in ast.walk(definition):
                    if isinstance(inner, ast.Call):
                        hit = self._direct_record(inner, ledger_names)
                        if hit:
                            return f"{node.func.id}() reaches {hit}"
        return None

    def _direct_record(
        self, node: ast.Call, ledger_names: Set[str]
    ) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            if (
                func.id in _RECORD_FUNCTIONS
                or (
                    func.id in ledger_names
                    and func.id != "get_ledger"
                    and func.id in _RECORD_FUNCTIONS | _RECORD_METHODS
                )
            ):
                return f"{func.id}(...)"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        if func.attr not in _RECORD_METHODS and func.attr not in (
            _RECORD_FUNCTIONS
        ):
            return None
        if self._ledger_like(func.value, ledger_names):
            rname = dotted_name(func.value)
            if rname is None and isinstance(func.value, ast.Call):
                inner = dotted_name(func.value.func)
                rname = f"{inner}()" if inner else "<ledger>()"
            return f"{rname or '<ledger>'}.{func.attr}(...)"
        return None

    def _ledger_like(self, expr: ast.AST, ledger_names: Set[str]) -> bool:
        """Does this receiver expression denote the program ledger?"""
        if isinstance(expr, ast.Call):
            fname = dotted_name(expr.func) or ""
            if fname:
                parts = fname.split(".")
                # get_ledger() / obs.get_ledger() / l.get_ledger()
                if parts[-1] == "get_ledger" or parts[0] in ledger_names:
                    return True
            return False
        rname = dotted_name(expr)
        if rname is None:
            return False
        parts = rname.split(".")
        return (
            any("ledger" in p.lower() for p in parts)
            or parts[0] in ledger_names
        )
