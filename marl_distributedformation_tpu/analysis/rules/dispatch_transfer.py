"""device-put-in-dispatch-loop: params re-placed per request.

``jax.device_put`` has exactly two sanctioned homes in a serving stack:
engine/registry construction and the reload coordinator's commit — the
once-per-SWAP placement events. A ``device_put`` inside a dispatch loop
(the ``while``-loop shape every serve/poll worker in this repo has) is
the per-request spelling of the same call: a full host->device weight
upload on EVERY iteration, which on a tunneled TPU is a full RTT per
request and silently caps throughput at the PCIe/link rate — the
serving twin of the per-iteration host-sync hazards rules 4 and 12
police on the training side. The fix is always the same: hoist the
placement to the swap/commit seam (``ModelRegistry.refresh``,
``FleetReloadCoordinator._load_and_commit``,
``ShardedPolicyEngine.shard_params``) and let dispatches reuse
device-resident buffers.

Scope, deliberately: ``jax.device_put``/``device_put`` calls inside a
host-side ``while``-loop body — directly, or through a chain of
plain-name helpers (same-module or imported) followed on the shared
call graph to its depth bound. METHOD calls are deliberately not
followed: the sanctioned placement homes in this repo are methods
(``ModelRegistry.refresh``, ``FleetReloadCoordinator._load_and_commit``)
invoked from poll loops at swap frequency, and following
``self.refresh()`` would flag exactly the once-per-swap seam the rule
exists to protect; the runtime ``no_host_transfers`` guard covers
per-request method paths. ``device_get`` is NOT this rule's
business: the trainer's host loop legitimately drains telemetry with
one amortized batched ``device_get`` per log interval, and policing
gets statically would flag exactly that idiom. Loops inside traced
scopes are skipped — a traced ``while`` is rule 2's report.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_TRANSFER_CALLS = frozenset({"jax.device_put", "device_put"})
_NAME_HOPS = frozenset({"local", "import"})


def _transfer_pred(node: ast.Call, fname) -> Optional[str]:
    return fname if fname in _TRANSFER_CALLS else None


class DevicePutInDispatchLoop(Rule):
    name = "device-put-in-dispatch-loop"
    default_severity = "error"
    description = (
        "jax.device_put inside a while-loop dispatch body — a "
        "host->device upload per request; place params once at "
        "swap/commit instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        reported: Set[Tuple[int, int]] = set()
        for loop in self._host_while_loops(ctx):
            for hit in self._scan_body(ctx, loop):
                if hit[:2] not in reported:
                    reported.add(hit[:2])
                    yield hit

    @staticmethod
    def _host_while_loops(ctx: ModuleContext) -> List[ast.While]:
        """Every ``while`` loop outside traced scopes. Nested loops each
        appear; the ``reported`` de-dup keeps one report per call site."""
        return [
            node
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.While)
            and not ctx._has_traced_ancestor(node)
        ]

    def _scan_body(
        self, ctx: ModuleContext, loop: ast.While
    ) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname in _TRANSFER_CALLS:
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{fname}(...) inside a dispatch loop re-uploads its "
                    "tree host->device every iteration — place params "
                    "once at the swap/commit seam and reuse the "
                    "device-resident buffers per dispatch",
                )
            elif isinstance(node.func, ast.Name):
                hit = callgraph.reachable_call(
                    ctx, node, _transfer_pred, first_hops=_NAME_HOPS
                )
                if hit is not None:
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{node.func.id}() is called from a dispatch "
                        f"loop and reaches {hit.matched}(...) — a "
                        "host->device upload every iteration; hoist the "
                        "placement out of the loop to the swap/commit "
                        "seam",
                    )
