"""rpc-in-traced-scope: mesh/network round trips smuggled into
compiled code.

The mesh control plane (``serving/mesh/``) is host-only by the same
contract as the Tracer (rule 15), the MetricsRegistry (rule 18), the
chaos plane (rule 19), and the ledger (rule 20): RPC calls — a
coordinator round trip, a heartbeat, a raw socket/HTTP request — live
at host seams, never inside the program being dispatched. A socket
call inside a jit/vmap/scan traced scope is doubly wrong: it fires
once at TRACE time (a heartbeat per COMPILE, not per step), and a dead
peer turns a compile into an indefinite hang — the tracer wedges on a
network timeout. Rejecting it statically is what lets the mesh tier be
wired into production paths unconditionally: the barrier provably
never enters the compiled path.

Detection surfaces (rule 15/18/19/20's reachability analysis extended
to the mesh RPC API and the stdlib network modules):

- bare calls to names imported from a mesh/rpc module or a network
  module (``socket``, ``http.client``, ``urllib.*``) —
  ``rpc_call(...)`` after ``from ...mesh.rpc import rpc_call``,
  ``urlopen(...)`` after ``from urllib.request import urlopen``;
- any attribute call through a network-module alias —
  ``socket.create_connection(...)``, ``urllib.request.urlopen(...)``:
  every entry point on those modules is host IO;
- method calls whose receiver chain names the mesh control plane —
  ``coordinator.global_reload(...)``, ``self._mesh.heartbeat(...)``,
  ``agent.fleet.prepare_global(...)`` — with the method in the RPC set
  and the receiver looking mesh-like (``mesh``/``coordinator``/``rpc``
  in a part or a root bound from a mesh import), so an unrelated
  ``registry.register(...)`` stays clean;
- one same-module call hop, like rules 12/15/18/19/20: a traced scope
  calling a local helper whose body does RPC is the same hazard
  wearing a function name.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Control-plane entry points on mesh handles (coordinator/agent/rpc).
_RPC_METHODS = frozenset({
    "rpc_call",
    "global_reload",
    "reload_pinned",
    "heartbeat",
    "register",
    "deregister",
    "prepare_global",
    "commit_prepared",
    "abort_prepared",
})
# Module-level callables that are an RPC/socket round trip by name.
_BARE_CALLS = frozenset({"rpc_call"})
# Module-path fragments that mark an import as the mesh RPC surface.
_MESH_MODULE_PARTS = frozenset({"mesh", "rpc"})
# Stdlib network modules: EVERY call through them is host IO.
_NET_MODULE_PARTS = frozenset({"socket", "urllib", "requests"})
_NET_MODULES = frozenset({"http.client", "http"})
# Receiver-chain fragments that make a method call look mesh-like.
_RECEIVER_PARTS = ("mesh", "coordinator", "rpc")


def _is_mesh_module(module: str) -> bool:
    return any(p in _MESH_MODULE_PARTS for p in module.split("."))


def _is_net_module(module: str) -> bool:
    return module in _NET_MODULES or any(
        p in _NET_MODULE_PARTS for p in module.split(".")
    )


class RpcInTracedScope(Rule):
    name = "rpc-in-traced-scope"
    default_severity = "error"
    description = (
        "mesh RPC / socket call reachable inside a jit/scan/vmap traced "
        "scope — the round trip fires once per COMPILE (not per step) "
        "and a dead peer wedges the tracer on a network timeout; keep "
        "coordinator/socket calls at the host dispatch seam"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        mesh_names, net_aliases = self._rpc_imports(ctx.tree)
        reported: Set[Tuple[int, int]] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_traced_scope(node) is None:
                continue
            hit = self._rpc_call(ctx, node, mesh_names, net_aliases)
            if hit and (node.lineno, node.col_offset) not in reported:
                reported.add((node.lineno, node.col_offset))
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{hit} inside a traced scope does a network round "
                    "trip at trace time (once per COMPILE, not per "
                    "step) and can wedge the tracer on a dead peer — "
                    "the mesh control plane is host-side only; make "
                    "the call at the dispatch seam around the jitted "
                    "call",
                )

    # -- import surface ---------------------------------------------------

    @staticmethod
    def _rpc_imports(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
        """``(mesh_names, net_aliases)``: local names bound from mesh
        RPC modules (callables AND module aliases), and module aliases
        of the stdlib network modules (any attribute call through one
        is host IO)."""
        mesh_names: Set[str] = set()
        net_aliases: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                module = node.module or ""
                if _is_mesh_module(module):
                    for alias in node.names:
                        if alias.name != "*":
                            mesh_names.add(alias.asname or alias.name)
                elif _is_net_module(module):
                    for alias in node.names:
                        if alias.name != "*":
                            # from urllib.request import urlopen —
                            # the bound name IS a network entry point.
                            mesh_names.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if _is_mesh_module(alias.name):
                        mesh_names.add(
                            alias.asname or alias.name.split(".")[0]
                        )
                    elif _is_net_module(alias.name):
                        net_aliases.add(
                            alias.asname or alias.name.split(".")[0]
                        )
        return mesh_names, net_aliases

    # -- call classification ----------------------------------------------

    def _rpc_call(
        self,
        ctx: ModuleContext,
        node: ast.Call,
        mesh_names: Set[str],
        net_aliases: Set[str],
    ) -> Optional[str]:
        direct = self._direct_rpc(node, mesh_names, net_aliases)
        if direct:
            return direct
        # One call hop: a traced scope calling a same-module helper
        # whose body does RPC (the rule 12/15/18/19/20 idiom).
        if isinstance(node.func, ast.Name):
            for definition in ctx._defs_by_name.get(node.func.id, ()):
                for inner in ast.walk(definition):
                    if isinstance(inner, ast.Call):
                        hit = self._direct_rpc(
                            inner, mesh_names, net_aliases
                        )
                        if hit:
                            return f"{node.func.id}() reaches {hit}"
        return None

    def _direct_rpc(
        self,
        node: ast.Call,
        mesh_names: Set[str],
        net_aliases: Set[str],
    ) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in _BARE_CALLS or func.id in mesh_names:
                return f"{func.id}(...)"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        rname = dotted_name(func.value)
        root = rname.split(".")[0] if rname else None
        # Any call through a network-module alias: socket.X(...),
        # urllib.request.urlopen(...).
        if root is not None and root in net_aliases:
            return f"{rname}.{func.attr}(...)"
        # mesh_module.rpc_call(...) via a module alias.
        if func.attr in _BARE_CALLS:
            if root is not None and root in mesh_names:
                return f"{rname}.{func.attr}(...)"
        if func.attr not in _RPC_METHODS:
            return None
        if self._mesh_like(func.value, mesh_names):
            if rname is None and isinstance(func.value, ast.Call):
                inner = dotted_name(func.value.func)
                rname = f"{inner}()" if inner else "<mesh>()"
            return f"{rname or '<mesh>'}.{func.attr}(...)"
        return None

    @staticmethod
    def _mesh_like(expr: ast.AST, mesh_names: Set[str]) -> bool:
        """Does this receiver denote the mesh control plane? Chains
        must look mesh-like (``mesh``/``coordinator``/``rpc`` in a
        part, or a root bound from a mesh import) before the
        method-name check applies — ``registry.register(...)`` on an
        unrelated object stays clean."""
        if isinstance(expr, ast.Call):
            fname = dotted_name(expr.func) or ""
            if fname:
                parts = [p.lower() for p in fname.split(".")]
                if parts[0] in mesh_names or any(
                    frag in p for p in parts for frag in _RECEIVER_PARTS
                ):
                    return True
            return False
        rname = dotted_name(expr)
        if rname is None:
            return False
        parts = [p.lower() for p in rname.split(".")]
        return parts[0] in mesh_names or any(
            frag in p for p in parts for frag in _RECEIVER_PARTS
        )
