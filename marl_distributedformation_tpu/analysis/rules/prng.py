"""prng-key-reuse: the same PRNG key consumed by two ``jax.random`` calls.

JAX keys are splittable, not advancing: feeding one key to two sampling
calls yields *correlated* (often identical) draws — in PPO that silently
couples action noise across rollout steps, which trains but converges to
the wrong policy. The rule tracks, per function scope and in execution
order, names passed as the key argument to consuming ``jax.random``
functions; a second consumption without an intervening rebind is
flagged. ``fold_in`` (designed for repeated use with varying data) and
key constructors are exempt; uses on disjoint ``if``/``else`` branches
are merged, and a consumption inside a loop body whose key is never
rebound in the body is flagged (every iteration reuses it).

Scope note: detection is alias-based (the spelled name), so it is
per-scope and conservative — keys smuggled through containers or
attributes are invisible. That is the usual lint trade-off: the rule
catches the way the bug is actually written.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    FunctionLike,
    ModuleContext,
    Rule,
    dotted_name,
)

# jax.random functions that CONSUME their key argument (reuse after any of
# these is the bug). fold_in / PRNGKey / key / clone / key_data are not
# consumers.
_CONSUMING = frozenset(
    {
        "split", "uniform", "normal", "bernoulli", "categorical", "gumbel",
        "choice", "permutation", "shuffle", "randint", "truncated_normal",
        "laplace", "exponential", "beta", "gamma", "poisson", "dirichlet",
        "multivariate_normal", "cauchy", "rademacher", "maxwell", "weibull_min",
        "double_sided_maxwell", "orthogonal", "t", "loggamma", "binomial",
        "bits", "ball", "logistic", "pareto", "rayleigh", "triangular",
        "wald", "geometric", "generalized_normal",
    }
)


def _random_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to the ``jax.random`` module (``from jax import
    random``, ``import jax.random as jr`` …)."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "random":
                    aliases.add(a.asname or "random")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    aliases.add(a.asname)
    return aliases


class _ScopeState:
    """Names whose key has been consumed and not yet rebound, mapped to
    the consuming call node (for the report)."""

    def __init__(self) -> None:
        self.armed: Dict[str, ast.Call] = {}

    def copy(self) -> "_ScopeState":
        s = _ScopeState()
        s.armed = dict(self.armed)
        return s


class PrngKeyReuse(Rule):
    name = "prng-key-reuse"
    default_severity = "error"
    description = (
        "a PRNG key passed to two consuming jax.random calls — draws "
        "become correlated; split the key"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        self._aliases = _random_aliases(ctx.tree) | {"jax.random"}
        scopes: List[ast.AST] = [ctx.tree] + [
            n for n in ast.walk(ctx.tree) if isinstance(n, FunctionLike)
        ]
        for scope in scopes:
            self._violations = []
            self._seen: Set[Tuple[int, int]] = set()
            state = _ScopeState()
            # Lambda bodies are a single expression, not a statement list
            # — and they are where scan/while_loop step functions (the
            # natural home of per-step keys) live.
            body = scope.body if isinstance(scope.body, list) else [scope.body]
            for stmt in body:
                self._visit(stmt, state)
            yield from self._violations

    # -- ordered walk ----------------------------------------------------

    def _key_name(self, call: ast.Call) -> Optional[str]:
        fname = dotted_name(call.func) or ""
        head, _, fn = fname.rpartition(".")
        if fn not in _CONSUMING or head not in self._aliases:
            return None
        key_arg: Optional[ast.AST] = call.args[0] if call.args else None
        for kw in call.keywords:
            if kw.arg == "key":
                key_arg = kw.value
        return key_arg.id if isinstance(key_arg, ast.Name) else None

    def _consume(self, call: ast.Call, state: _ScopeState) -> None:
        name = self._key_name(call)
        if name is None:
            return
        prior = state.armed.get(name)
        pos = (call.lineno, call.col_offset)
        if prior is not None and pos not in self._seen:
            self._seen.add(pos)
            self._violations.append(
                (
                    *pos,
                    f"key {name!r} already consumed by the jax.random call "
                    f"on line {prior.lineno} — split it instead of reusing",
                )
            )
        state.armed[name] = call

    def _bind(self, target: ast.AST, state: _ScopeState) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                state.armed.pop(node.id, None)

    def _visit(self, node: ast.AST, state: _ScopeState) -> None:
        if isinstance(node, FunctionLike):
            return  # separate scope (closures run at their own cadence)
        if isinstance(node, ast.Assign):
            self._visit(node.value, state)
            for t in node.targets:
                self._bind(t, state)
            return
        if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if node.value is not None:
                self._visit(node.value, state)
            self._bind(node.target, state)
            return
        if isinstance(node, ast.NamedExpr):
            self._visit(node.value, state)
            self._bind(node.target, state)
            return
        if isinstance(node, ast.If):
            self._visit(node.test, state)
            a = state.copy()
            for s in node.body:
                self._visit(s, a)
            b = state.copy()
            for s in node.orelse:
                self._visit(s, b)
            state.armed = {**a.armed, **b.armed}
            return
        if isinstance(node, (ast.For, ast.While)):
            if isinstance(node, ast.For):
                self._visit(node.iter, state)
                self._bind(node.target, state)
            else:
                self._visit(node.test, state)
            # Two symbolic iterations: the second starts from the first's
            # end state, so a key consumed in the body and not rebound
            # before its next consumption flags exactly like straight-line
            # reuse. Violations dedupe by position, so intra-body reuses
            # (already reported on pass one) are not double-counted.
            body_state = state.copy()
            for s in node.body:
                self._visit(s, body_state)
            for s in node.body:
                self._visit(s, body_state)
            state.armed = body_state.armed
            for s in node.orelse:
                self._visit(s, state)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, state)
        if isinstance(node, ast.Call):
            self._consume(node, state)
