"""host-sync-in-jit: device->host synchronization inside traced code.

``.item()`` / ``.tolist()`` / ``float()`` / ``int()`` / ``bool()`` /
``np.asarray()`` / ``jax.device_get()`` on a traced value force a
round-trip to the host: under ``jit`` they raise a concretization error;
in the eager fragments around a hot loop they serialize every dispatch
behind a transfer (the failure mode Podracer's anakin architecture
exists to avoid). The runtime complement is
``analysis.guards.no_host_transfers``, which catches the spellings the
AST cannot see.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_SYNC_BUILTINS = frozenset({"float", "int", "bool", "complex"})
_SYNC_METHODS = frozenset({"item", "tolist", "__array__"})
# The numpy spellings this rule owns; numpy-in-jit imports this set to
# stay out of the way (one defect must yield one report).
NUMPY_SYNC_SPELLINGS = frozenset(
    {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
)
_SYNC_CALLS = NUMPY_SYNC_SPELLINGS | frozenset(
    {"jax.device_get", "device_get"}
)


class HostSyncInJit(Rule):
    name = "host-sync-in-jit"
    default_severity = "error"
    description = (
        "device->host sync (.item()/float()/np.asarray()/device_get) "
        "inside a jitted function"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for root in ctx.traced_roots:
            taint = ctx.taint_for(root)
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._spelling(ctx, node, taint)
                if hit:
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{hit} forces a device->host sync inside a jitted "
                        "function (concretization error under jit; a "
                        "serializing transfer in eager hot loops)",
                    )

    @staticmethod
    def _spelling(ctx: ModuleContext, node: ast.Call, taint) -> str:
        fname = dotted_name(node.func)
        if fname in _SYNC_CALLS and any(
            ctx.expr_tainted(a, taint) for a in node.args
        ):
            return f"{fname}(...)"
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in _SYNC_BUILTINS
            and node.args
            and any(ctx.expr_tainted(a, taint) for a in node.args)
        ):
            return f"{node.func.id}(...)"
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SYNC_METHODS
            and ctx.expr_tainted(node.func.value, taint)
        ):
            return f".{node.func.attr}()"
        return ""
