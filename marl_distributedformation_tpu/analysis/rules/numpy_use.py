"""numpy-in-jit: host numpy applied to traced values inside traced code.

``np.*`` on static Python values inside a jitted function is fine (it
folds into a trace-time constant — the idiomatic way to precompute
tables). ``np.*`` on a *traced* value is a silent catastrophe: it forces
the tracer to concretize, which either raises TracerArrayConversionError
or — worse, via implicit __array__ on committed arrays in eager helpers —
synchronizes device to host every step. Flag numpy calls whose arguments
touch tainted names; the pure host-sync spellings (``np.asarray`` /
``np.array``) are owned by the host-sync-in-jit rule.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)
from marl_distributedformation_tpu.analysis.rules.host_sync import (
    NUMPY_SYNC_SPELLINGS,
)


class NumpyInJit(Rule):
    name = "numpy-in-jit"
    default_severity = "error"
    description = (
        "host numpy called on a traced value inside a jitted function — "
        "concretizes the tracer (error) or silently syncs the device; "
        "use jax.numpy"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for root in ctx.traced_roots:
            taint = ctx.taint_for(root)
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if not fname or not fname.split(".", 1)[0] in ("np", "numpy"):
                    continue
                if fname in ("np", "numpy") or fname in NUMPY_SYNC_SPELLINGS:
                    continue
                args = [*node.args, *(k.value for k in node.keywords)]
                if any(ctx.expr_tainted(a, taint) for a in args):
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{fname}(...) applied to a traced value inside a "
                        "jitted function — use the jax.numpy equivalent",
                    )
