"""scan-carry-sharding-drift: a ``lax.scan`` carry leaf whose sharding
constraint in the body differs from the init's.

The fused-scan trainers donate the whole training state into a scan
whose carry must alias the input buffers (``donate_argnums``). A carry
leaf pinned to one sharding at the scan boundary
(``with_sharding_constraint(x, P('dp'))`` on the init) but to a
*different* spec inside the body forces XLA to materialize a resharded
copy every iteration — the donation silently stops aliasing (memory
doubles) or, across dispatches, the drifted output sharding retraces
the jitted program. The fix is one line: make the body's constraint
agree with the producing value's (or drop one of the two and let
propagation decide consistently).

Detection is positional and deliberately conservative: for each
``lax.scan(body, init, ...)`` whose body resolves in the same module,
the rule pairs the init expression's leaves with the body's returned
carry leaves (tuple/list displays element-by-element; a lone leaf as
itself) and compares the sharding specs it can SEE — a leaf that is a
direct ``with_sharding_constraint(...)`` call, or a name assigned from
one in the enclosing scope. Both sides known and textually different →
violation. Unannotated sides stay silent (the producer's sharding is
whatever propagation gives both sides consistently).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

_SCAN_NAMES = frozenset({"jax.lax.scan", "lax.scan"})
_WSC_NAMES = frozenset(
    {
        "jax.lax.with_sharding_constraint",
        "lax.with_sharding_constraint",
        "with_sharding_constraint",
    }
)

Path = Tuple[int, ...]


def _wsc_spec(node: ast.AST) -> Optional[str]:
    """The normalized spec text of a direct with_sharding_constraint
    call, else None."""
    if (
        isinstance(node, ast.Call)
        and dotted_name(node.func) in _WSC_NAMES
        and len(node.args) >= 2
    ):
        return ast.unparse(node.args[1])
    return None


_FN_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _scoped_walk(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk ``scope`` WITHOUT descending into nested functions — a scan
    body that rebinds the init's variable name must not be mistaken for
    the init's own binding (its assignment is a different scope), and a
    module-level fallback must not pick up sibling functions' names."""
    stack = [scope]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, _FN_NODES):
                stack.append(child)


def _assigned_specs(scope: ast.AST) -> Dict[str, Optional[str]]:
    """Name -> spec for simple assignments ``x = with_sharding_constraint
    (..., spec)`` directly in ``scope`` (nested function bodies are other
    scopes and are skipped). The LAST assignment (source order) wins —
    the idiomatic spelling computes first, constrains last (``h = f(x);
    h = with_sharding_constraint(h, P(...))``); a name whose final
    binding is unconstrained maps to None."""
    last: Dict[str, Tuple[int, Optional[str]]] = {}
    for node in _scoped_walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        spec = _wsc_spec(node.value)
        for target in node.targets:
            if not isinstance(target, ast.Name):
                continue
            seen = last.get(target.id)
            if seen is None or node.lineno >= seen[0]:
                last[target.id] = (node.lineno, spec)
    return {name: spec for name, (_, spec) in last.items()}


def _leaf_specs(
    expr: ast.AST,
    names: Dict[str, Optional[str]],
    path: Path = (),
) -> Iterator[Tuple[Path, str, ast.AST]]:
    """(position-path, spec, node) for every leaf of a tuple/list display
    whose sharding constraint is syntactically visible."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        for i, elt in enumerate(expr.elts):
            yield from _leaf_specs(elt, names, (*path, i))
        return
    spec = _wsc_spec(expr)
    if spec is None and isinstance(expr, ast.Name):
        spec = names.get(expr.id)
    if spec is not None:
        yield path, spec, expr


class ScanCarryShardingDrift(Rule):
    name = "scan-carry-sharding-drift"
    default_severity = "error"
    description = (
        "lax.scan carry leaf whose with_sharding_constraint in the body "
        "differs from the init's — under donation XLA reshards a copy "
        "every iteration instead of aliasing the buffer (or retraces on "
        "the drifted output sharding); make the two specs agree"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in _SCAN_NAMES or not node.args:
                continue
            init = node.args[1] if len(node.args) >= 2 else next(
                (kw.value for kw in node.keywords if kw.arg == "init"),
                None,
            )
            if init is None:
                continue
            # The init's bindings live in the function that CALLS scan
            # (traced or not), never in the scan body or a sibling —
            # nearest function ancestor, module as the fallback.
            scope = next(
                (
                    a
                    for a in ctx._ancestors(node)
                    if isinstance(a, _FN_NODES)
                ),
                ctx.tree,
            )
            init_names = _assigned_specs(scope)
            init_specs = {
                p: (spec, leaf)
                for p, spec, leaf in _leaf_specs(init, init_names)
            }
            if not init_specs:
                continue
            for body in ctx._resolve_callable(node.args[0]):
                body_names = _assigned_specs(body)
                # scan bodies return (carry, ys); collect every returned
                # carry expression (a lambda's is its body expression).
                returned = []
                if isinstance(body, ast.Lambda):
                    returned.append(body.body)
                else:
                    returned.extend(
                        ret.value
                        for ret in ast.walk(body)
                        if isinstance(ret, ast.Return)
                        and ret.value is not None
                    )
                carries = [
                    value.elts[0]
                    for value in returned
                    if isinstance(value, ast.Tuple) and len(value.elts) == 2
                ]
                for carry in carries:
                    for p, spec, leaf in _leaf_specs(carry, body_names):
                        known = init_specs.get(p)
                        if known is None or known[0] == spec:
                            continue
                        yield (
                            leaf.lineno,
                            leaf.col_offset,
                            f"scan carry leaf at position {list(p) or '()'}"
                            f" is constrained to {spec} in the body but "
                            f"its init is constrained to {known[0]} — a "
                            "donated carry with drifting sharding "
                            "annotations reshards a copy per iteration "
                            "(or retraces); make the specs agree",
                        )
