"""callback-in-hot-loop: host callbacks inside a compiled loop body.

``io_callback`` / ``pure_callback`` / ``jax.debug.print`` /
``jax.debug.callback`` inside the body of ``lax.scan`` / ``while_loop``
/ ``fori_loop`` / ``lax.map`` executes a device->host round trip EVERY
iteration of the compiled loop — under a fused training scan that is one
tunnel RTT per rollout, which is precisely the overhead whole-loop
fusion exists to remove (train/trainer.py drains telemetry as stacked
scan outputs in ONE batched ``device_get`` per chunk instead). Outside a
loop body the same callbacks cost one transfer per dispatch and are
legitimate debugging tools, so this rule fires only where a compiled
loop multiplies them. Reachability runs on the shared call graph
(``analysis/callgraph.py``): a loop body calling into a chain of
same-module helpers or methods that performs the callback is the same
hazard wearing function names, followed to the engine's depth bound.
Chains that ENTER through an import are rule 14's report — the two
rules split on the first hop so a finding has exactly one owner.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# First-hop kinds this rule owns; import-entered chains are rule 14's.
_LOCAL_HOPS = frozenset({"local", "method"})


def _callback_pred(node: ast.Call, fname) -> Optional[str]:
    return fname if fname in _CALLBACK_CALLS else None

# Compiled-loop entry points -> positions of the body callables among the
# positional args (the loop subset of linter.TRACING_ENTRY_ARGS: vmap/jit
# run their target once per dispatch, a loop body runs per iteration).
LOOP_ENTRY_ARGS: Dict[str, Tuple[int, ...]] = {
    "jax.lax.scan": (0,),
    "lax.scan": (0,),
    "jax.lax.map": (0,),
    "lax.map": (0,),
    "jax.lax.while_loop": (0, 1),
    "lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "lax.fori_loop": (2,),
}

_CALLBACK_CALLS = frozenset(
    {
        "jax.experimental.io_callback",
        "io_callback",
        "jax.pure_callback",
        "pure_callback",
        "jax.debug.print",
        "debug.print",
        "jax.debug.callback",
        "debug.callback",
        "jax.experimental.host_callback.call",
        "host_callback.call",
        "hcb.call",
    }
)


class CallbackInHotLoop(Rule):
    name = "callback-in-hot-loop"
    default_severity = "error"
    description = (
        "io_callback/pure_callback/jax.debug.print inside a compiled "
        "loop body — a host round trip every scanned iteration"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        reported: Set[Tuple[int, int]] = set()
        for body in self._loop_bodies(ctx):
            for hit in self._scan_body(ctx, body):
                if hit[:2] not in reported:
                    reported.add(hit[:2])
                    yield hit

    @staticmethod
    def _loop_bodies(ctx: ModuleContext) -> List[ast.AST]:
        bodies: List[ast.AST] = []
        seen: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            positions = LOOP_ENTRY_ARGS.get(dotted_name(node.func) or "")
            if positions is None:
                continue
            for pos in positions:
                if pos < len(node.args):
                    for body in ctx._resolve_callable(node.args[pos]):
                        if id(body) not in seen:
                            seen.add(id(body))
                            bodies.append(body)
        return bodies

    def _scan_body(
        self, ctx: ModuleContext, body: ast.AST
    ) -> Iterator[Tuple[int, int, str]]:
        for node in ast.walk(body):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname in _CALLBACK_CALLS:
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{fname}(...) inside a compiled loop body runs a "
                    "host callback every scanned iteration — stack the "
                    "values into the scan output and drain them once per "
                    "chunk instead",
                )
            else:
                hit = callgraph.reachable_call(
                    ctx, node, _callback_pred, first_hops=_LOCAL_HOPS
                )
                if hit is not None:
                    called = dotted_name(node.func) or "<callable>"
                    yield (
                        node.lineno,
                        node.col_offset,
                        f"{called}() is called from a compiled "
                        f"loop body and reaches {hit.matched}(...) — a "
                        "host callback every scanned iteration; hoist it "
                        "out of the loop or stack values into the scan "
                        "output",
                    )
