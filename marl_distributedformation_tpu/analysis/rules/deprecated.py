"""deprecated-api: version-drifting JAX spellings, allow/deny table.

The concrete motivating case: ``jax.shard_map`` exists only on new JAX
and ``jax.experimental.shard_map`` only on old — spelling either one
directly makes the package version-bound (this exact drift broke 3
tier-1 tests across 5 call sites before ``jax_compat.shard_map``
centralized it). The table also covers the removed xmap-era APIs and the
pjit axis-resources spellings. The shim module itself carries an inline
``# graftlint: disable=deprecated-api`` — the one place a drifting
spelling is allowed to live.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from marl_distributedformation_tpu.analysis.linter import (
    ModuleContext,
    Rule,
    dotted_name,
)

# Dotted-name prefixes -> guidance. Matched against attribute chains and
# import statements; the longest (most specific) match wins.
DENYLIST = {
    "jax.shard_map": (
        "exists only on jax >= 0.6 — route through "
        "marl_distributedformation_tpu.jax_compat.shard_map"
    ),
    "jax.experimental.shard_map": (
        "removed on new jax — route through "
        "marl_distributedformation_tpu.jax_compat.shard_map"
    ),
    "jax.experimental.maps": "xmap-era API, removed from jax",
    "jax.experimental.pjit": (
        "use jax.jit with in_shardings/out_shardings"
    ),
    "jax.experimental.global_device_array": "removed; use jax.Array",
    "jax.tree_map": "removed in jax 0.6; use jax.tree_util.tree_map",
    "jax.tree_multimap": "removed; use jax.tree_util.tree_map",
}

_DEPRECATED_KWARGS = frozenset({"in_axis_resources", "out_axis_resources"})


def _match(name: str) -> Tuple[str, str]:
    best = ""
    for key in DENYLIST:
        if (name == key or name.startswith(key + ".")) and len(key) > len(best):
            best = key
    return (best, DENYLIST[best]) if best else ("", "")


class DeprecatedApi(Rule):
    name = "deprecated-api"
    default_severity = "error"
    description = (
        "version-drifting / removed JAX API spelling — see the "
        "allow/deny table in analysis/rules/deprecated.py"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        reported = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name is None:
                    continue
                key, why = _match(name)
                # Attribute chains nest (jax.experimental.shard_map is a
                # child of jax.experimental.shard_map.shard_map); the
                # whole chain shares one source position, so position
                # dedup reports it once.
                pos = (node.lineno, node.col_offset)
                if key and pos not in reported:
                    reported.add(pos)
                    yield (*pos, f"{key}: {why}")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    key, why = _match(alias.name)
                    if key:
                        yield (node.lineno, node.col_offset, f"{key}: {why}")
            elif isinstance(node, ast.ImportFrom):
                module = node.module or ""
                for alias in node.names:
                    full = f"{module}.{alias.name}" if module else alias.name
                    key, why = _match(full)
                    if key:
                        yield (
                            node.lineno, node.col_offset, f"{key}: {why}",
                        )
                        break  # one report per import statement
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in _DEPRECATED_KWARGS:
                        yield (
                            node.lineno,
                            node.col_offset,
                            f"{kw.arg}= is the removed pjit axis-resources "
                            "spelling; use in_shardings/out_shardings",
                        )
