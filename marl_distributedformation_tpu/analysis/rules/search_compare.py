"""traced-python-comparison-in-search: fitness branching under trace.

The evolutionary-search foot-gun: a search loop written as (or fused
into) a traced program — a ``lax.while_loop`` / ``fori_loop`` / ``scan``
body, or a Python generation loop inside a jitted function — selects
candidates by COMPARING traced fitness/severity values, and the natural
spelling is a little helper::

    def better(best, cand):
        if cand > best:      # ConcretizationTypeError under trace
            return cand
        return best

Rule 2 (``traced-python-control-flow``) catches a comparison branch
written DIRECTLY in the traced body, but the helper above lives at
module level: it is not itself traced, so rule 2 never walks it — the
error only surfaces at trace time, one call hop away from the loop that
caused it. This rule extends detection that one hop (the rules 12/14/16
reachability precedent): a plain-name call inside a traced search-loop
body is followed into its same-module definition, and a Python
``if``/``while`` there whose test compares the helper's (presumed
traced) parameters is reported at the CALL site. The fix is the same as
rule 2's: ``jnp.where`` / ``lax.cond`` keep the selection inside the
compiled program.

Scope, deliberately: loop bodies only — a helper called from straight-
line traced code is still a latent bug, but the search-loop shape is
where evolutionary code actually puts selection, and bounding the scope
keeps the false-positive surface small (helpers comparing static config
are already filtered by the taint engine's static-parameter rules).
Host-side search loops (this repo's ``AdversarySearch``) drain fitness
to numpy before comparing and stay clean. Reachability runs on the
shared call graph (``analysis/callgraph.py``): the branching helper may
sit a chain of helpers away — same-module, method, or imported — up to
the engine's depth bound. Helpers that are themselves traced scopes are
pruned (a traced helper's branch is rule 2's report, not a second one
here).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from marl_distributedformation_tpu.analysis import callgraph
from marl_distributedformation_tpu.analysis.linter import (
    TRACING_ENTRY_ARGS,
    ModuleContext,
    Rule,
    dotted_name,
)


def _branching_pred(
    func: "callgraph.FuncInfo", owner_ctx: ModuleContext
) -> Optional[str]:
    """Does this function Python-branch on a comparison of its own
    (presumed traced) parameters? Traced helpers answer no — their
    branches are rule 2's report in their own module."""
    node = func.node
    if isinstance(node, ast.Lambda) or node in owner_ctx.traced_scopes:
        return None
    taint = ModuleContext._param_names(node)
    for inner in ast.walk(node):
        if not isinstance(inner, (ast.If, ast.IfExp, ast.While)):
            continue
        for cmp_node in ast.walk(inner.test):
            if isinstance(cmp_node, ast.Compare) and owner_ctx.expr_tainted(
                cmp_node, taint
            ):
                return f"{func.qualname} (line {inner.lineno})"
    return None

# Tracing entry points whose traced callables are LOOP BODIES — the
# search-loop shapes (cond fns included: a while_loop condition that
# compares through a branching helper concretizes identically).
_LOOP_ENTRIES = frozenset(
    name
    for name in TRACING_ENTRY_ARGS
    if name.rsplit(".", 1)[-1] in {"while_loop", "fori_loop", "scan", "map"}
)


class TracedComparisonInSearch(Rule):
    name = "traced-python-comparison-in-search"
    default_severity = "error"
    description = (
        "a traced search loop body calls a helper that Python-branches "
        "on a comparison of its (traced) arguments — concretizes at "
        "trace time; select with jnp.where / lax.cond instead"
    )

    def check(self, ctx: ModuleContext) -> Iterator[Tuple[int, int, str]]:
        reported: Set[Tuple[int, int]] = set()
        for site in self._search_sites(ctx):
            for node in ast.walk(site):
                if not isinstance(node, ast.Call) or not isinstance(
                    node.func, ast.Name
                ):
                    continue
                hit = callgraph.reachable_function(
                    ctx, node, _branching_pred
                )
                if hit is None:
                    continue
                if (node.lineno, node.col_offset) in reported:
                    continue
                reported.add((node.lineno, node.col_offset))
                yield (
                    node.lineno,
                    node.col_offset,
                    f"{node.func.id}() is called from a traced search "
                    f"loop and reaches a Python branch on a comparison "
                    f"of traced arguments in {hit.matched} — a "
                    "ConcretizationTypeError at trace time; return "
                    "jnp.where(cmp, a, b) or use lax.cond so the "
                    "selection stays in the program",
                )

    def _search_sites(self, ctx: ModuleContext) -> List[ast.AST]:
        """AST subtrees that are traced search-loop bodies: callables
        handed to lax loop entries, plus host ``for``/``while`` loops
        jitted wholesale inside any traced scope."""
        sites: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname in _LOOP_ENTRIES:
                    for pos in TRACING_ENTRY_ARGS[fname]:
                        if pos < len(node.args):
                            sites.extend(
                                ctx._resolve_callable(node.args[pos])
                            )
            elif isinstance(node, (ast.For, ast.While)):
                if ctx._has_traced_ancestor(node):
                    sites.append(node)
        return sites

